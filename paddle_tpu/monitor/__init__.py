"""Runtime telemetry: the framework's metrics registry and exporters.

Reference parity: paddle/fluid/platform/monitor.h — StatRegistry +
STAT_ADD/STAT_SUB macros, the always-on named-stat layer the reference
sprinkles through its executors and collectives — paired here with
paddle_tpu.profiler's RecordEvent trees (profiler.{h,cc} parity). The
profiler answers "where did this step's time go"; this module answers
"what has the process done and how fast, cumulatively" — counters,
gauges, and latency histograms cheap enough to leave on in serving.

Instrumented hot paths (each records into the DEFAULT registry):

- ``static.Executor.run``/``_compile`` — compile count, jit-cache
  hit/miss per feed-signature, step wall time, FLAGS_benchmark syncs;
- ``distributed.spmd.SpmdTrainer.train_step`` — same compile-cache and
  step-latency families under ``site="trainer"``;
- ``Tensor._to_host()`` — every device->host sync (the PR-1 chokepoint);
- ``inference.ServingEngine`` — request lifecycle: queue wait, TTFT,
  inter-token latency, batch occupancy, prefill/decode/speculative step
  split, prefix-cache hit rate, speculative accept rate (plus
  per-request ``Request.stats()`` / engine ``ServingEngine.stats()``);
- ``distributed.collective.*`` — call count + payload bytes by HLO
  family (analysis/collectives.py naming);
- ``framework.io.save/load`` — checkpoint count, wall time, bytes;
- ``framework.aot`` — the persistent AOT compile cache: the shared
  ``compile_cache_total`` family carries a ``source=memory|disk|fresh``
  label, plus serialize/deserialize latency + entry-size histograms and
  store/evict counters (docs/AOT.md).

Three exporters, one schema (docs/OBSERVABILITY.md):
``snapshot()`` JSON dict -> ``to_json`` / ``to_prometheus`` text /
``log_event``+``log_snapshot`` JSONL (``FLAGS_monitor_log_path``).

``FLAGS_monitor=0`` (or ``disable()``) turns every recording call into a
single boolean check — the tier-1 overhead gate in
tests/test_perf_budgets.py holds that bar.
"""
import contextlib
import time

from .. import flags as _flags
from .exporters import (flatten, log_event, log_snapshot, parse_prometheus,
                        to_json, to_prometheus)
from .registry import (DEFAULT_BUCKETS, LABEL_CARDINALITY_CAP,
                       OVERFLOW_LABEL, Counter, Gauge, Histogram,
                       StatRegistry)

__all__ = [
    "StatRegistry", "Counter", "Gauge", "Histogram", "DEFAULT_BUCKETS",
    "LABEL_CARDINALITY_CAP", "OVERFLOW_LABEL",
    "default_registry", "counter", "gauge", "histogram", "snapshot",
    "reset", "enable", "disable", "is_enabled", "timed",
    "to_json", "to_prometheus", "parse_prometheus", "flatten",
    "log_event", "log_snapshot", "record_collective", "tensor_nbytes",
    "STAT_ADD", "STAT_SUB", "STAT_RESET",
    "blackbox_on", "bb_note", "bb_note_span", "bb_beacon", "bb_progress",
    "bb_register_provider", "bb_dump", "blackbox_lazy",
]


def __getattr__(name):   # PEP 562
    # the numerics telescope, the flight recorder, the perf ledger, AND
    # the goodput accountant load lazily: a plain (flags-unset) process
    # must never import any — tests/test_numerics_gate.py,
    # tests/test_perfledger_gate.py, tests/test_goodput_gate.py, and
    # the ISSUE 12 import-graph contract (analysis/import_graph.py
    # LAZY_MODULES) pin it. Deliberately NOT in __all__: a star-import
    # resolves every listed name, which would defeat the laziness
    if name in ("numerics", "blackbox", "perfledger", "goodput"):
        import importlib

        return importlib.import_module("." + name, __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

_flags.define_flag("monitor", True,
                   "runtime telemetry registry on/off; off turns every "
                   "instrumented call site into one boolean check")
_flags.define_flag("monitor_log_path", "",
                   "JSONL structured-event log path for "
                   "monitor.log_event/log_snapshot (empty = disabled); "
                   "bench.py phase heartbeats land here")

_DEFAULT = StatRegistry(enabled=bool(_flags.get_flag("monitor", True)))


def default_registry():
    return _DEFAULT


def counter(name, help="", labelnames=()):
    return _DEFAULT.counter(name, help=help, labelnames=labelnames)


def gauge(name, help="", labelnames=()):
    return _DEFAULT.gauge(name, help=help, labelnames=labelnames)


def histogram(name, help="", labelnames=(), buckets=None):
    return _DEFAULT.histogram(name, help=help, labelnames=labelnames,
                              buckets=buckets)


def snapshot():
    return _DEFAULT.snapshot()


def reset():
    _DEFAULT.reset()


def enable():
    _DEFAULT.enable()


def disable():
    _DEFAULT.disable()


def is_enabled():
    return _DEFAULT.is_enabled()


@contextlib.contextmanager
def timed(hist_or_bound):
    """Observe a with-block's wall time in MILLISECONDS on a histogram
    (or a .labels(...) handle). Skips the clock reads when disabled."""
    if not _DEFAULT.is_enabled():
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        hist_or_bound.observe((time.perf_counter() - t0) * 1e3)


# ---- monitor.h macro parity --------------------------------------------------
# STAT_ADD/STAT_SUB mutate one named int stat; monitor.h stats can go both
# ways, so they map onto gauges in the default registry.

def STAT_ADD(name, value=1):
    gauge(name).inc(value)


def STAT_SUB(name, value=1):
    gauge(name).dec(value)


def STAT_RESET(name):
    gauge(name).set(0)


# ---- shared instrumentation helpers ------------------------------------------

_COLL_CALLS = None
_COLL_BYTES = None
_COLL_SAVED = None


def tensor_nbytes(x):
    """Payload bytes of a Tensor/jax array/np array — works on tracers too
    (aval carries shape+dtype); returns 0 when undeterminable."""
    try:
        data = getattr(x, "_data", x)
        shape = getattr(data, "shape", None)
        dtype = getattr(data, "dtype", None)
        if shape is None or dtype is None:
            return 0
        n = 1
        for s in shape:
            n *= int(s)
        return n * dtype.itemsize
    except Exception:
        return 0


def record_collective(kind, nbytes=0, saved_bytes=0):
    """Count one collective API call by HLO family (`kind` follows
    analysis/collectives.py naming: all-reduce, all-gather,
    reduce-scatter, all-to-all, collective-permute). Calls made inside a
    jit trace count once per TRACE (host-side accounting), mirroring the
    static collective-count pass rather than a device profiler.

    `nbytes` is what actually crosses the interconnect: for uncompressed
    ops that IS the logical payload (the PR 2 meaning, unchanged); for
    wire-compressed ops (the quantized reduce family,
    docs/DISTRIBUTED.md) the caller passes the encoded wire bytes here
    and the fp32 bytes the encoding displaced as `saved_bytes`, which
    land in the lazy ``collective_bytes_saved_total{op}`` counter —
    ``bytes + saved`` recovers the dequantized logical payload."""
    global _COLL_CALLS, _COLL_BYTES, _COLL_SAVED
    # flight-recorder byte tag BEFORE the monitor-enabled early-out: the
    # two recorders are independent flags, and the last collectives
    # before a wedge are prime evidence even with metrics off
    bb_note("collective", op=kind, bytes=int(nbytes))
    if not _DEFAULT.is_enabled():
        return
    if _COLL_CALLS is None:
        _COLL_CALLS = counter(
            "collective_calls_total",
            "collective API calls by HLO family (trace-time accounting; "
            "exact per-execution counts live in the perf-budget HLO gate)",
            labelnames=("op",))
        _COLL_BYTES = counter(
            "collective_bytes_total",
            "bytes a collective API call puts on the wire, by HLO family "
            "(== the logical payload except for wire-compressed ops, "
            "whose fp32 displacement is collective_bytes_saved_total)",
            labelnames=("op",))
    _COLL_CALLS.labels(op=kind).inc()
    if nbytes:
        _COLL_BYTES.labels(op=kind).inc(nbytes)
    if saved_bytes:
        if _COLL_SAVED is None:
            _COLL_SAVED = counter(
                "collective_bytes_saved_total",
                "fp32 bytes a wire-compressed collective (quantized "
                "reduce family) did NOT move: logical payload minus the "
                "int8+scales wire encoding counted in "
                "collective_bytes_total (lazy — no series until a "
                "compressed op runs)",
                labelnames=("op",))
        _COLL_SAVED.labels(op=kind).inc(saved_bytes)


# ---- flight-recorder indirection (ISSUE 12) ----------------------------------
# monitor/blackbox.py is MANIFEST-LAZY (analysis/import_graph.py): a plain
# process never imports it. Its on/off latch and the provider registry
# live HERE so every instrumented hot path stays one boolean check
# without pulling the recorder in; blackbox adopts these objects as its
# own at import (the latch list is shared, not copied).

import threading as _threading  # noqa: E402  (for the pre-import lock)

_BB_ON = [False]          # flipped by blackbox.enable()/disable()
_BB_PROVIDERS = []        # (kind, weakref(obj), fn) — shared with blackbox
_BB_PROVIDER_CAP = 64     # one cap, adopted by blackbox.register_provider
_BB_PROVIDERS_LOCK = _threading.Lock()   # the ONE lock for the provider
#                          list — blackbox.register_provider adopts it
#                          too, so pre- and post-import registrations
#                          can never interleave under different locks
_BB_NULL_CM = contextlib.nullcontext()


def blackbox_on():
    """Is the flight recorder enabled? One list read — safe to call on
    any hot path without importing the recorder."""
    return _BB_ON[0]


def _bb():
    from . import blackbox

    return blackbox


def bb_note(kind, **fields):
    """Forward one flight-recorder ring event iff the recorder is on
    (disabled: one boolean check, no blackbox import)."""
    if _BB_ON[0]:
        _bb().note(kind, **fields)


def bb_note_span(sp):
    if _BB_ON[0]:
        _bb().note_span(sp)


def bb_beacon(site):
    if _BB_ON[0]:
        _bb().beacon(site)


def bb_progress(site):
    """`with bb_progress(site):` — a blackbox progress window when the
    recorder is on, a no-op context otherwise."""
    if not _BB_ON[0]:
        return _BB_NULL_CM
    return _bb().progress(site)


def bb_dump(reason, **kw):
    """Write a dump bundle (imports the recorder; a disabled recorder
    writes nothing and returns None). Keywords pass through to
    blackbox.dump (site=, extra=, dir_=)."""
    if not _BB_ON[0]:
        return None
    return _bb().dump(reason, **kw)


def bb_register_provider(kind, obj, fn):
    """Register a live-state dump provider WITHOUT importing the
    recorder: entries land in the shared list blackbox adopts at import
    (same weakref shape + cap as blackbox.register_provider)."""
    import sys as _sys
    import weakref

    # delegate only to a FULLY-initialized module: mid-import (another
    # thread is executing blackbox.py right now) the half-built module
    # already sits in sys.modules without register_provider — fall
    # through to the shared list, which blackbox mutates under the SAME
    # _BB_PROVIDERS_LOCK, so nothing is lost either way
    mod = _sys.modules.get(__name__ + ".blackbox")
    reg = getattr(mod, "register_provider", None)
    if reg is not None:
        reg(kind, obj, fn)
        return
    with _BB_PROVIDERS_LOCK:
        _BB_PROVIDERS[:] = [(k, r, f) for (k, r, f) in _BB_PROVIDERS
                            if r() is not None][-(_BB_PROVIDER_CAP - 1):]
        _BB_PROVIDERS.append((str(kind), weakref.ref(obj), fn))


class _BlackboxLazy:
    """The recorder API surface the instrumented hot paths consume,
    import-free: ``from ..monitor import blackbox_lazy as _blackbox``
    keeps every call site spelled exactly as before ISSUE 12 while the
    heavy module (ring, sentinel, bundle writer) loads only once the
    recorder is actually enabled."""

    is_enabled = staticmethod(blackbox_on)
    note = staticmethod(bb_note)
    note_span = staticmethod(bb_note_span)
    beacon = staticmethod(bb_beacon)
    progress = staticmethod(bb_progress)
    register_provider = staticmethod(bb_register_provider)
    dump = staticmethod(bb_dump)


blackbox_lazy = _BlackboxLazy()


# env-armed opt-in (FLAGS_blackbox=1 python serve.py): load the recorder
# eagerly so its sync_from_flag() enables it at import, exactly as when
# it rode the package import. The flag itself is defined in flags.py so
# this check never touches the lazy module.
if _flags.get_flag("blackbox", False):
    from . import blackbox  # noqa: E402,F401  # lint: allow(lazy-import)

# same opt-in for the perf ledger (FLAGS_perf_ledger=1 python bench.py):
# create the process ledger eagerly so its blackbox dump provider and
# env fingerprint exist before the first recording site runs.
if _flags.get_flag("perf_ledger", False):
    from . import perfledger  # noqa: E402,F401  # lint: allow(lazy-import)

    perfledger.get_ledger()

# same opt-in for the goodput accountant (FLAGS_goodput=1 python ...):
# import the module eagerly so hook sites' construction-consumed handles
# resolve without re-paying the import inside a step loop. No run is
# opened here — trainers/supervisors/tools ensure_run() when they start.
if _flags.get_flag("goodput", False):
    from . import goodput  # noqa: E402,F401  # lint: allow(lazy-import)
