"""Perf ledger: persistent cross-run performance telemetry + regression sentinel.

Every performance number the framework produces used to die with the
process: step times lived in in-memory gauges, BENCH rounds landed as
opaque JSON legs, and the plan cost model priced compute against nominal
peak-flops tables no measurement had ever corrected. This module is the
durable record (ISSUE 17):

**Ledger** — when ``FLAGS_perf_ledger`` is armed, trainers, serving
engines, stage graphs, and every banked bench leg append one JSON row
per observation window to ``FLAGS_perf_ledger_path``: an append-only
JSONL file (single write+flush+fsync per row; readers tolerate a torn
tail, the ``bench.py --banked`` discipline). Each row carries the site,
the batch signature, the mesh fingerprint, an environment fingerprint
(jax/jaxlib/python/machine/cpu_count + device kind when available), and
a flat metrics dict — step wall ms, t_exec-windowed MFU, executable
flops/HBM bytes from the cost registry, per-op collective wire+saved
bytes, dispatch fraction, compile-cache sources, and p50/p90/p99
latency digests from the registry histograms' ``summary()``.

**Regression sentinel** — per-(site, metric) EMA mean/variance baselines
(the :class:`NumericsMonitor` pattern) watch every observation; a value
more than ``FLAGS_perf_ledger_sigma`` deviations on the *bad* side of
its baseline (direction per :data:`HIGH_IS_BAD`/:data:`LOW_IS_BAD`)
fires ``perf_regression_total{site,metric}``, notes the flight-recorder
ring, and latches per episode so a sustained regression counts once.
The ledger registers itself as a blackbox dump provider, so crash/stall
bundles carry the last perf snapshot and ledger tail.

**Calibration** — :mod:`paddle_tpu.analysis.calibrate` least-squares
fits effective peak flops / HBM bandwidth / per-collective-op wire
bandwidth from these rows, producing the constants table
``CostModel(constants=)`` consumes (``tools/plan_search.py
--calibrated``). ``tools/perf_report.py`` is the CLI over all of it.

Inert-by-default with the PR 9/10/15 discipline: ``FLAGS_perf_ledger``
is defined in flags.py so every hook site is one boolean check, the
disarmed path never imports this module (manifest-lazy;
analysis/import_graph.py), no ``perf_*`` metric series exists until
armed, and — the flag being deliberately NON-structural — armed and
disarmed runs share executables and train byte-identically
(tests/test_perfledger_gate.py pins all of it).
"""
import collections
import json
import math
import os
import platform
import threading
import time

from .. import flags as _flags
from . import blackbox_lazy as _blackbox  # import-free recorder facade

__all__ = [
    "SCHEMA_VERSION", "CORE_FINGERPRINT", "HIGH_IS_BAD", "LOW_IS_BAD",
    "is_armed", "env_fingerprint", "fingerprint_key", "append_row",
    "load_rows", "tail", "Ema", "PerfLedger", "get_ledger",
    "reset_ledger", "baselines", "check_value", "record_trainer",
    "record_engine", "record_stage_runner", "record_leg",
]

#: ledger row schema version; readers skip rows of any other version
SCHEMA_VERSION = 1

#: fingerprint fields that KEY baseline/calibration grouping — the
#: software env. Device fields (platform/device_kind/device_count) ride
#: along in rows for humans and the calibrator but do not gate matching:
#: a re-run under a different virtual-device count should still find its
#: software baselines on CPU, and real-hardware rows are split by the
#: device fields the calibrator reports.
CORE_FINGERPRINT = ("jax", "jaxlib", "python", "machine", "cpu_count")

#: metrics where LARGER observations are regressions (wall times)
HIGH_IS_BAD = ("step_ms", "exec_ms", "sync_ms", "compile_ms",
               "queue_wait_ms", "ttft_ms", "inter_token_ms", "tick_ms",
               "run_ms", "fetch_ms", "kv_bytes_per_session")

#: metrics where SMALLER observations are regressions (throughputs).
#: ``dispatch_fraction`` is deliberately in NEITHER list: the budget
#: tests treat a HIGH fraction (host-bound step) as the failure, so it
#: is recorded in rows but never sentinel-fired.
LOW_IS_BAD = ("mfu", "tokens_per_s", "prefix_hit_rate", "accept_rate",
              "goodput")   # run/goodput rows (monitor/goodput.py): a
#                            goodput fraction BELOW its banked baseline
#                            is the regression (ISSUE 20)


def is_armed():
    """The one master switch (FLAGS_perf_ledger). Hook sites read the
    flag directly so the disarmed path never imports this module; this
    helper is for code that already did."""
    return bool(_flags.get_flag("perf_ledger", False))


# -- environment fingerprint ---------------------------------------------------

def env_fingerprint():
    """The env a measurement is only comparable within: jax/jaxlib/
    python versions, machine, cpu count — plus the device platform/kind/
    count when a backend is already up (never forces one up: a ledger
    row must not initialize jax)."""
    fp = {
        "python": platform.python_version(),
        "machine": platform.machine(),
        "cpu_count": os.cpu_count() or 1,
    }
    try:
        import sys

        jax = sys.modules.get("jax")
        if jax is not None:
            fp["jax"] = jax.__version__
            import jaxlib

            fp["jaxlib"] = jaxlib.__version__
            devs = jax.devices()
            fp["platform"] = devs[0].platform
            fp["device_kind"] = devs[0].device_kind
            fp["device_count"] = len(devs)
    except Exception:
        pass
    return fp


def fingerprint_key(fp):
    """Stable string key over :data:`CORE_FINGERPRINT` — what baseline
    and calibration grouping match on."""
    return "|".join(f"{k}={fp.get(k)}" for k in CORE_FINGERPRINT)


# -- JSONL persistence (the --banked discipline) -------------------------------

def _jsonable(v):
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if v is None or isinstance(v, (bool, str, int)):
        return v
    if isinstance(v, float):
        return v if math.isfinite(v) else None
    try:
        f = float(v)  # numpy scalars
        return f if math.isfinite(f) else None
    except Exception:
        return str(v)


def append_row(path, row):
    """Append ONE row as one line: a single buffered write, flushed and
    fsynced, so a concurrent reader (or a crash) sees whole lines plus
    at most one torn tail — which :func:`load_rows` skips."""
    line = json.dumps(_jsonable(row), sort_keys=True) + "\n"
    with open(path, "a", encoding="utf-8") as f:
        f.write(line)
        f.flush()
        os.fsync(f.fileno())


def load_rows(path):
    """Every well-formed current-schema row in the ledger; a torn tail
    (partial last line from a killed writer), blank lines, and rows of a
    foreign schema version are skipped, never raised on."""
    rows = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue  # torn tail / partial write
                if isinstance(row, dict) \
                        and row.get("v") == SCHEMA_VERSION:
                    rows.append(row)
    except OSError:
        return []
    return rows


def tail(path, n=20):
    """The last ``n`` rows — crash-bundle and --explain fodder."""
    return load_rows(path)[-n:] if path else []


# -- metric families (lazy: no perf_* series until armed) ----------------------

_M = None


def _metrics():
    global _M
    if _M is None:
        from .. import monitor as _monitor

        _M = {
            "rows": _monitor.counter(
                "perf_ledger_rows_total",
                "perf-ledger rows appended, by site (lazy — no series "
                "until FLAGS_perf_ledger arms a recording site)",
                labelnames=("site",)),
            "regression": _monitor.counter(
                "perf_regression_total",
                "perf-regression sentinel fires: an observation "
                "FLAGS_perf_ledger_sigma EMA deviations on the bad side "
                "of its per-(site,metric) baseline (one fire per "
                "episode, not per step)",
                labelnames=("site", "metric")),
        }
    return _M


class Ema:
    """EMA mean/variance baseline for one (site, metric) series — the
    numerics-telescope estimator, shared with tools/perf_report.py."""

    __slots__ = ("mean", "var", "n")

    def __init__(self):
        self.mean = 0.0
        self.var = 0.0
        self.n = 0

    def update(self, x, alpha=0.25):
        if self.n == 0:
            self.mean = x
            self.var = 0.0
        else:
            diff = x - self.mean
            incr = alpha * diff
            self.mean += incr
            self.var = (1.0 - alpha) * (self.var + diff * incr)
        self.n += 1

    def std(self):
        return math.sqrt(max(self.var, 0.0))


def baselines(rows, env=None):
    """Fold ledger rows into per-(site, metric) :class:`Ema` baselines,
    keeping only rows whose :func:`fingerprint_key` matches ``env``
    (default: this process) and only sentinel-directed metrics — a
    cross-machine row must never tighten this machine's floors."""
    key = fingerprint_key(env if env is not None else env_fingerprint())
    out = {}
    for row in rows:
        if fingerprint_key(row.get("env") or {}) != key:
            continue
        if (row.get("metrics") or {}).get("cold"):
            continue  # compile-resolving window: not the steady state
        site = row.get("site")
        for name, v in (row.get("metrics") or {}).items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            v = float(v)
            if not math.isfinite(v):
                continue
            if name not in HIGH_IS_BAD and name not in LOW_IS_BAD:
                continue
            ema = out.get((site, name))
            if ema is None:
                ema = out[(site, name)] = Ema()
            ema.update(v)
    return out


def check_value(ema, metric, value, sigma):
    """One fresh measurement against one baseline: (regressed?, excess
    in floored sigmas). The deviation floor (5% of the mean) keeps a
    near-constant series from declaring noise a regression."""
    sign = 1.0 if metric in HIGH_IS_BAD else -1.0
    floor = max(ema.std(), 0.05 * abs(ema.mean), 1e-9)
    excess = sign * (float(value) - ema.mean) / floor
    return excess > float(sigma), excess


# -- the ledger ----------------------------------------------------------------

class PerfLedger:
    """One per process (see :func:`get_ledger`): the JSONL appender, the
    per-(site, metric) sentinel, and the blackbox dump provider. Flag
    knobs (path/sigma/warmup/interval) are consumed at construction."""

    def __init__(self, path=None):
        self.path = str(path if path is not None
                        else _flags.get_flag("perf_ledger_path", ""))
        self.sigma = float(_flags.get_flag("perf_ledger_sigma", 4.0))
        self.warmup = max(2, int(_flags.get_flag("perf_ledger_warmup", 5)))
        self.interval = max(1, int(_flags.get_flag("perf_ledger_interval",
                                                   1)))
        self.env = env_fingerprint()
        self.rows_written = 0
        self.regressions = collections.deque(maxlen=64)
        self._ema = {}        # (site, metric) -> Ema
        self._counts = {}     # site -> observations so far
        self._episode = set()  # (site, metric) latched while out of band
        self._last_row = {}   # site -> last row (bundle fodder)
        self._lock = threading.Lock()
        _blackbox.register_provider("perf_ledger", self,
                                    lambda led: led.snapshot())

    # -- sentinel ----------------------------------------------------------
    def _check(self, site, metric, value):
        """Baseline one observation; returns the fired regression record
        or None. Out-of-band values do NOT update the EMA — a sustained
        regression must not drag its own baseline up to meet it."""
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            return None
        value = float(value)
        if not math.isfinite(value):
            return None
        if metric in HIGH_IS_BAD:
            sign = 1.0
        elif metric in LOW_IS_BAD:
            sign = -1.0
        else:
            return None  # recorded in rows, never fired on
        key = (site, metric)
        ema = self._ema.get(key)
        if ema is None:
            ema = self._ema[key] = Ema()
        if ema.n >= self.warmup:
            floor = max(ema.std(), 0.05 * abs(ema.mean), 1e-9)
            if sign * (value - ema.mean) > self.sigma * floor:
                if key in self._episode:
                    return None
                self._episode.add(key)
                return self._fire(site, metric, value, ema)
            self._episode.discard(key)
        ema.update(value)
        return None

    def _fire(self, site, metric, value, ema):
        rec = {"site": site, "metric": metric, "value": float(value),
               "mean": float(ema.mean), "std": float(ema.std())}
        self.regressions.append(rec)
        from .. import monitor as _monitor

        if _monitor.is_enabled():
            _metrics()["regression"].labels(site=site, metric=metric).inc()
        _blackbox.note("perf_regression", site=site, metric=metric,
                       value=rec["value"], mean=rec["mean"],
                       std=rec["std"])
        return rec

    # -- recording ---------------------------------------------------------
    def observe(self, site, metrics):
        """Sentinel-only pass: baseline every numeric metric, fire on
        the out-of-band ones, append NO row and advance NO interval
        counter (per-round feeds whose rows come from a richer stats()
        fold — the serving engine's step hook)."""
        site = str(site)
        fired = []
        with self._lock:
            for name in sorted(metrics):
                rec = self._check(site, name, metrics[name])
                if rec is not None:
                    fired.append(rec)
        return fired

    def on_step(self, site, metrics, sig=None, mesh=None, force=False,
                check=True):
        """Ingest one observation window for ``site``: every numeric
        metric goes through the sentinel; every
        ``FLAGS_perf_ledger_interval``-th call per site (or ``force``)
        appends a ledger row. ``check=False`` records the row but skips
        the sentinel — for out-of-distribution windows (a step that
        resolved a compile) that must not poison the steady-state
        baseline. Returns the list of fired regressions."""
        site = str(site)
        fired = []
        with self._lock:
            if check:
                for name in sorted(metrics):
                    rec = self._check(site, name, metrics[name])
                    if rec is not None:
                        fired.append(rec)
            n = self._counts.get(site, 0) + 1
            self._counts[site] = n
            if force or n % self.interval == 0:
                self._append(site, metrics, sig=sig, mesh=mesh)
        return fired

    def _append(self, site, metrics, sig=None, mesh=None):
        row = {"v": SCHEMA_VERSION, "ts": time.time(), "site": site,
               "sig": None if sig is None else str(sig),
               "mesh": None if mesh is None else str(mesh),
               "env": self.env, "metrics": _jsonable(metrics)}
        self._last_row[site] = row
        if self.path:
            try:
                append_row(self.path, row)
            except OSError:
                # a full disk / revoked path drops telemetry, never the
                # step it was observing
                return row
        self.rows_written += 1
        from .. import monitor as _monitor

        if _monitor.is_enabled():
            _metrics()["rows"].labels(site=site).inc()
        return row

    # -- surfacing ---------------------------------------------------------
    def snapshot(self):
        """JSON-able perf snapshot: the blackbox dump-provider table, so
        crash/stall bundles carry the last rows + recent regressions +
        the on-disk tail."""
        return {
            "path": self.path or None,
            "env": self.env,
            "rows_written": self.rows_written,
            "sites": dict(sorted(self._counts.items())),
            "regressions": list(self.regressions)[-10:],
            "last_rows": {s: r for s, r in sorted(self._last_row.items())},
            "tail": tail(self.path, 5),
        }


_LEDGER = None
_LEDGER_LOCK = threading.Lock()


def get_ledger():
    """The process ledger (created on first armed use — flag knobs are
    read then). All sites share it: one file, one env fingerprint, one
    sentinel namespace."""
    global _LEDGER
    with _LEDGER_LOCK:
        if _LEDGER is None:
            _LEDGER = PerfLedger()
        return _LEDGER


def reset_ledger():
    """Drop the process ledger so the next :func:`get_ledger` re-reads
    the flag knobs (tests re-pointing FLAGS_perf_ledger_path)."""
    global _LEDGER
    with _LEDGER_LOCK:
        _LEDGER = None


# -- site recorders ------------------------------------------------------------
# Each folds one subsystem's stats() into a flat metrics dict and hands
# it to the ledger. They live HERE (not on the subsystems) so the hook
# in each subsystem stays one boolean + one call.

def _registry_collectives():
    """Per-op collective tallies from the default registry: wire bytes,
    displaced (saved) bytes, call counts — cumulative process totals."""
    from .. import monitor as _monitor

    out = {}
    reg = _monitor.default_registry()
    for fam, key in (("collective_bytes_total", "bytes"),
                     ("collective_bytes_saved_total", "saved"),
                     ("collective_calls_total", "calls")):
        met = reg.get(fam)
        if met is None:
            continue
        for s in met.series():
            op = s.labels.get("op", "")
            out.setdefault(op, {})[key] = s.value
    return out


def _registry_compile():
    """compile_cache_total by source (memory|disk|fresh) + the compile
    wall-ms digest when those families exist."""
    from .. import monitor as _monitor

    reg = _monitor.default_registry()
    out = {}
    met = reg.get("compile_cache_total")
    if met is not None:
        srcs = {}
        for s in met.series():
            lab = ",".join(f"{k}={v}" for k, v in sorted(s.labels.items()))
            srcs[lab or "total"] = s.value
        out["cache"] = srcs
    for fam in ("compile_ms", "aot_deserialize_ms"):
        met = reg.get(fam)
        if met is not None and met.kind == "histogram":
            try:
                out[fam] = _agg_summary(met)
            except Exception:
                pass
    return out


def _agg_summary(met):
    """summary() aggregated over every series of a histogram family."""
    total = None
    for s in met.series():
        if total is None:
            total = {"count": 0, "sum": 0.0}
        d = s.summary()
        total["count"] += d.pop("count")
        total["sum"] += d.pop("sum")
        for k, v in d.items():
            total[k] = max(total.get(k, 0.0), v)  # worst-case quantile
    return total


def _hist_summary(name, **labels):
    from .. import monitor as _monitor

    met = _monitor.default_registry().get(name)
    if met is None or met.kind != "histogram":
        return None
    try:
        bound = met.labels(**labels) if labels else met
        d = bound.summary()
    except (TypeError, ValueError):
        return None
    return d if d.get("count") else None


def record_trainer(trainer, ledger=None, site="trainer"):
    """One ledger row + sentinel pass from ``SpmdTrainer.stats()``:
    averaged step/sync wall ms, t_exec-windowed MFU, cost-registry
    flops/HBM bytes, dispatch fraction, per-op collective bytes, the
    compile-cache split, and the step-latency digest."""
    led = ledger if ledger is not None else get_ledger()
    st = trainer.stats()
    br = st.get("breakdown") or {}
    steps = max(1, int(st.get("steps") or 0))
    tot = float(st.get("step_ms_total") or 0.0)
    m = {
        "steps": st.get("steps"),
        "step_ms": st.get("step_ms_avg"),
        "sync_ms": float(br.get("sync_ms_total") or 0.0) / steps,
        "mfu": st.get("mfu"),
        "flops_per_step": st.get("flops_per_step"),
        "peak_flops": st.get("peak_flops"),
    }
    hbm = st.get("hbm") or {}
    for k, v in hbm.items():
        m["hbm_" + str(k)] = v
    if tot > 0:
        m["dispatch_fraction"] = \
            float(br.get("dispatch_ms_total") or 0.0) / tot
    coll = _registry_collectives()
    if coll:
        m["collectives"] = coll
    comp = _registry_compile()
    if comp:
        m["compile"] = comp
    dig = _hist_summary("step_latency_ms", site=site)
    if dig:
        m["step_latency"] = dig
    mesh = None
    try:
        from ..framework import aot as _aot

        mesh = _aot.mesh_fingerprint(trainer.mesh)
    except Exception:
        pass
    return led.on_step(site, m, sig=st.get("batch_sig"), mesh=mesh,
                       force=True)


def record_engine(engine, ledger=None, site="serving"):
    """One ledger row + sentinel pass from
    ``ServingEngine.stats()["breakdown"]`` (per-kind step wall ms +
    executed device flops) + the request-lifecycle latency digests
    (queue wait, TTFT, inter-token: the engine's own accumulators plus
    the registry histograms' p50/p90/p99 summary())."""
    led = ledger if ledger is not None else get_ledger()
    st = engine.stats()
    br = st.get("breakdown") or {}
    m = {
        "tokens_generated": st.get("tokens_generated"),
        "batch_occupancy_avg": st.get("batch_occupancy_avg"),
        "wall_ms_total": br.get("wall_ms_total"),
    }
    hit_rate = (st.get("prefix_cache") or {}).get("hit_rate")
    if hit_rate is not None:
        m["prefix_hit_rate"] = hit_rate
    accept = (st.get("speculative") or {}).get("accept_rate")
    if accept is not None:
        m["accept_rate"] = accept
    for kind, row in (br.get("kinds") or {}).items():
        count = int(row.get("count") or 0)
        if count:
            m[str(kind) + "_step_ms"] = \
                float(row.get("wall_ms") or 0.0) / count
        if row.get("device_flops_total") is not None:
            m[str(kind) + "_flops_total"] = row["device_flops_total"]
    for key in ("queue_wait_ms", "ttft_ms", "inter_token_ms"):
        acc = st.get(key)
        if isinstance(acc, dict) and acc.get("count"):
            m[key] = acc.get("avg_ms", 0.0)
        dig = _hist_summary("serving_" + key)
        if dig:
            m[key[:-3] + "digest"] = dig
    out = led.on_step(site, m, force=True)
    pg = st.get("paging")
    if isinstance(pg, dict):
        # paged engines (FLAGS_paged_kv) append a second row under
        # site/paged_step: pool occupancy + the per-session KV footprint
        # the block tables exist to shrink. kv_bytes_per_session is
        # sentinel-watched HIGH_IS_BAD — a sharing regression (lost
        # prefix dedup, leaked frames) fires perf_regression_total
        # before it becomes an OOM
        mp = {k: v for k, v in pg.items()
              if isinstance(v, (int, float)) and not isinstance(v, bool)}
        ad = pg.get("adapters")
        if isinstance(ad, dict):
            for k, v in ad.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    mp["adapter_" + str(k)] = v
        led.on_step(site + "/paged_step", mp, force=True)
    return out


def record_stage_runner(runner, ledger=None, site="stage"):
    """One ledger row + sentinel pass from a StageGraph /
    MpmdPipelineRunner ``stats()`` dict (tick wall ms, edge transfer
    bytes — whatever the runner reports numerically)."""
    led = ledger if ledger is not None else get_ledger()
    st = runner.stats() if hasattr(runner, "stats") else dict(runner)
    m = {}

    def _flatten(prefix, d):
        for k, v in d.items():
            name = (prefix + "_" + str(k)) if prefix else str(k)
            if isinstance(v, dict):
                _flatten(name, v)
            elif isinstance(v, (int, float)) and not isinstance(v, bool):
                m[name] = v

    _flatten("", st)
    return led.on_step(site, m, force=True)


def record_leg(leg, data, ledger=None):
    """One ledger row per banked bench leg: the leg's numeric fields
    (tokens/s, MFU, wall s, ...) under ``site="bench/<leg>"`` — BENCH
    retries auto-accumulate calibration data."""
    led = ledger if ledger is not None else get_ledger()
    m = {k: v for k, v in dict(data).items()
         if isinstance(v, (int, float)) and not isinstance(v, bool)}
    for k in ("collectives", "hbm"):
        v = dict(data).get(k)
        if isinstance(v, dict):
            m[k] = v
    return led.on_step("bench/" + str(leg), m, force=True)
