"""StatRegistry: named counters, gauges, and bucketed histograms with labels.

Reference parity: paddle/fluid/platform/monitor.h — the StatRegistry
singleton of named StatValue<T> slots mutated through STAT_ADD/STAT_SUB
macros sprinkled over the framework's hot paths. This is the same idea
with three metric kinds instead of one, prometheus-style labels, and an
explicit enabled/disabled switch so instrumentation left compiled into a
hot loop costs one boolean check when monitoring is off.

Contract (tests/test_monitor.py pins all of it):

- get-or-create by name: ``counter("x")`` twice returns the SAME metric;
  re-declaring a name as a different kind (or different labelnames)
  raises — a silent second registry entry would split the stat;
- thread-safe: series creation and every mutation take the metric lock
  (observations are read-modify-write; the GIL alone does not make
  ``+=`` atomic);
- label cardinality is CAPPED per metric (``LABEL_CARDINALITY_CAP``):
  past the cap, new label combinations collapse into one
  ``__overflow__`` series instead of growing without bound (a runaway
  feed-signature label must not OOM the host);
- ``reset()`` zeroes values IN PLACE and drops labeled children but keeps
  every metric object registered — instrumentation call sites cache
  metric handles, so reset must never detach them;
- disabled mode: every mutator returns after one attribute check; nothing
  is recorded, nothing allocates.
"""
import bisect
import threading

__all__ = ["StatRegistry", "Counter", "Gauge", "Histogram",
           "DEFAULT_BUCKETS", "LABEL_CARDINALITY_CAP", "OVERFLOW_LABEL"]

# latency-in-ms oriented (the framework's histograms are all wall-time);
# a metric that wants different resolution passes buckets= explicitly
DEFAULT_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                   100.0, 250.0, 500.0, 1000.0, 2500.0, 10000.0)

LABEL_CARDINALITY_CAP = 64
OVERFLOW_LABEL = "__overflow__"


class _CounterSeries:
    __slots__ = ("labels", "value")
    kind = "counter"

    def __init__(self, labels):
        self.labels = labels
        self.value = 0.0

    def _zero(self):
        self.value = 0.0

    def to_dict(self):
        return {"labels": dict(self.labels), "value": self.value}


class _GaugeSeries(_CounterSeries):
    __slots__ = ()
    kind = "gauge"


class _HistogramSeries:
    __slots__ = ("labels", "buckets", "counts", "sum", "count")
    kind = "histogram"

    def __init__(self, labels, buckets):
        self.labels = labels
        self.buckets = buckets          # ascending upper bounds; +Inf implicit
        self.counts = [0] * (len(buckets) + 1)
        self.sum = 0.0
        self.count = 0

    def _zero(self):
        self.counts = [0] * len(self.counts)
        self.sum = 0.0
        self.count = 0

    def to_dict(self):
        # cumulative bucket counts, prometheus-style: [le, count<=le]
        cum, out = 0, []
        for le, n in zip(self.buckets, self.counts):
            cum += n
            out.append([le, cum])
        out.append(["+Inf", self.count])
        return {"labels": dict(self.labels), "count": self.count,
                "sum": self.sum, "buckets": out}

    def _quantile(self, q):
        """Estimate the q-quantile from the bucket layout: linear
        interpolation inside the winning bucket (prometheus
        histogram_quantile discipline); observations that landed past
        the last finite bound clamp to it — the layout cannot resolve
        further."""
        if self.count <= 0:
            return 0.0
        target = q * self.count
        cum, lo = 0, 0.0
        for le, n in zip(self.buckets, self.counts):
            if n and cum + n >= target:
                frac = (target - cum) / n
                return lo + (le - lo) * min(max(frac, 0.0), 1.0)
            cum += n
            lo = le
        return self.buckets[-1]

    def summary(self, quantiles=(0.5, 0.9, 0.99)):
        """Quantile digest of this series: {"count", "sum", "p50",
        "p90", "p99"} (keys follow the requested quantiles). Estimates,
        not exact order statistics — the raw observations are gone; only
        the bucket layout remains. An empty series digests to zeros."""
        out = {"count": self.count, "sum": self.sum}
        for q in quantiles:
            out["p" + format(q * 100, "g").replace(".", "_")] = \
                self._quantile(q)
        return out


class _Bound:
    """A metric bound to one label combination — the mutation handle the
    instrumentation call sites hold. Mutators re-resolve the series on
    every call (one dict hit) so ``reset()`` can drop children without
    invalidating cached handles."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric, key):
        self._metric = metric
        self._key = key

    # counter / gauge ------------------------------------------------------
    def inc(self, n=1.0):
        m = self._metric
        if not m._registry._enabled:
            return
        if m.kind == "counter" and n < 0:
            raise ValueError(f"counter {m.name!r} cannot decrease")
        with m._lock:
            m._series_for(self._key).value += n

    add = inc

    def dec(self, n=1.0):
        m = self._metric
        if m.kind != "gauge":
            raise TypeError(f"{m.kind} {m.name!r} has no dec()")
        if not m._registry._enabled:
            return
        with m._lock:
            m._series_for(self._key).value -= n

    def set(self, v):
        m = self._metric
        if m.kind != "gauge":
            raise TypeError(f"{m.kind} {m.name!r} has no set()")
        if not m._registry._enabled:
            return
        with m._lock:
            m._series_for(self._key).value = float(v)

    # histogram ------------------------------------------------------------
    def observe(self, v):
        m = self._metric
        if m.kind != "histogram":
            raise TypeError(f"{m.kind} {m.name!r} has no observe()")
        if not m._registry._enabled:
            return
        v = float(v)
        with m._lock:
            s = m._series_for(self._key)
            s.counts[bisect.bisect_left(s.buckets, v)] += 1
            s.sum += v
            s.count += 1

    def summary(self, quantiles=(0.5, 0.9, 0.99)):
        """Quantile digest of the bound series (histogram only); an
        unobserved label combination digests to zeros."""
        m = self._metric
        if m.kind != "histogram":
            raise TypeError(f"{m.kind} {m.name!r} has no summary()")
        with m._lock:
            s = m._peek(self._key)
            if s is None:
                s = m._new_series(self._key)  # zeros; NOT registered
            return s.summary(quantiles)

    # reads (tests / stats()) ----------------------------------------------
    @property
    def value(self):
        s = self._metric._peek(self._key)
        return 0.0 if s is None else s.value

    @property
    def count(self):
        s = self._metric._peek(self._key)
        return 0 if s is None else s.count

    @property
    def sum(self):
        s = self._metric._peek(self._key)
        return 0.0 if s is None else s.sum


class Metric:
    """One named metric: a family of label-keyed series."""

    kind = None
    _series_cls = None

    def __init__(self, registry, name, help="", labelnames=(), buckets=None):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._registry = registry
        self._lock = threading.Lock()
        self._series = {}
        self._buckets = None
        if self.kind == "histogram":
            bks = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS)))
            if not bks:
                raise ValueError(f"histogram {name!r}: empty buckets")
            self._buckets = bks
        self._default = _Bound(self, ()) if not self.labelnames else None

    # series management ----------------------------------------------------
    def _new_series(self, key):
        labels = dict(zip(self.labelnames, key))
        if self.kind == "histogram":
            return _HistogramSeries(labels, self._buckets)
        return self._series_cls(labels)

    def _series_for(self, key):
        """Resolve (creating if needed) under self._lock — callers hold it."""
        s = self._series.get(key)
        if s is None:
            if key != () and len(self._series) >= LABEL_CARDINALITY_CAP:
                key = (OVERFLOW_LABEL,) * len(self.labelnames)
                s = self._series.get(key)
                if s is not None:
                    return s
            s = self._series[key] = self._new_series(key)
        return s

    def _peek(self, key):
        s = self._series.get(key)
        if s is None and key != () \
                and len(self._series) >= LABEL_CARDINALITY_CAP:
            s = self._series.get((OVERFLOW_LABEL,) * len(self.labelnames))
        return s

    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.kind} {self.name!r} takes labels "
                f"{self.labelnames}, got {tuple(sorted(kv))}")
        return _Bound(self, tuple(str(kv[k]) for k in self.labelnames))

    def _require_unlabeled(self):
        if self.labelnames:
            raise ValueError(
                f"{self.kind} {self.name!r} declares labels "
                f"{self.labelnames}; use .labels(...)")
        return self._default

    # unlabeled convenience (delegates to the default series)
    def inc(self, n=1.0):
        self._require_unlabeled().inc(n)

    add = inc

    def dec(self, n=1.0):
        self._require_unlabeled().dec(n)

    def set(self, v):
        self._require_unlabeled().set(v)

    def observe(self, v):
        self._require_unlabeled().observe(v)

    def summary(self, quantiles=(0.5, 0.9, 0.99)):
        return self._require_unlabeled().summary(quantiles)

    @property
    def value(self):
        return self._require_unlabeled().value

    @property
    def count(self):
        return self._require_unlabeled().count

    @property
    def sum(self):
        return self._require_unlabeled().sum

    def series(self):
        with self._lock:
            return [self._series[k] for k in sorted(self._series)]

    def to_dict(self):
        d = {"name": self.name, "type": self.kind, "help": self.help,
             "labelnames": list(self.labelnames),
             "series": [s.to_dict() for s in self.series()]}
        return d

    def _reset(self):
        with self._lock:
            self._series = {k: s for k, s in self._series.items() if k == ()}
            for s in self._series.values():
                s._zero()


class Counter(Metric):
    kind = "counter"
    _series_cls = _CounterSeries


class Gauge(Metric):
    kind = "gauge"
    _series_cls = _GaugeSeries


class Histogram(Metric):
    kind = "histogram"


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class StatRegistry:
    """platform/monitor.h StatRegistry parity: the named-stat singleton
    (module-level ``default_registry()``), get-or-create by name."""

    def __init__(self, enabled=True):
        self._metrics = {}
        self._lock = threading.Lock()
        self._enabled = bool(enabled)

    # enable/disable -------------------------------------------------------
    def enable(self):
        self._enabled = True

    def disable(self):
        self._enabled = False

    def is_enabled(self):
        return self._enabled

    # metric creation ------------------------------------------------------
    def _get_or_create(self, kind, name, help="", labelnames=(),
                       buckets=None):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}, "
                        f"cannot re-register as {kind}")
                if tuple(labelnames) != m.labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered with labels "
                        f"{m.labelnames}, got {tuple(labelnames)}")
                if kind == "histogram":
                    want = tuple(sorted(float(b) for b in
                                        (buckets or DEFAULT_BUCKETS)))
                    if want != m._buckets:
                        raise ValueError(
                            f"histogram {name!r} already registered with "
                            f"buckets {m._buckets}, got {want} — a second "
                            "layout would silently mis-bucket observations")
                return m
            m = _KINDS[kind](self, name, help=help, labelnames=labelnames,
                             buckets=buckets)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()):
        return self._get_or_create("counter", name, help, labelnames)

    def gauge(self, name, help="", labelnames=()):
        return self._get_or_create("gauge", name, help, labelnames)

    def histogram(self, name, help="", labelnames=(), buckets=None):
        return self._get_or_create("histogram", name, help, labelnames,
                                   buckets=buckets)

    def get(self, name):
        return self._metrics.get(name)

    def metrics(self):
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def reset(self):
        """Zero every series in place; labeled children are dropped (their
        call sites re-create them), metric objects stay registered."""
        for m in self.metrics():
            m._reset()

    def snapshot(self):
        """The one schema all three exporters share (docs/OBSERVABILITY.md):
        {"version", "enabled", "metrics": [metric.to_dict()...]}."""
        return {"version": 1, "enabled": self._enabled,
                "metrics": [m.to_dict() for m in self.metrics()]}
