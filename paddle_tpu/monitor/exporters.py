"""Exporters: one snapshot schema, three wire forms.

- ``to_json(snap)`` — the snapshot dict as JSON (machine-readable, the
  form tools/metrics_dump.py prints and bench.py attaches);
- ``to_prometheus(snap)`` — Prometheus text exposition 0.0.4 of the SAME
  snapshot (``parse_prometheus`` inverts it; the round-trip is pinned by
  tests/test_monitor.py);
- JSONL structured event log — ``log_event(kind, **fields)`` appends one
  ``{"ts", "event", ...}`` line to ``FLAGS_monitor_log_path`` (unset =
  disabled). ``log_snapshot()`` writes the whole snapshot as one event,
  so a log tail always carries the latest counters — the wedge-
  attribution channel bench.py's phase heartbeats ride.
"""
import json
import re
import threading
import time

__all__ = ["to_json", "to_prometheus", "parse_prometheus", "flatten",
           "log_event", "log_snapshot"]

_LOG_LOCK = threading.Lock()


def _label_str(labels):
    if not labels:
        return ""
    esc = {k: str(v).replace("\\", r"\\").replace('"', r'\"')
           .replace("\n", r"\n") for k, v in labels.items()}
    return "{" + ",".join(f'{k}="{esc[k]}"' for k in sorted(esc)) + "}"


def _num(v):
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def to_json(snap, indent=None):
    return json.dumps(snap, indent=indent, sort_keys=True)


#: histogram summary() percentile -> prometheus quantile label value
_QUANTILES = (("p50", "0.5"), ("p90", "0.9"), ("p99", "0.99"))


def to_prometheus(snap, summaries=None):
    """Prometheus text exposition of a registry snapshot.

    ``summaries`` (optional) is the ``{'name{k=v,...}': {'p50': ...,
    'p90': ..., 'p99': ...}}`` digest tools/metrics_dump.py computes from
    the registry histograms' ``summary()`` — given, each histogram series
    additionally emits standard ``name{...,quantile="0.5"} v`` samples,
    so the percentile digest survives the text form and
    :func:`parse_prometheus` round-trips it losslessly instead of the
    digest lines being dropped (or, worse, crashing the parser as the
    old human-format ``name{...}: {json}`` lines did). Default (None)
    output is byte-identical to the historical form."""
    lines = []
    for m in snap["metrics"]:
        name = m["name"].replace("-", "_").replace(".", "_")
        if m["help"]:
            lines.append(f"# HELP {name} {m['help']}")
        lines.append(f"# TYPE {name} {m['type']}")
        for s in m["series"]:
            if m["type"] in ("counter", "gauge"):
                lines.append(
                    f"{name}{_label_str(s['labels'])} {_num(s['value'])}")
            else:  # histogram
                for le, cum in s["buckets"]:
                    lb = dict(s["labels"])
                    lb["le"] = le if le == "+Inf" else _num(le)
                    lines.append(f"{name}_bucket{_label_str(lb)} {cum}")
                base = _label_str(s["labels"])
                lines.append(f"{name}_sum{base} {_num(s['sum'])}")
                lines.append(f"{name}_count{base} {s['count']}")
                if summaries:
                    lb0 = s["labels"]
                    key = m["name"] + ("" if not lb0 else "{" + ",".join(
                        f"{k}={lb0[k]}" for k in sorted(lb0)) + "}")
                    summ = summaries.get(key) or {}
                    for pct, q in _QUANTILES:
                        if summ.get(pct) is None:
                            continue
                        lb = dict(lb0)
                        lb["quantile"] = q
                        lines.append(
                            f"{name}{_label_str(lb)} {_num(summ[pct])}")
    return "\n".join(lines) + "\n"


def parse_prometheus(text, skipped=None):
    """Invert to_prometheus: {(sample_name, frozenset(labels)): value}.
    Covers exactly the subset to_prometheus emits — including the
    ``quantile=``-labelled summary samples the ``summaries=`` form adds
    (no exemplars/escapes beyond its own) — the exporter round-trip
    contract, not a general prometheus parser.

    A non-comment line that is not a valid sample (e.g. a human-format
    ``name{...}: {json}`` percentile digest from an older metrics dump)
    is SKIPPED instead of raising; pass a list as ``skipped`` to collect
    ``(line, reason)`` pairs — explicit skip-with-reason rather than a
    silent drop or a ValueError crash mid-parse."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        body, _, val = line.rpartition(" ")
        try:
            value = float("inf") if val == "+Inf" else float(val)
        except ValueError:
            if skipped is not None:
                skipped.append((line, f"sample value {val!r} is not a "
                                      "float — not exposition format"))
            continue
        if "{" in body:
            name, _, rest = body.partition("{")
            rest = rest.rstrip("}")
            labels = {}
            for part in _split_labels(rest):
                k, _, v = part.partition("=")
                if len(v) >= 2 and v[0] == '"' and v[-1] == '"':
                    v = v[1:-1]   # exactly one quote per side: .strip('"')
                    # would also eat a trailing ESCAPED quote's char
                # single-pass unescape: sequential .replace would decode
                # an escaped backslash followed by 'n' as a newline
                labels[k] = re.sub(
                    r"\\(.)",
                    lambda mt: {"n": "\n"}.get(mt.group(1), mt.group(1)), v)
        else:
            name, labels = body, {}
        out[(name, frozenset(labels.items()))] = value
    return out


def _split_labels(s):
    """Split 'a="x",b="y,z"' on commas outside quotes."""
    parts, cur, inq, prev = [], [], False, ""
    for ch in s:
        if ch == '"' and prev != "\\":
            inq = not inq
        if ch == "," and not inq:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
        prev = ch
    if cur:
        parts.append("".join(cur))
    return [p for p in parts if p]


def flatten(snap):
    """Compact one-level view: 'name{k=v,...}' -> value (counters/gauges)
    or {'count', 'sum'} (histograms). What bench.py attaches to its
    metric line — small enough for a log line, still attributable."""
    out = {}
    for m in snap["metrics"]:
        for s in m["series"]:
            lb = s["labels"]
            key = m["name"] + ("" if not lb else
                               "{" + ",".join(f"{k}={lb[k]}"
                                              for k in sorted(lb)) + "}")
            if m["type"] == "histogram":
                out[key] = {"count": s["count"], "sum": round(s["sum"], 3)}
            else:
                out[key] = s["value"]
    return out


def _log_path():
    from .. import flags as _flags

    return _flags.get_flag("monitor_log_path", "") or None


def log_event(event, _path=None, **fields):
    """Append one structured event line to the JSONL log. Returns the
    record, or None when logging is off (no path configured)."""
    path = _path or _log_path()
    if not path:
        return None
    rec = {"ts": round(time.time(), 6), "event": str(event)}
    rec.update(fields)
    line = json.dumps(rec, sort_keys=True)
    with _LOG_LOCK:
        with open(path, "a") as f:
            f.write(line + "\n")
    return rec


def log_snapshot(snap, _path=None, **fields):
    """Write a full registry snapshot as one 'snapshot' event."""
    return log_event("snapshot", _path=_path, snapshot=snap, **fields)
