"""Numerics telescope: fused on-device tensor-health stats + drift detectors.

PRs 2/5/7 made the *system* observable — metrics, spans/MFU, the flight
recorder. The model interior stayed a black box: the PR 4 non-finite
guard fires only after a step is already ruined. This module watches the
numbers themselves, TPU-natively:

**Fused stats** — when ``FLAGS_numerics`` is armed, ``SpmdTrainer._build``
appends :func:`device_stats` to the existing jitted step: ONE fused
on-device aggregation producing, per layer, the gradient L2 norm / rms /
absmax / max, the non-finite element count, the post-update param norm,
the update norm and update/param ratio, and a small abs-gradient
quantile digest (p50/p90/p99 over a deterministic strided subsample so
huge tensors don't pay a full device sort). The stacked result rides the
step's output tuple — device-resident, replicated — and is fetched to
host only every ``FLAGS_numerics_interval`` steps under a
``numerics/fetch`` span (no new per-step host syncs).

**Drift detectors** — :class:`NumericsMonitor` keeps bounded per-series
history rings with EMA mean/variance baselines and runs anomaly rules on
every fetch:

- ``grad_spike``  — a layer's grad norm jumps past
  ``FLAGS_numerics_spike_sigma`` sigmas of its EMA baseline;
- ``dead_layer``  — a layer's gradient is exactly zero for
  ``FLAGS_numerics_dead_steps`` consecutive observations;
- ``update_ratio`` — the update/param ratio leaves the sane band
  (> ``FLAGS_numerics_ratio_max``) AND sits well above the layer's own
  EMA baseline (fresh zero-init params legitimately run O(1) ratios
  through warmup): the step is rewriting the layer;
- ``nonfinite``   — non-finite elements in a layer's gradient (named
  *per layer*, before/alongside the PR 4 whole-step guard);
- ``loss_plateau`` — the loss stops moving across the last
  ``FLAGS_numerics_plateau_window`` fetches.

Each anomaly increments ``numerics_anomaly_total{kind,layer}``, lands in
the PR 7 flight-recorder ring (``numerics_anomaly`` note), and the
monitor registers itself as a blackbox dump provider so a crash bundle
carries the last model-health snapshot.

Everything is inert-by-default with the PR 2–7 discipline: the trainer
gates on ``FLAGS_numerics`` (defined in flags.py so the plain path never
imports this module), the disarmed step is bit-identical, and no
``numerics_*`` metric/span series exists until armed
(tests/test_numerics_gate.py pins all of it). The lockstep A/B
loss-parity harness over these stats lives in
:mod:`paddle_tpu.testing.parity` (docs/OBSERVABILITY.md "Numerics
telescope").
"""
import collections
import math

import numpy as np
import jax.numpy as jnp

from .. import flags as _flags
from . import blackbox_lazy as _blackbox  # import-free recorder facade

__all__ = [
    "STAT_KEYS", "QUANTILES", "DIGEST_CAP", "MIN_BASELINE_POINTS",
    "is_armed", "device_stats", "stat_shardings", "NumericsMonitor",
]

_flags.define_flag(
    "numerics_history", 64,
    "per-series history-ring capacity of the numerics drift detectors "
    "(oldest observations dropped past it)")
_flags.define_flag(
    "numerics_spike_sigma", 6.0,
    "grad-norm spike rule: fire numerics_anomaly_total{kind=grad_spike} "
    "when a layer's grad norm exceeds its EMA baseline by this many "
    "(floored) standard deviations")
_flags.define_flag(
    "numerics_dead_steps", 3,
    "dead-layer rule: fire after this many CONSECUTIVE observations of "
    "an exactly-zero gradient for one layer")
_flags.define_flag(
    "numerics_ratio_max", 0.25,
    "update-ratio band rule: fire when ||update||/||param|| exceeds "
    "this (the step is rewriting the layer, not nudging it)")
_flags.define_flag(
    "numerics_plateau_window", 8,
    "loss-plateau rule: the loss ring length inspected; a full ring "
    "whose spread is below numerics_plateau_eps fires once per episode")
_flags.define_flag(
    "numerics_plateau_eps", 1e-4,
    "loss-plateau rule: relative spread (max-min over the window, "
    "scaled by |mean|) under which the loss counts as flat")

#: keys of the device_stats output dict — the trainer builds the step's
#: out_shardings for the stats leg from this list, so it is part of the
#: compiled program's shape contract
STAT_KEYS = ("grad_norm", "grad_rms", "grad_absmax", "grad_max",
             "nonfinite", "param_norm", "update_norm", "update_ratio",
             "quantiles", "loss")

#: abs-gradient quantile digest points (p50/p90/p99)
QUANTILES = (0.5, 0.9, 0.99)

#: quantile digests over tensors larger than this use a deterministic
#: strided subsample — a full device sort of an embedding-table gradient
#: would dominate the step it is meant to observe
DIGEST_CAP = 4096

#: EMA baselines need this many observations before the spike rule arms
#: (a 2-point "baseline" would fire on ordinary early-training motion)
MIN_BASELINE_POINTS = 3

#: the update-ratio rule skips layers whose param norm is below this —
#: against a ~zero denominator (a fresh zero-init bias) the ratio is
#: meaningless and would fire on every ordinary step
RATIO_PARAM_FLOOR = 1e-2


def is_armed():
    """The one master switch (FLAGS_numerics). The trainer reads the
    flag directly so the disarmed path never imports this module; this
    helper is for code that already did."""
    return bool(_flags.get_flag("numerics", False))


# -- fused on-device aggregation ----------------------------------------------

def _digest_source(flat):
    """Deterministic strided subsample for the quantile digest. Ceil
    division: a floor stride would degenerate to a prefix-only sample
    for sizes just past the cap, silently blinding the digest to the
    tail of row-major tensors."""
    n = flat.shape[0]
    if n <= DIGEST_CAP:
        return flat
    stride = -(-n // DIGEST_CAP)
    return flat[::stride][:DIGEST_CAP]


def device_stats(names, loss, grads, old_params, new_params):
    """The fused per-layer health aggregation, traced INTO the jitted
    train step (everything here is jnp on tracers; XLA fuses it with the
    backward pass it reads from). Returns a dict of stacked float32
    arrays — one row per layer in ``names`` order — matching
    :data:`STAT_KEYS`. Computed on the RAW grads/updates, before the PR 4
    guard's where-select, so a poisoned step still shows WHICH layer
    went non-finite."""
    gn, rms, amax, gmax, nonf, pn, un, ratio, digs = \
        [], [], [], [], [], [], [], [], []
    qs = jnp.asarray(QUANTILES, jnp.float32)
    for name in names:
        g = grads[name].astype(jnp.float32).ravel()
        p_new = new_params[name].astype(jnp.float32).ravel()
        p_old = old_params[name].astype(jnp.float32).ravel()
        size = max(1, g.shape[0] if g.shape else 1)
        sq = jnp.sum(g * g)
        norm = jnp.sqrt(sq)
        gn.append(norm)
        rms.append(jnp.sqrt(sq / size))
        ag = jnp.abs(g)
        amax.append(jnp.max(ag))
        gmax.append(jnp.max(g))
        nonf.append(jnp.sum(~jnp.isfinite(g)).astype(jnp.float32))
        pnorm = jnp.sqrt(jnp.sum(p_new * p_new))
        upd = p_new - p_old
        unorm = jnp.sqrt(jnp.sum(upd * upd))
        pn.append(pnorm)
        un.append(unorm)
        ratio.append(unorm / (pnorm + 1e-12))
        digs.append(jnp.quantile(_digest_source(ag), qs))
    return {
        "grad_norm": jnp.stack(gn),
        "grad_rms": jnp.stack(rms),
        "grad_absmax": jnp.stack(amax),
        "grad_max": jnp.stack(gmax),
        "nonfinite": jnp.stack(nonf),
        "param_norm": jnp.stack(pn),
        "update_norm": jnp.stack(un),
        "update_ratio": jnp.stack(ratio),
        "quantiles": jnp.stack(digs),           # [layers, len(QUANTILES)]
        "loss": jnp.asarray(loss, jnp.float32),
    }


def stat_shardings(replicated):
    """out_shardings leg for the stats dict (everything replicated)."""
    return {k: replicated for k in STAT_KEYS}


# -- metric families (lazy: no numerics_* series until armed) ------------------

_M = None


def _metrics():
    global _M
    if _M is None:
        from .. import monitor as _monitor

        _M = {
            "grad_norm": _monitor.gauge(
                "numerics_grad_norm",
                "per-layer gradient L2 norm at the last numerics fetch",
                labelnames=("layer",)),
            "param_norm": _monitor.gauge(
                "numerics_param_norm",
                "per-layer post-update parameter L2 norm at the last "
                "numerics fetch", labelnames=("layer",)),
            "update_ratio": _monitor.gauge(
                "numerics_update_ratio",
                "per-layer ||update|| / ||param|| at the last numerics "
                "fetch (federated rounds report the cohort-weighted "
                "aggregate under layer='federated/round')",
                labelnames=("layer",)),
            "grad_rms": _monitor.gauge(
                "numerics_grad_rms",
                "per-layer gradient RMS at the last numerics fetch",
                labelnames=("layer",)),
            "grad_absmax": _monitor.gauge(
                "numerics_grad_absmax",
                "per-layer max |grad| at the last numerics fetch",
                labelnames=("layer",)),
            "loss": _monitor.gauge(
                "numerics_loss",
                "loss at the last numerics fetch (the plateau detector's "
                "input)"),
            "nonfinite": _monitor.counter(
                "numerics_nonfinite_total",
                "non-finite gradient elements seen, by layer (counts "
                "elements, not steps — one poisoned embedding row reads "
                "differently than a fully-NaN tensor)",
                labelnames=("layer",)),
            "anomaly": _monitor.counter(
                "numerics_anomaly_total",
                "drift-detector fires by rule and layer (grad_spike | "
                "dead_layer | update_ratio | nonfinite | loss_plateau)",
                labelnames=("kind", "layer")),
            "fetch_ms": _monitor.histogram(
                "numerics_fetch_ms",
                "wall time of one device->host numerics stats fetch "
                "(every FLAGS_numerics_interval steps)"),
        }
    return _M


class _Ema:
    """EMA mean/variance baseline for one (layer, stat) series."""

    __slots__ = ("mean", "var", "n")

    def __init__(self):
        self.mean = 0.0
        self.var = 0.0
        self.n = 0

    def update(self, x, alpha=0.25):
        if self.n == 0:
            self.mean = x
            self.var = 0.0
        else:
            diff = x - self.mean
            incr = alpha * diff
            self.mean += incr
            self.var = (1.0 - alpha) * (self.var + diff * incr)
        self.n += 1

    def std(self):
        return math.sqrt(max(self.var, 0.0))


class NumericsMonitor:
    """Host-side half of the telescope: per-layer history rings, EMA
    baselines, the anomaly rules, and the metric/blackbox surfacing.
    One per SpmdTrainer (created lazily on the first armed fetch) or per
    FederatedAverager; registers itself as a blackbox dump provider so
    every crash/stall bundle carries the last model-health snapshot."""

    def __init__(self, layers, source="trainer"):
        self.layers = [str(n) for n in layers]
        self.source = str(source)
        maxlen = max(2, int(_flags.get_flag("numerics_history", 64)))
        self._hist = collections.defaultdict(
            lambda: collections.deque(maxlen=maxlen))   # (layer, stat) ->
        self._ema = {}                                  # (layer, stat) -> _Ema
        self._dead = {}                                 # layer -> zero streak
        self._plateau_active = False
        self.anomalies = collections.deque(maxlen=64)
        self.fetches = 0
        self.last_step = None
        self.last_loss = None
        self._last = {}          # layer -> {stat: float} (latest snapshot)
        _blackbox.register_provider("numerics", self,
                                    lambda m: m.snapshot())

    # -- ring/baseline plumbing -------------------------------------------
    def history(self, layer, stat):
        """The bounded observation ring for one (layer, stat) series."""
        return list(self._hist[(layer, stat)])

    def _push(self, layer, stat, value):
        self._hist[(layer, stat)].append(value)

    def _baseline(self, layer, stat):
        key = (layer, stat)
        ema = self._ema.get(key)
        if ema is None:
            ema = self._ema[key] = _Ema()
        return ema

    def _fire(self, kind, layer, step, value, baseline=None):
        rec = {"kind": kind, "layer": layer, "step": step,
               "value": None if value is None else float(value)}
        if baseline is not None:
            rec["baseline"] = float(baseline)
        self.anomalies.append(rec)
        from .. import monitor as _monitor

        if _monitor.is_enabled():
            _metrics()["anomaly"].labels(kind=kind, layer=layer).inc()
        _blackbox.note("numerics_anomaly", source=self.source, rule=kind,
                       layer=layer, step=step, value=rec["value"])
        return rec

    # -- the fetch entry point --------------------------------------------
    def observe(self, host_stats, step):
        """Ingest one host-fetched stats dict ({stat: np.ndarray row per
        layer in self.layers order}; missing keys tolerated — the
        federated path reports a partial set). Updates gauges and rings,
        runs every detector, returns the list of NEW anomalies."""
        from .. import monitor as _monitor

        step = int(step)
        self.fetches += 1
        self.last_step = step
        fired = []
        per_layer = {k: np.asarray(v) for k, v in host_stats.items()
                     if k in STAT_KEYS and k != "loss"}
        loss = host_stats.get("loss")
        mon = _monitor.is_enabled()
        m = _metrics() if mon else None
        spike_sigma = float(_flags.get_flag("numerics_spike_sigma", 6.0))
        dead_steps = max(1, int(_flags.get_flag("numerics_dead_steps", 3)))
        ratio_max = float(_flags.get_flag("numerics_ratio_max", 0.25))

        for i, layer in enumerate(self.layers):
            snap = self._last.setdefault(layer, {})
            for stat, arr in per_layer.items():
                if i >= len(arr):
                    continue
                val = arr[i]
                if stat == "quantiles":
                    snap["quantiles"] = [float(q) for q in
                                         np.asarray(val).ravel()]
                    continue
                val = float(val)
                snap[stat] = val
                if mon and stat in ("grad_norm", "param_norm",
                                    "update_ratio", "grad_rms",
                                    "grad_absmax"):
                    m[stat].labels(layer=layer).set(
                        val if math.isfinite(val) else -1.0)
            # ---- detectors (per layer) --------------------------------
            gn = snap.get("grad_norm")
            if gn is not None:
                base = self._baseline(layer, "grad_norm")
                if base.n >= MIN_BASELINE_POINTS and math.isfinite(gn):
                    floor = max(base.std(), 0.05 * abs(base.mean), 1e-9)
                    if gn > base.mean + spike_sigma * floor:
                        fired.append(self._fire(
                            "grad_spike", layer, step, gn,
                            baseline=base.mean))
                if math.isfinite(gn):
                    base.update(gn)
                self._push(layer, "grad_norm", gn)
                # dead layer: EXACT zero — an optimizer that unhooked a
                # layer produces true zeros, not small floats
                if gn == 0.0:
                    streak = self._dead.get(layer, 0) + 1
                    self._dead[layer] = streak
                    if streak == dead_steps:
                        fired.append(self._fire(
                            "dead_layer", layer, step, 0.0))
                else:
                    self._dead[layer] = 0
            ratio = snap.get("update_ratio")
            if ratio is not None:
                self._push(layer, "update_ratio", ratio)
                base = self._baseline(layer, "update_ratio")
                pnorm = snap.get("param_norm")
                # out of band AND well above the layer's own baseline: a
                # fresh zero-init param legitimately runs ratios of O(1)
                # for its first steps (norm growing from nothing), so the
                # absolute band alone would cry wolf through warmup
                if (base.n >= MIN_BASELINE_POINTS
                        and math.isfinite(ratio) and ratio > ratio_max
                        and ratio > 3.0 * abs(base.mean)
                        and (pnorm is None or pnorm > RATIO_PARAM_FLOOR)):
                    fired.append(self._fire(
                        "update_ratio", layer, step, ratio,
                        baseline=base.mean))
                if math.isfinite(ratio):
                    base.update(ratio)
            nonf = snap.get("nonfinite")
            if nonf:
                if mon:
                    m["nonfinite"].labels(layer=layer).inc(nonf)
                fired.append(self._fire("nonfinite", layer, step, nonf))

        # ---- loss plateau (whole model) -------------------------------
        if loss is not None:
            loss = float(np.asarray(loss))
            self.last_loss = loss
            if mon:
                m["loss"].set(loss if math.isfinite(loss) else -1.0)
            ring = self._hist[("loss", "loss")]
            ring.append(loss)
            # window clamped to the ring's capacity: a window larger
            # than numerics_history could otherwise never fill and the
            # plateau rule would be silently dead
            window = max(2, min(
                int(_flags.get_flag("numerics_plateau_window", 8)),
                ring.maxlen))
            eps = float(_flags.get_flag("numerics_plateau_eps", 1e-4))
            tail = list(ring)[-window:]
            if len(tail) >= window and all(math.isfinite(v) for v in tail):
                spread = max(tail) - min(tail)
                scale = max(abs(sum(tail) / len(tail)), 1e-6)
                if spread <= eps * scale:
                    if not self._plateau_active:
                        self._plateau_active = True
                        fired.append(self._fire(
                            "loss_plateau", "loss", step, loss,
                            baseline=spread))
                else:
                    self._plateau_active = False
        return fired

    # -- surfacing ---------------------------------------------------------
    def snapshot(self):
        """JSON-able model-health snapshot: the blackbox dump provider
        table and ``SpmdTrainer.stats()["numerics"]``."""
        return {
            "source": self.source,
            "step": self.last_step,
            "loss": self.last_loss,
            "fetches": self.fetches,
            "layers": {layer: dict(stats)
                       for layer, stats in self._last.items()},
            "anomalies": list(self.anomalies)[-10:],
        }
