"""Black-box flight recorder: progress beacons, stall sentinel, dump bundles.

PRs 2 and 5 made the system observable when it *finishes* — metrics,
spans, MFU. This module explains runs that *don't*: a wedged
``run_until_complete``, a hung compile, a process killed by an external
watchdog. Three pieces, all inert behind ``FLAGS_blackbox`` (one boolean
check per call — the monitor/trace/failpoint gate discipline, pinned by
tests/test_blackbox_gate.py):

**Flight recorder** — ``note(kind, **fields)`` appends one event to a
bounded thread-safe ring (``FLAGS_blackbox_ring`` capacity, oldest
dropped). Wired feeds: span close digests (trace._record), checkpoint
and collective byte tags (framework/io, monitor.record_collective),
bench phase heartbeats, metric-counter deltas (sampled by the sentinel),
and every dump itself. The ring is the "last N seconds before the wedge"
evidence every bundle carries.

**Progress beacons** — ``beacon(site)`` stamps (site, monotonic ns,
count += 1) in a per-site registry. Two styles:

- *window* beacons wrap one operation via ``with progress(site):`` —
  the instrumented hot paths all use this form (``serving/step``,
  ``trainer/step``, ``executor/run``, ``router/step``,
  ``disagg/handoff`` around each step/handoff sweep; ``aot/compile``,
  ``serving/admit``, ``disagg/prefill`` around one-shot operations).
  A site is *active* only while at least one window is open (overlap
  refcounted), so a finished step/compile can never read as a stall
  and a finished sibling engine can never mask a wedged one;
- *raw* beacons (``bench/phase``, user sites) just beat: they stay
  active until the owner calls ``quiesce(site)`` when the loop
  legitimately completes.

**Stall sentinel** — a background daemon thread (started explicitly via
``start_sentinel()``, or automatically on the first beacon when
``FLAGS_blackbox`` and ``FLAGS_stall_timeout_s`` are both set) polls the
registry; when an ACTIVE site stops advancing for longer than the
timeout it writes ONE dump bundle per stall episode (re-armed when the
site advances again), named after the most recently advancing stalled
site — the loop that was running right up to the wedge.

**Dump bundles** — ``dump(reason)`` writes one JSON bundle to
``FLAGS_blackbox_dir`` (default: <tmp>/paddle_tpu_blackbox): all-thread
python stacks (``sys._current_frames`` + a ``faulthandler`` rendering),
the flight-recorder ring, the beacon table, a full metrics snapshot, the
open-span tree with trace_ids, every live serving engine's in-flight
request table, and the ambient context (e.g. the last bench phase).
``blackbox_dump_total{reason=stall|signal|crash}`` counts them and a
``blackbox_dump`` span records each write. On-demand/crash paths:
SIGUSR1 triggers a dump (tools/blackbox_dump.py --trigger), an
uncaught exception dumps through sys.excepthook/threading.excepthook
(with an atexit backstop), and ``ServingEngine.run_until_complete``'s
``engine_stalled`` error plus the Router's no-live-engine error name the
bundle they just wrote. Read bundles with ``tools/blackbox_dump.py
--read`` (docs/OBSERVABILITY.md "Flight recorder & stall diagnostics").
"""
import atexit
import collections
import contextlib
import itertools
import json
import os
import signal
import sys
import tempfile
import threading
import time
import traceback
import weakref

from .. import flags as _flags

__all__ = [
    "is_enabled", "enable", "disable", "sync_from_flag",
    "beacon", "progress", "quiesce", "beacons",
    "note", "note_span", "ring", "ring_summary", "set_capacity",
    "capacity", "set_context", "context",
    "register_provider",
    "start_sentinel", "stop_sentinel", "sentinel_running",
    "dump", "default_dir", "load_bundle", "validate_bundle",
    "install_hooks", "reset", "BUNDLE_KEYS",
]

# FLAGS_blackbox itself is defined in flags.py: the monitor package
# gates its env-armed eager import on it, and this module is
# manifest-lazy (analysis/import_graph.py) — defining the switch here
# would mean importing the module to learn whether to import it
_flags.define_flag(
    "blackbox_dir", "",
    "directory dump bundles are written to; empty = "
    "<system tmp>/paddle_tpu_blackbox")
_flags.define_flag(
    "blackbox_ring", 512,
    "flight-recorder ring capacity (events); oldest dropped past it so "
    "a long-lived instrumented server cannot OOM on event bookkeeping")
_flags.define_flag(
    "stall_timeout_s", 0.0,
    "stall-sentinel threshold: an ACTIVE beacon site that stops "
    "advancing for this many seconds produces a dump bundle. 0 = the "
    "sentinel never auto-starts (start_sentinel() can still arm it "
    "explicitly with its own timeout)")
_flags.define_flag(
    "blackbox_max_bundles", 32,
    "keep-newest cap on dump bundles in FLAGS_blackbox_dir (oldest "
    "pruned after each write): an oscillating stall or crash storm "
    "must never fill the disk of the host it is diagnosing")

# this module is manifest-lazy (ISSUE 12): the enabled latch and the
# provider list are OWNED by the parent package (monitor/__init__.py
# _BB_ON/_BB_PROVIDERS) so instrumented hot paths can check/queue
# without importing the recorder; we adopt the SAME objects — flipping
# _ENABLED[0] here is what monitor.blackbox_on() reads out there
from .. import monitor as _parent  # noqa: E402  (fully imported first)

_ENABLED = _parent._BB_ON     # the ONE read on every disabled fast path
_AUTO_SENTINEL = [False]      # beacon() auto-starts the sentinel thread
_LOCK = threading.RLock()
_RING = collections.deque(maxlen=int(_flags.get_flag("blackbox_ring", 512)))
_BEACONS = {}                 # site -> _Beacon
_CONTEXT = {}                 # ambient key/value carried in every bundle
_PROVIDERS = _parent._BB_PROVIDERS   # (kind, weakref(obj), fn(obj)->table)
_SENTINEL = None              # the live _Sentinel thread, or None
_HOOKS = [False]              # excepthook/atexit installation latch
_SIGNAL_HOOK = [False]        # SIGUSR1 latch (separate: only the main
#                               thread can install it — retried until
#                               an enable() runs there)
_CRASH = [False, False]       # [uncaught exception seen, dump written]

SENTINEL_THREAD_NAME = "paddle-tpu-stall-sentinel"

_DUMP_SEQ = itertools.count()   # collision-proofs same-ms bundle names

#: keys every well-formed dump bundle must carry (the CLI validates them)
BUNDLE_KEYS = ("format", "reason", "ts", "pid", "beacons", "ring",
               "stacks", "metrics", "requests", "context")

# dump accounting, created lazily so a disabled process never grows the
# registry (the tier-1 gate pins zero blackbox_* series with flag unset)
_DUMP_TOTAL = None
_RING_TOTAL = None


class _Beacon:
    """One progress site: a monotonically increasing count plus the last
    beat's monotonic timestamp. `active` gates the sentinel; `dumped_at`
    dedups stall dumps to one per episode (re-armed on the next beat);
    `windows` counts OPEN progress() windows so overlapping windows on
    one site (two engines admitting on two threads) only deactivate it
    when the LAST one closes."""

    __slots__ = ("count", "last_ns", "active", "dumped_at", "windows")

    def __init__(self):
        self.count = 0
        self.last_ns = time.monotonic_ns()
        self.active = True
        self.dumped_at = -1
        self.windows = 0


# -- enable/disable -----------------------------------------------------------

def is_enabled():
    return _ENABLED[0]


def enable(install=True):
    """Turn the recorder on (and, by default, install the SIGUSR1 /
    excepthook dump hooks — idempotent)."""
    _ENABLED[0] = True
    _AUTO_SENTINEL[0] = float(_flags.get_flag("stall_timeout_s", 0.0)) > 0
    if install:
        install_hooks()


def disable():
    _ENABLED[0] = False
    _AUTO_SENTINEL[0] = False


def sync_from_flag():
    """Re-read FLAGS_blackbox/FLAGS_blackbox_ring/FLAGS_stall_timeout_s
    (after paddle.set_flags)."""
    set_capacity(int(_flags.get_flag("blackbox_ring", 512)))
    if bool(_flags.get_flag("blackbox", False)):
        enable()
    else:
        disable()


# -- flight recorder ring -----------------------------------------------------

def set_capacity(n):
    global _RING
    n = max(1, int(n))
    if n == _RING.maxlen:
        return
    with _LOCK:
        _RING = collections.deque(_RING, maxlen=n)


def capacity():
    return _RING.maxlen


def note(kind, **fields):
    """Append one event to the flight-recorder ring. One boolean check
    when disabled; thread-safe; never raises on unserializable fields
    (the bundle writer stringifies them)."""
    if not _ENABLED[0]:
        return
    rec = {"ts": round(time.time(), 6), "kind": str(kind)}
    rec.update(fields)
    with _LOCK:
        _RING.append(rec)
    _count_ring_event()


def note_span(sp):
    """Span-close digest (called by trace._record): name + duration +
    trace identity only — the ring holds digests, not full spans."""
    if not _ENABLED[0]:
        return
    dur = None if sp.end_ns is None else \
        round((sp.end_ns - sp.start_ns) / 1e6, 3)
    note("span", name=sp.name, subsystem=sp.subsystem,
         trace_id=sp.trace_id, dur_ms=dur)


def ring():
    """Snapshot of the ring (oldest first)."""
    with _LOCK:
        return [dict(r) for r in _RING]


def ring_summary(n=5):
    """Compact ring view (count + last-n events) — what trace_dump and
    bench heartbeats attach."""
    with _LOCK:
        tail = [dict(r) for r in list(_RING)[-int(n):]]
        return {"events": len(_RING), "tail": tail}


def _count_ring_event():
    global _RING_TOTAL
    from .. import monitor as _monitor

    if not _monitor.is_enabled():
        return
    if _RING_TOTAL is None:
        # double-checked publish of the cached handle (the metric itself
        # is get-or-create under the registry's own lock either way)
        with _LOCK:
            if _RING_TOTAL is None:
                _RING_TOTAL = _monitor.counter(
                    "blackbox_ring_events_total",
                    "events appended to the flight-recorder ring (only "
                    "exists once FLAGS_blackbox is on)")
    _RING_TOTAL.inc()


# -- ambient context ----------------------------------------------------------

def set_context(key, value):
    """Attach one ambient key/value to every future bundle (e.g. bench
    stamps the current phase here)."""
    if not _ENABLED[0]:
        return
    with _LOCK:
        _CONTEXT[str(key)] = value


def context():
    with _LOCK:
        return dict(_CONTEXT)


# -- progress beacons ---------------------------------------------------------

def _beat(site, open_window=False):
    """One locked beat: count/timestamp/active move together (and the
    window opens atomically with its beat, so a sibling window closing
    concurrently can never leave an OPEN window deactivated — the
    sentinel-blindness race). Returns the site's _Beacon."""
    with _LOCK:
        b = _BEACONS.get(site)
        if b is None:
            b = _BEACONS[site] = _Beacon()
        b.count += 1
        b.last_ns = time.monotonic_ns()
        b.active = True
        if open_window:
            b.windows += 1
    if _AUTO_SENTINEL[0] and _SENTINEL is None:
        start_sentinel()
    return b


def beacon(site):
    """Record one unit of progress at `site`. Disabled: one boolean check
    (the tier-1 gate pins <5us/call). Enabled: one locked beat; also
    (re)activates the site for the sentinel and, when
    FLAGS_stall_timeout_s is set, lazily starts the sentinel thread."""
    if not _ENABLED[0]:
        return
    _beat(site)


@contextlib.contextmanager
def progress(site):
    """Window beacon: active only while the with-block runs — the shape
    for every instrumented operation ("stopped advancing" is only
    meaningful INSIDE the work: a hot-loop step, a compile, an
    admission prefill). Overlap-safe: with two concurrent windows on
    one site (two engines stepping on two threads), the site stays
    active until the LAST one closes — a window closing must not hide
    its still-running sibling from the sentinel."""
    if not _ENABLED[0]:
        yield
        return
    b = _beat(site, open_window=True)
    try:
        yield
    finally:
        # a concurrent reset() may have swept the registry; the held
        # _Beacon still closes consistently (it is simply unreachable)
        with _LOCK:
            b.windows -= 1
            if b.windows <= 0:
                b.active = False


def quiesce(site=None):
    """Mark a site (or all sites) legitimately idle: the sentinel stops
    watching it until its next beacon. Owners of RAW beacon sites call
    this when their loop legitimately completes; progress() windows
    deactivate themselves."""
    if site is None:
        with _LOCK:
            for b in _BEACONS.values():
                b.active = False
        return
    b = _BEACONS.get(site)
    if b is not None:
        b.active = False


def beacons():
    """{site: {"count", "age_s", "active"}} — the bundle's beacon table."""
    now = time.monotonic_ns()
    with _LOCK:
        return {site: {"count": b.count,
                       "age_s": round((now - b.last_ns) / 1e9, 3),
                       "active": bool(b.active)}
                for site, b in _BEACONS.items()}


# -- in-flight state providers ------------------------------------------------

# the cap AND the list lock are owned by the parent package:
# monitor.bb_register_provider mutates the same list pre-import, so both
# sides must serialize on the same lock against the same bound
_PROVIDER_CAP = _parent._BB_PROVIDER_CAP
_PROVIDERS_LOCK = _parent._BB_PROVIDERS_LOCK


def register_provider(kind, obj, fn):
    """Register a live-state provider for dump bundles: ``fn(obj)`` must
    return a JSON-able table (e.g. a serving engine's in-flight request
    table). `obj` is held weakly — dead providers are pruned, the list is
    capped so short-lived engines cannot grow it without bound."""
    with _PROVIDERS_LOCK:
        _PROVIDERS[:] = [(k, r, f) for (k, r, f) in _PROVIDERS
                         if r() is not None][-(_PROVIDER_CAP - 1):]
        _PROVIDERS.append((str(kind), weakref.ref(obj), fn))


def _provider_tables():
    out = []
    with _PROVIDERS_LOCK:
        providers = list(_PROVIDERS)
    for kind, ref, fn in providers:
        obj = ref()
        if obj is None:
            continue
        try:
            out.append({"kind": kind, "table": fn(obj)})
        except Exception as e:   # a broken provider must not kill a dump
            out.append({"kind": kind, "error": f"{type(e).__name__}: {e}"})
    return out


# -- dump bundles -------------------------------------------------------------

def default_dir():
    return os.path.join(tempfile.gettempdir(), "paddle_tpu_blackbox")


def _prune_bundles(d):
    """Keep the newest FLAGS_blackbox_max_bundles bundles in `d` — an
    oscillating stall (a new episode per slow loop iteration) writes one
    bundle per episode forever; the recorder must bound its own disk
    footprint instead of exhausting the host it is diagnosing."""
    keep = int(_flags.get_flag("blackbox_max_bundles", 32))
    if keep < 1:
        return
    try:
        names = [n for n in os.listdir(d)
                 if n.startswith("blackbox-") and n.endswith(".json")]
        if len(names) <= keep:
            return
        paths = sorted((os.path.join(d, n) for n in names),
                       key=os.path.getmtime)
        for p in paths[:-keep]:
            os.remove(p)
    except OSError:
        pass


def _thread_stacks():
    names = {t.ident: (t.name, t.daemon) for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        name, daemon = names.get(tid, ("?", None))
        out.append({"thread_id": tid, "name": name, "daemon": daemon,
                    "stack": traceback.format_stack(frame)})
    return out


def _faulthandler_text():
    try:
        import faulthandler

        with tempfile.TemporaryFile(mode="w+") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
            f.seek(0)
            return f.read()
    except Exception as e:
        return f"faulthandler unavailable: {e}"


def _count_dump(reason):
    global _DUMP_TOTAL
    from .. import monitor as _monitor

    if not _monitor.is_enabled():
        return
    if _DUMP_TOTAL is None:
        with _LOCK:   # double-checked publish of the cached handle
            if _DUMP_TOTAL is None:
                _DUMP_TOTAL = _monitor.counter(
                    "blackbox_dump_total",
                    "dump bundles written, by trigger "
                    "(stall = sentinel/non-convergence, signal = SIGUSR1/"
                    "on-demand, crash = excepthook/abnormal exit)",
                    labelnames=("reason",))
    _DUMP_TOTAL.labels(reason=reason).inc()


def dump(reason, site=None, extra=None, dir_=None):
    """Write one dump bundle; returns its path, or None if the write
    failed (a dump must never take the host down with it). `reason` is
    one of stall|signal|crash; `site` names the stalled beacon when the
    sentinel (or a loop's own non-convergence path) is the trigger."""
    t0_ns = time.perf_counter_ns()
    try:
        d = dir_ or _flags.get_flag("blackbox_dir", "") or default_dir()
        os.makedirs(d, exist_ok=True)
        ts = time.time()
        bundle = {
            "format": 1,
            "reason": str(reason),
            "site": site,
            "ts": round(ts, 6),
            "pid": os.getpid(),
            "argv": list(sys.argv),
            "beacons": beacons(),
            "context": context(),
            "ring": ring(),
            "stacks": _thread_stacks(),
            "faulthandler": _faulthandler_text(),
        }
        try:
            from .. import monitor as _monitor

            bundle["metrics"] = _monitor.snapshot()
        except Exception as e:
            bundle["metrics"] = {"error": f"{type(e).__name__}: {e}"}
        try:
            import paddle_tpu.trace as _trace

            bundle["open_spans"] = _trace.open_spans()
            bundle["span_summary"] = _trace.snapshot_summary(5)
        except Exception:
            bundle["open_spans"] = []
        bundle["requests"] = _provider_tables()
        if extra:
            bundle["extra"] = extra
        # per-process sequence in the name: two same-reason dumps in the
        # same millisecond (thread fan-out crashes) must not clobber
        # each other through the atomic replace
        path = os.path.join(
            d, f"blackbox-{os.getpid()}-{int(ts * 1e3)}-"
               f"{next(_DUMP_SEQ):04d}-{reason}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f, default=str)
        os.replace(tmp, path)   # readers never see a torn bundle
        _prune_bundles(d)
    except Exception:
        return None
    note("dump", reason=reason, site=site, path=path)
    try:
        _count_dump(str(reason))
    except Exception:
        pass
    try:
        import paddle_tpu.trace as _trace

        _trace.emit("blackbox_dump", t0_ns, time.perf_counter_ns(),
                    subsystem="blackbox", reason=str(reason), site=site,
                    path=path)
    except Exception:
        pass
    return path


def load_bundle(path):
    """Read a bundle back; raises ValueError on a missing/malformed file
    or one missing required keys (the CLI's exit-1 contract)."""
    try:
        with open(path) as f:
            bundle = json.load(f)
    except OSError as e:
        raise ValueError(f"cannot read bundle {path!r}: {e}")
    except json.JSONDecodeError as e:
        raise ValueError(f"malformed bundle {path!r}: {e}")
    missing = validate_bundle(bundle)
    if missing:
        raise ValueError(
            f"bundle {path!r} is missing required keys: {missing}")
    return bundle


def validate_bundle(bundle):
    """Missing required keys of a bundle dict (empty = well-formed)."""
    if not isinstance(bundle, dict):
        return list(BUNDLE_KEYS)
    return [k for k in BUNDLE_KEYS if k not in bundle]


# -- stall sentinel -----------------------------------------------------------

class _Sentinel(threading.Thread):
    """Background watcher: every poll it samples counter-family deltas
    into the ring and checks active beacons for stalls. One bundle per
    stall episode, named after the most recently advancing stalled site
    (the loop that was running right up to the wedge; longer-stale sites
    ride along in extra["stalled"])."""

    def __init__(self, timeout_s, poll_s=None):
        super().__init__(name=SENTINEL_THREAD_NAME, daemon=True)
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s) if poll_s is not None \
            else max(0.05, min(1.0, self.timeout_s / 4.0))
        self._stop_ev = threading.Event()
        self._counter_totals = {}

    def stop(self):
        self._stop_ev.set()

    def run(self):
        while not self._stop_ev.wait(self.poll_s):
            try:
                self._poll()
            except Exception:
                pass   # the watcher must outlive anything it watches

    def _poll(self):
        if not _ENABLED[0]:
            return
        self._sample_metric_deltas()
        now = time.monotonic_ns()
        timeout_ns = int(self.timeout_s * 1e9)
        stalled = []
        fresh = False
        with _LOCK:
            items = list(_BEACONS.items())
        for site, b in items:
            if not b.active:
                continue
            age_ns = now - b.last_ns
            if age_ns > timeout_ns:
                stalled.append((age_ns, site, b))
                if b.dumped_at != b.count:
                    fresh = True
        if not stalled or not fresh:
            return
        # the wedged loop is the one that was advancing most recently
        stalled.sort(key=lambda t: t[0])
        _, wedged_site, _ = stalled[0]
        for _, _, b in stalled:
            b.dumped_at = b.count   # one bundle per episode per site
        dump("stall", site=wedged_site,
             extra={"stall_timeout_s": self.timeout_s,
                    "stalled": [{"site": s, "age_s": round(a / 1e9, 3)}
                                for a, s, _ in stalled]})

    def _sample_metric_deltas(self):
        """Ring feed: which counter families moved since the last poll —
        the 'what was it doing' trail next to the beacon timestamps."""
        from .. import monitor as _monitor

        try:
            for metric in _monitor.default_registry().metrics():
                if metric.kind != "counter" \
                        or metric.name.startswith("blackbox_"):
                    continue
                total = sum(s.value for s in metric.series())
                prev = self._counter_totals.get(metric.name)
                if prev is not None and total != prev:
                    note("metric_delta", name=metric.name,
                         delta=total - prev, total=total)
                self._counter_totals[metric.name] = total
        except Exception:
            pass


def start_sentinel(timeout_s=None, poll_s=None):
    """Start (or return) the stall-sentinel thread. `timeout_s` defaults
    to FLAGS_stall_timeout_s (or 60s when that flag is unset). Implicitly
    enables the recorder — a sentinel without beacons watches nothing."""
    global _SENTINEL
    with _LOCK:
        if _SENTINEL is not None and _SENTINEL.is_alive():
            return _SENTINEL
        if not _ENABLED[0]:
            enable()
        if timeout_s is None:
            timeout_s = float(_flags.get_flag("stall_timeout_s", 0.0)) \
                or 60.0
        _SENTINEL = _Sentinel(timeout_s, poll_s=poll_s)
        _SENTINEL.start()
        return _SENTINEL


def stop_sentinel():
    global _SENTINEL
    with _LOCK:
        s, _SENTINEL = _SENTINEL, None
    if s is not None:
        s.stop()
        s.join(timeout=2.0)


def sentinel_running():
    s = _SENTINEL
    return s is not None and s.is_alive()


# -- crash / on-demand hooks --------------------------------------------------

def _on_signal(signum, frame):
    # the handler outlives disable() (hooks are never uninstalled):
    # honor the flag so a disabled recorder stays side-effect-free
    if not _ENABLED[0]:
        return
    # dump on a helper thread, not inside the handler: the signal may
    # have interrupted the main thread while it holds a non-reentrant
    # lock (trace ring, metric series) that the bundle writer needs —
    # inline dumping could deadlock the very process being debugged
    threading.Thread(target=dump, args=("signal",),
                     kwargs={"site": "SIGUSR1"},
                     name="paddle-tpu-blackbox-dump", daemon=True).start()


def _on_excepthook(exc_type, exc, tb):
    _CRASH[0] = True
    try:
        if _ENABLED[0]:
            path = dump(
                "crash", site="excepthook",
                extra={"exception": "".join(traceback.format_exception_only(
                    exc_type, exc)).strip()})
            if path is not None:   # a failed write leaves the atexit
                _CRASH[1] = True   # backstop armed to retry
    except Exception:
        pass
    _ORIG_EXCEPTHOOK(exc_type, exc, tb)


def _on_thread_excepthook(args):
    _CRASH[0] = True
    try:
        if _ENABLED[0]:
            path = dump(
                "crash", site="threading.excepthook",
                extra={"exception": "".join(traceback.format_exception_only(
                    args.exc_type, args.exc_value)).strip(),
                       "thread": getattr(args.thread, "name", None)})
            if path is not None:
                _CRASH[1] = True
    except Exception:
        pass
    _ORIG_THREAD_EXCEPTHOOK(args)


def _on_exit():
    # backstop only: an uncaught exception whose excepthook dump failed
    # (or was bypassed) still leaves a bundle behind
    if _ENABLED[0] and _CRASH[0] and not _CRASH[1]:
        dump("crash", site="atexit")


_ORIG_EXCEPTHOOK = sys.__excepthook__
_ORIG_THREAD_EXCEPTHOOK = threading.__excepthook__


def install_hooks():
    """Install the SIGUSR1 handler + sys/threading excepthooks + atexit
    backstop (idempotent; the dumps themselves still honor the enabled
    flag, so installing is side-effect-free while disabled)."""
    global _ORIG_EXCEPTHOOK, _ORIG_THREAD_EXCEPTHOOK
    if not _SIGNAL_HOOK[0]:
        # the signal half latches only on SUCCESS: a first call from a
        # worker thread (signal.signal raises there) must not burn the
        # one chance to install — the next enable() from the main
        # thread retries
        try:
            if hasattr(signal, "SIGUSR1"):
                signal.signal(signal.SIGUSR1, _on_signal)
            _SIGNAL_HOOK[0] = True
        except (ValueError, OSError):
            pass
    if _HOOKS[0]:
        return
    _HOOKS[0] = True
    if sys.excepthook is not _on_excepthook:
        _ORIG_EXCEPTHOOK = sys.excepthook
        sys.excepthook = _on_excepthook
    if threading.excepthook is not _on_thread_excepthook:
        _ORIG_THREAD_EXCEPTHOOK = threading.excepthook
        threading.excepthook = _on_thread_excepthook
    atexit.register(_on_exit)


# -- test/tooling lifecycle ---------------------------------------------------

def reset():
    """Clear the ring, beacon registry, and ambient context (providers
    are kept — live engines remain dump-visible). Stops nothing: pair
    with stop_sentinel()/disable() as needed."""
    with _LOCK:
        _RING.clear()
        _BEACONS.clear()
        _CONTEXT.clear()


# seed from the environment (FLAGS_blackbox=1 python serve.py)
sync_from_flag()
