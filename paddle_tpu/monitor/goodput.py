"""Goodput ledger: account every wall-second of a run (FLAGS_goodput).

PR 19 made runs survive preemption and PR 17 made per-step speed
persistent, but nothing measured what elasticity *costs*: a run that
resumes twice and reshards once reports the same step_ms as an
uninterrupted twin, and a wedged bench round cannot say where its 900 s
went. This module is the per-run wall-clock accountant (ISSUE 20): one
:class:`GoodputRun` classifies every second between ``start_run`` and
``end_run`` into EXCLUSIVE buckets —

========================  ====================================================
bucket                    meaning
========================  ====================================================
``step``                  productive train/stage-tick time (the goodput)
``compile``               AOT-miss jit-build + compile wall time
``ckpt_save``             checkpoint save (framework/io + CheckpointSaver)
``ckpt_restore``          checkpoint load + same-topology restore
``reshard``               cross-topology restore / live resize(mesh)
``resume_backoff``        elastic recovery leg: backoff sleep + rebuild
``stall``                 an unattributed gap >= FLAGS_goodput_stall_s
``edge_wait``             MPMD stage-edge backpressure
``other``                 every remaining (short) unattributed gap
========================  ====================================================

Attribution is a BUCKET STACK: ``begin(b)``/``end(b)`` push/pop, and
every transition books the elapsed wall time to the bucket that was on
top — nesting *pauses* the outer bucket (a compile resolving inside a
step books ``compile``, not ``step``), so buckets are exclusive and sum
to wall time BY CONSTRUCTION. Hook sites live in ``SpmdTrainer`` (step +
AOT path), ``framework/io.py`` + ``CheckpointSaver``,
``set_state_dict``/``resize``, ``ElasticSupervisor``, and
``StageGraph``/``StageEdge`` — each one boolean check when disarmed.

A finalized run publishes ``goodput_seconds_total{bucket}`` + the
``goodput_fraction`` gauge (``step`` seconds / wall), appends one
``site=run/goodput`` row to the PR 17 perf ledger (``FLAGS_perf_ledger``
also armed) through the direction-aware regression sentinel
(``goodput`` is LOW_IS_BAD: a run whose goodput drops below its banked
baseline fires ``perf_regression_total{site=run/goodput}``), and every
OPEN run is a blackbox dump provider — crash/stall bundles name the
active bucket at kill time, the "where did the 900 s go" answer.

This module also owns the serving-side lineage metric families
(``serving_weight_version`` gauge, ``serving_stale_sessions_total``
counter) so they share the one flag gate and stay out of the disarmed
series namespace.

Inert-by-default with the PR 9/10/17 discipline: ``FLAGS_goodput`` is
defined in flags.py so every hook site is one cached boolean, the
disarmed path never imports this module (manifest-lazy;
analysis/import_graph.py), no ``goodput_*``/``serving_weight_*`` series
exists until armed, and — the flag being deliberately NON-structural —
armed and disarmed runs share executables and train byte-identically
(tests/test_goodput_gate.py pins all of it).
"""
import contextlib
import threading
import time

from .. import flags as _flags
from . import blackbox_lazy as _blackbox  # import-free recorder facade

__all__ = [
    "BUCKETS", "is_armed", "GoodputRun", "start_run", "ensure_run",
    "current_run", "end_run", "reset", "bucket", "count",
    "note_serving_version", "note_stale_session",
]

#: the exclusive wall-time buckets, in reporting order. ``step`` is the
#: goodput; everything else is overhead the ledger exists to expose.
BUCKETS = ("step", "compile", "ckpt_save", "ckpt_restore", "reshard",
           "resume_backoff", "stall", "edge_wait", "other")


def is_armed():
    """The one master switch (FLAGS_goodput). Hook sites read the flag
    (or their construction-consumed handle) directly so the disarmed
    path never imports this module; this helper is for code that
    already did."""
    return bool(_flags.get_flag("goodput", False))


# -- metric families (lazy: no goodput_*/serving_* series until armed) ---------

_M = None


def _metrics():
    global _M
    if _M is None:
        from .. import monitor as _monitor

        _M = {
            "seconds": _monitor.counter(
                "goodput_seconds_total",
                "wall seconds of the current goodput run by exclusive "
                "bucket (lazy — no series until FLAGS_goodput opens a "
                "run); buckets sum to run wall time by construction",
                labelnames=("bucket",)),
            "fraction": _monitor.gauge(
                "goodput_fraction",
                "step-bucket seconds / wall seconds of the last "
                "finalized goodput run (lazy; FLAGS_goodput)"),
            "version": _monitor.gauge(
                "serving_weight_version",
                "weight-version counter the serving engine currently "
                "decodes under (last engine to bump wins; lazy — no "
                "series unless FLAGS_goodput)"),
            "stale": _monitor.counter(
                "serving_stale_sessions_total",
                "served sessions that FINISHED under a weight version "
                "older than the engine's current one (a hot-swap or "
                "adapter load landed mid-session); fires exactly once "
                "per stale finish (lazy; FLAGS_goodput)"),
        }
    return _M


def note_serving_version(counter_value):
    """Publish the serving engine's current weight-version counter on
    the ``serving_weight_version`` gauge (armed call sites only)."""
    from .. import monitor as _monitor

    if _monitor.is_enabled():
        _metrics()["version"].set(int(counter_value))


def note_stale_session():
    """Count one session that finished under a stale weight version."""
    from .. import monitor as _monitor

    if _monitor.is_enabled():
        _metrics()["stale"].inc()


# -- the accountant ------------------------------------------------------------

class GoodputRun:
    """One run's wall-clock accountant: a bucket stack + per-bucket
    totals. Thread-safe (stage graphs tick from the driving thread but
    checkpoint savers may not); every transition — begin, end,
    finalize — books the elapsed time to the bucket that was active."""

    def __init__(self, run_id, stall_threshold_s=None):
        self.run_id = str(run_id)
        self.stall_s = float(
            stall_threshold_s if stall_threshold_s is not None
            else _flags.get_flag("goodput_stall_s", 2.0))
        self.t_start = time.perf_counter()
        self.wall_s = None            # set at finalize
        self.finalized = False
        self.buckets = {b: 0.0 for b in BUCKETS}
        self.counts = {}              # resume/reshard/... event tallies
        self.last_bucket = None       # most recently BOOKED bucket: the
        #                               "what was it doing" answer when a
        #                               crash dump lands after the active
        #                               bucket unwound with the exception
        self._stack = []
        self._last = self.t_start
        self._lock = threading.RLock()
        # crash/stall bundles carry the breakdown + the active bucket at
        # dump time (weakly held; read only when a bundle is written)
        _blackbox.register_provider("goodput", self,
                                    lambda run: run.snapshot())

    # -- attribution -------------------------------------------------------
    def _book(self, now):
        """Book the time since the last transition to the active bucket
        (stack top); an idle gap books ``stall`` past the threshold,
        ``other`` under it. Caller holds the lock."""
        elapsed = now - self._last
        self._last = now
        if elapsed <= 0.0:
            return
        if self._stack:
            b = self._stack[-1]
        else:
            b = "stall" if elapsed >= self.stall_s else "other"
        self.buckets[b] += elapsed
        self.last_bucket = b
        from .. import monitor as _monitor

        if _monitor.is_enabled():
            _metrics()["seconds"].labels(bucket=b).inc(elapsed)

    def begin(self, bucket_name):
        """Enter a bucket: time booked to the PREVIOUS top (or gap)
        first, then this bucket becomes active. Nest freely — the outer
        bucket pauses."""
        if bucket_name not in BUCKETS:
            raise ValueError(
                f"unknown goodput bucket {bucket_name!r} — one of "
                f"{BUCKETS}")
        with self._lock:
            if self.finalized:
                return
            self._book(time.perf_counter())
            self._stack.append(bucket_name)

    def end(self, bucket_name):
        """Leave a bucket: its time is booked and the enclosing bucket
        (if any) resumes. A mismatched end pops the DEEPEST matching
        entry (best effort — an exception may have skipped inner ends);
        an end with no matching begin is a no-op."""
        with self._lock:
            if self.finalized:
                return
            self._book(time.perf_counter())
            if self._stack and self._stack[-1] == bucket_name:
                self._stack.pop()
                return
            for i in range(len(self._stack) - 1, -1, -1):
                if self._stack[i] == bucket_name:
                    del self._stack[i]
                    return

    @contextlib.contextmanager
    def bucket(self, bucket_name):
        self.begin(bucket_name)
        try:
            yield
        finally:
            self.end(bucket_name)

    def count(self, name, n=1):
        """Tally one run-level event (``resume``, ``reshard``, ...) —
        the ``n_resumes``/``n_reshards`` columns of the ledger row."""
        with self._lock:
            self.counts[name] = self.counts.get(name, 0) + int(n)

    # -- surfacing ---------------------------------------------------------
    def active(self):
        """The bucket currently on top of the stack, or None (idle)."""
        with self._lock:
            return self._stack[-1] if self._stack else None

    def wall(self):
        if self.wall_s is not None:
            return self.wall_s
        return time.perf_counter() - self.t_start

    def goodput(self):
        """step seconds / wall seconds so far (0.0 on an empty run)."""
        w = self.wall()
        return (self.buckets["step"] / w) if w > 0 else 0.0

    def snapshot(self):
        """JSON-able breakdown — the blackbox dump-provider table, so a
        crash/stall bundle names the active bucket at kill time."""
        with self._lock:
            return {
                "run_id": self.run_id,
                "active_bucket": self._stack[-1] if self._stack else None,
                "last_bucket": self.last_bucket,
                "stack": list(self._stack),
                "wall_s": self.wall(),
                "buckets": dict(self.buckets),
                "counts": dict(self.counts),
                "goodput": self.goodput(),
                "finalized": self.finalized,
            }

    def finalize(self):
        """Close the run: book the trailing gap, freeze wall time, set
        the ``goodput_fraction`` gauge. Idempotent; returns the per-run
        row dict (what end_run hands the perf ledger)."""
        with self._lock:
            if not self.finalized:
                now = time.perf_counter()
                self._book(now)
                self._stack.clear()
                self.wall_s = now - self.t_start
                self.finalized = True
                from .. import monitor as _monitor

                if _monitor.is_enabled():
                    _metrics()["fraction"].set(self.goodput())
            return {
                "run_id": self.run_id,
                "goodput": self.goodput(),
                "wall_s": self.wall_s,
                "n_resumes": self.counts.get("resume", 0),
                "n_reshards": self.counts.get("reshard", 0),
                "buckets": dict(self.buckets),
            }


# -- the process-current run ---------------------------------------------------

_RUN = None
_RUN_LOCK = threading.Lock()


def start_run(run_id):
    """Open THE process goodput run (hook sites feed whichever run is
    current — one accountant per process, like the perf ledger). An
    unfinalized prior run is finalized + ledgered first, so per-leg
    callers (bench.py) just call start_run at each leg head."""
    global _RUN
    with _RUN_LOCK:
        prior, _RUN = _RUN, None
    if prior is not None and not prior.finalized:
        _close(prior)
    run = GoodputRun(run_id)
    with _RUN_LOCK:
        _RUN = run
    return run


def ensure_run(run_id):
    """The current run, or a fresh one under ``run_id`` if none is open
    — how armed trainers/supervisors self-open attribution without
    clobbering a run a tool or bench leg already started."""
    with _RUN_LOCK:
        if _RUN is not None and not _RUN.finalized:
            return _RUN
    return start_run(run_id)


def current_run():
    return _RUN


def end_run():
    """Finalize + detach the current run; publishes the fraction gauge
    and (``FLAGS_perf_ledger`` also armed) appends the per-run ledger
    row at ``site=run/goodput`` THROUGH the regression sentinel —
    ``goodput`` is LOW_IS_BAD, so a run under its banked baseline fires
    ``perf_regression_total{site=run/goodput}``. Returns the row dict
    or None when no run was open."""
    global _RUN
    with _RUN_LOCK:
        run, _RUN = _RUN, None
    if run is None:
        return None
    return _close(run)


def _close(run):
    row = run.finalize()
    _blackbox.note("goodput_run", run_id=run.run_id,
                   goodput=row["goodput"], wall_s=row["wall_s"],
                   n_resumes=row["n_resumes"],
                   n_reshards=row["n_reshards"])
    if _flags.get_flag("perf_ledger", False):
        from . import perfledger as _perfledger

        # force=True: every run lands a row; check=True: the sentinel
        # watches goodput itself (direction-aware — LOW_IS_BAD)
        _perfledger.get_ledger().on_step(
            "run/goodput",
            {"goodput": row["goodput"], "wall_s": row["wall_s"],
             "n_resumes": row["n_resumes"],
             "n_reshards": row["n_reshards"],
             "run_id": row["run_id"], "buckets": row["buckets"]},
            sig=row["run_id"], force=True, check=True)
    return row


def reset():
    """Drop the current run WITHOUT finalizing/ledgering it (tests)."""
    global _RUN
    with _RUN_LOCK:
        _RUN = None


# -- hook-site helpers ---------------------------------------------------------

@contextlib.contextmanager
def bucket(bucket_name):
    """``with goodput.bucket("step"):`` against whichever run is
    current — a no-op (beyond one global read) when none is open, so
    armed hook sites never have to know whether a run started."""
    run = _RUN
    if run is None:
        yield
        return
    run.begin(bucket_name)
    try:
        yield
    finally:
        run.end(bucket_name)


def count(name, n=1):
    """Tally one event on the current run (no-op when none is open)."""
    run = _RUN
    if run is not None:
        run.count(name, n=n)
