"""Weight normalization utilities (python/paddle/nn/utils/weight_norm_hook.py parity)."""
import jax.numpy as jnp

from ..core.tensor import ParamBase


def _norm_except(w, dim):
    if dim is None:
        return jnp.sqrt(jnp.sum(w * w))
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=False))


def weight_norm(layer, name="weight", dim=0):
    w = getattr(layer, name)
    g0 = _norm_except(w._data, dim)
    v0 = w._data
    layer.add_parameter(name + "_g", ParamBase(g0))
    layer.add_parameter(name + "_v", ParamBase(v0))
    del layer._parameters[name]

    def hook(l, inputs):
        from ..core.dispatch import apply

        g = l._parameters[name + "_g"]
        v = l._parameters[name + "_v"]

        def fn(gv, vv):
            n = _norm_except(vv, dim)
            if dim is not None:
                shape = [1] * vv.ndim
                shape[dim] = -1
                return vv * (gv / n).reshape(shape)
            return vv * (gv / n)

        w_t = apply(fn, g, v)
        object.__setattr__(l, "_wn_cached", w_t)
        l._parameters[name] = w_t  # temporary for forward
        return None

    def post_hook(l, inputs, output):
        l._parameters.pop(name, None)
        return None

    layer._wn_pre = layer.register_forward_pre_hook(hook)
    layer._wn_post = layer.register_forward_post_hook(post_hook)
    return layer


def remove_weight_norm(layer, name="weight"):
    if hasattr(layer, "_wn_pre"):
        layer._wn_pre.remove()
        layer._wn_post.remove()
        g = layer._parameters.pop(name + "_g")
        v = layer._parameters.pop(name + "_v")
        n = _norm_except(v._data, 0)
        shape = [1] * v._data.ndim
        shape[0] = -1
        layer.add_parameter(name, ParamBase(v._data * (g._data / n).reshape(shape)))
    return layer
