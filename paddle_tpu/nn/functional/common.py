"""Common functionals: linear, dropout, pad, interpolate, embedding-adjacent utilities.

Reference parity: python/paddle/nn/functional/common.py (+ input.py) backed by
operators/{matmul_v2,dropout,pad3d,interpolate_v2,one_hot_v2,embedding}*.
Linear is the MXU workhorse: kept as a single jnp.matmul (+bias add) so XLA emits one
fused GEMM.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ...core import dtype as dtype_mod
from ...core.dispatch import apply
from ...core.generator import default_generator
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def linear(x, weight, bias=None, name=None):
    from ...amp.auto_cast import amp_dtype

    def fn(v, w, *b):
        d = amp_dtype()
        if d is not None and jnp.issubdtype(v.dtype, jnp.floating):
            v, w = v.astype(d), w.astype(d)
        out = jnp.matmul(v, w)
        if b:
            out = out + b[0].astype(out.dtype)
        return out

    if bias is None:
        return apply(fn, _t(x), _t(weight))
    return apply(fn, _t(x), _t(weight), _t(bias))


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    x = _t(x)
    if not training or p == 0.0:
        return x
    if p == 1.0:
        return apply(lambda v: jnp.zeros_like(v), x)
    key = default_generator().split()

    def fn(v):
        shape = list(v.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), jnp.zeros_like(v))
        return jnp.where(keep, v, jnp.zeros_like(v))

    return apply(fn, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = _t(x)
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = default_generator().split()

    def fn(v):
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = (1.0 / np.sqrt((1.0 - p) * (1.0 + p * alpha_p**2))).astype(np.float32)
        b = -a * alpha_p * p
        return a * jnp.where(keep, v, jnp.full_like(v, alpha_p)) + b

    return apply(fn, x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = _t(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]

    def fn(v):
        nd = v.ndim
        if len(pad) == 2 * nd:
            # paddle "all-dim" format: [lo0, hi0, lo1, hi1, ...]
            widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # spatial-only format, reversed (last dim first): NCHW [l,r,t,b]
            widths = [(0, 0)] * nd
            n_spatial = len(pad) // 2
            if data_format.startswith("NC"):
                spatial_dims = list(range(nd - n_spatial, nd))  # pad the trailing dims
            else:
                spatial_dims = list(range(1, 1 + n_spatial))
            for i, d in enumerate(reversed(spatial_dims)):
                widths[d] = (pad[2 * i], pad[2 * i + 1])
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(v, widths, mode="constant", constant_values=value)
        return jnp.pad(v, widths, mode=jmode)

    return apply(fn, x)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def interpolate(
    x,
    size=None,
    scale_factor=None,
    mode="nearest",
    align_corners=False,
    align_mode=0,
    data_format="NCHW",
    name=None,
):
    """operators/interpolate_v2_op.cc parity via jax.image.resize."""
    x = _t(x)
    nd = x.ndim
    channel_last = not data_format.startswith("NC")
    spatial = nd - 2
    in_spatial = x.shape[1:-1] if channel_last else x.shape[2:]
    if size is not None:
        if isinstance(size, Tensor):
            size = size.tolist()
        out_spatial = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in (size if isinstance(size, (list, tuple)) else [size])]
    else:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * spatial
        out_spatial = [int(d * s) for d, s in zip(in_spatial, scale_factor)]

    method = {
        "nearest": "nearest",
        "bilinear": "bilinear",
        "trilinear": "trilinear",
        "bicubic": "bicubic",
        "linear": "linear",
        "area": "linear",
    }[mode]

    def fn(v):
        if channel_last:
            out_shape = (v.shape[0],) + tuple(out_spatial) + (v.shape[-1],)
        else:
            out_shape = v.shape[:2] + tuple(out_spatial)
        if mode == "nearest" or not align_corners:
            return jax.image.resize(v, out_shape, method=method)
        # align_corners: linear interpolation on corner-aligned grid
        sp_dims = list(range(1, 1 + spatial)) if channel_last else list(range(2, 2 + spatial))
        out = v
        for d, new in zip(sp_dims, out_spatial):
            old = out.shape[d]
            if old == new:
                continue
            idx = jnp.linspace(0.0, old - 1.0, new)
            lo = jnp.floor(idx).astype(jnp.int32)
            hi = jnp.clip(lo + 1, 0, old - 1)
            w = (idx - lo).reshape([-1 if i == d else 1 for i in range(out.ndim)])
            out = jnp.take(out, lo, axis=d) * (1 - w) + jnp.take(out, hi, axis=d) * w
        return out

    return apply(fn, x)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, *bs):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bs:
            out = out + bs[0]
        return out

    if bias is None:
        return apply(fn, _t(x1), _t(x2), _t(weight))
    return apply(fn, _t(x1), _t(x2), _t(weight), _t(bias))


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)

    return apply(fn, _t(x1), _t(x2))


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(v):
        if data_format == "NCHW":
            b, c, h, w = v.shape
            v = v.reshape(b, c // (r * r), r, r, h, w)
            v = jnp.transpose(v, (0, 1, 4, 2, 5, 3))
            return v.reshape(b, c // (r * r), h * r, w * r)
        b, h, w, c = v.shape
        v = v.reshape(b, h, w, c // (r * r), r, r)
        v = jnp.transpose(v, (0, 1, 4, 2, 5, 3))
        return v.reshape(b, h * r, w * r, c // (r * r))

    return apply(fn, _t(x))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def fn(v):
        if data_format == "NCHW":
            b, c, h, w = v.shape
            v = v.reshape(b, c, h // r, r, w // r, r)
            v = jnp.transpose(v, (0, 1, 3, 5, 2, 4))
            return v.reshape(b, c * r * r, h // r, w // r)
        raise NotImplementedError

    return apply(fn, _t(x))


def _norm_pad4(paddings):
    """Normalize paddle's int | (ph, pw) | [top, left, bottom, right] padding
    spec to (top, left, bottom, right)."""
    if isinstance(paddings, (list, tuple)) and len(paddings) == 4:
        pt, pl, pb, pr = paddings
    elif isinstance(paddings, (list, tuple)):
        (pt, pl) = (pb, pr) = tuple(paddings)
    else:
        pt = pb = pl = pr = paddings
    return pt, pl, pb, pr


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """operators/unfold_op.cc parity (im2col)."""

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    pt, pl, pb, pr = _norm_pad4(paddings)
    dh, dw = _pair(dilations)

    def fn(v):
        b, c, h, w = v.shape
        v = jnp.pad(v, ((0, 0), (0, 0), (pt, pb), (pl, pr)))
        out_h = (h + pt + pb - dh * (kh - 1) - 1) // sh + 1
        out_w = (w + pl + pr - dw * (kw - 1) - 1) // sw + 1
        patches = []
        for i in range(kh):
            for j in range(kw):
                sl = v[:, :, i * dh : i * dh + out_h * sh : sh, j * dw : j * dw + out_w * sw : sw]
                patches.append(sl)
        out = jnp.stack(patches, axis=2)  # [b, c, kh*kw, oh, ow]
        return out.reshape(b, c * kh * kw, out_h * out_w)

    return apply(fn, _t(x))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """operators/fold (col2im) parity — inverse of unfold: [b, c*kh*kw, L]
    patches scatter-added back into [b, c, H, W] (overlaps accumulate)."""

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    oh_out, ow_out = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    pt, pl, pb, pr = _norm_pad4(paddings)
    dh, dw = _pair(dilations)
    out_h = (oh_out + pt + pb - dh * (kh - 1) - 1) // sh + 1
    out_w = (ow_out + pl + pr - dw * (kw - 1) - 1) // sw + 1

    def fn(v):
        b, ckk, L = v.shape
        c = ckk // (kh * kw)
        v = v.reshape(b, c, kh * kw, out_h, out_w)
        canvas = jnp.zeros((b, c, oh_out + pt + pb, ow_out + pl + pr), v.dtype)
        idx = 0
        for i in range(kh):
            for j in range(kw):
                patch = v[:, :, idx]                      # [b, c, oh, ow]
                # strided scatter-add of this kernel tap
                canvas = canvas.at[
                    :, :, i * dh : i * dh + out_h * sh : sh,
                    j * dw : j * dw + out_w * sw : sw].add(patch)
                idx += 1
        return canvas[:, :, pt : pt + oh_out, pl : pl + ow_out]

    return apply(fn, _t(x))


def one_hot(x, num_classes, name=None):
    out = apply(lambda v: jax.nn.one_hot(v.astype(jnp.int32), num_classes, dtype=jnp.float32), _t(x).detach())
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """operators/lookup_table_v2_op.cc parity. `sparse` (SelectedRows grads) is a no-op:
    XLA scatter-add on the gather VJP is already sparse-friendly."""

    def fn(ids, w):
        out = jnp.take(w, ids.astype(jnp.int32), axis=0)
        if padding_idx is not None and padding_idx >= 0:
            mask = (ids != padding_idx)[..., None]
            out = out * mask.astype(out.dtype)
        return out

    return apply(fn, _t(x).detach(), _t(weight))


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(l):
        k = l.shape[-1]
        if prior_dist is not None:
            pd = prior_dist._data if isinstance(prior_dist, Tensor) else jnp.asarray(prior_dist)
            return (1 - epsilon) * l + epsilon * pd
        return (1 - epsilon) * l + epsilon / k

    return apply(fn, _t(label))


def class_center_sample(label, num_classes, num_samples, group=None):
    """PartialFC class-center sampling (paddle 2.x API, post-dating the
    reference snapshot; kept for margin-softmax training).

    Returns (remapped_label, sampled_class_index): every class present in
    `label` is sampled, the rest of the num_samples budget is filled with
    uniformly-drawn negative classes, and the sampled set is sorted
    ascending; remapped_label re-indexes each label into that set.

    TPU design: fixed [num_samples] output (XLA static shapes) via priority
    keys — positives rank 2+u, negatives u~U[0,1), one top_k over
    num_classes — instead of host-side rejection sampling. Deviation: the
    reference grows the output when label holds > num_samples distinct
    classes; here the budget is hard and over-budget positives remap to -1
    (see PARITY.md).
    """
    if group not in (None, False):
        raise ValueError(
            "class_center_sample: process groups are not supported in this "
            "build; shard classes with distributed.split instead")
    if num_samples > num_classes:
        raise ValueError("num_samples may not exceed num_classes")
    key = default_generator().split()
    lab_t = _t(label)
    if isinstance(lab_t._data, jax.core.Tracer) and \
            not isinstance(key, jax.core.Tracer):
        import warnings

        warnings.warn(
            "class_center_sample under a jit trace without a traced RNG "
            "scope: the negative-class sample is drawn at trace time and "
            "BAKED into the compiled program. Run inside a trainer step "
            "(traced_rng) or eagerly to resample per step.", stacklevel=2)

    def fn(l):
        flat = l.reshape(-1).astype(jnp.int32)
        pos = jnp.zeros((num_classes,), jnp.float32).at[flat].set(1.0)
        prio = pos * 2.0 + jax.random.uniform(key, (num_classes,))
        _, idx = jax.lax.top_k(prio, num_samples)
        sampled = jnp.sort(idx.astype(jnp.int32))
        slot = jnp.clip(jnp.searchsorted(sampled, flat), 0, num_samples - 1)
        remapped = jnp.where(sampled[slot] == flat, slot, -1)
        return remapped.reshape(l.shape).astype(jnp.int32), sampled

    return apply(fn, lab_t.detach())
