"""Convolution functionals.

Reference parity: python/paddle/nn/functional/conv.py backed by operators/conv_op.cc /
conv_cudnn_op.cu / conv_transpose_op.cc.
TPU-native design: all convs lower to a single lax.conv_general_dilated — XLA maps it
onto the MXU (no cuDNN algorithm search / workspace logic needed).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def _ntuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    if len(v) == 1:
        return v * n
    return v


def _padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(p, p) if isinstance(p, int) else tuple(p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _match_dtypes(v, w):
    """lax conv requires matching dtypes; mixed bf16-input/f32-weight calls
    (outside auto_cast) promote like the reference would."""
    if v.dtype != w.dtype:
        ct = jnp.promote_types(v.dtype, w.dtype)
        return v.astype(ct), w.astype(ct)
    return v, w


def _conv(x, weight, bias, stride, padding, dilation, groups, n, channel_last, transpose=False, output_padding=0):
    strides = _ntuple(stride, n)
    dils = _ntuple(dilation, n)
    pad = _padding(padding, n)

    if channel_last:
        lhs_spec = "N" + "DHW"[3 - n :] + "C"
    else:
        lhs_spec = "NC" + "DHW"[3 - n :]
    out_spec = lhs_spec
    rhs_spec = "OI" + "DHW"[3 - n :]
    dn = jax.lax.conv_dimension_numbers((1,) * (n + 2), (1,) * (n + 2), (lhs_spec, rhs_spec, out_spec))

    if not transpose:
        def fn(v, w, *b):
            v, w = _match_dtypes(v, w)
            out = jax.lax.conv_general_dilated(
                v, w, window_strides=strides, padding=pad,
                rhs_dilation=dils, dimension_numbers=dn, feature_group_count=groups,
                preferred_element_type=None,
            )
            if b:
                bias_shape = [1] * out.ndim
                bias_shape[out.ndim - 1 if channel_last else 1] = b[0].shape[0]
                out = out + b[0].reshape(bias_shape)
            return out
    else:
        opad = _ntuple(output_padding, n)

        def fn(v, w, *b):
            # conv_transpose: lhs_dilation = stride; weight layout [in, out//groups, *k]
            v, w = _match_dtypes(v, w)
            k_dims = w.shape[2:]
            if isinstance(pad, str):
                pads = [(0, 0)] * n if pad == "VALID" else None
                if pads is None:
                    raise ValueError("SAME padding unsupported for conv_transpose")
            else:
                pads = [
                    (dils[i] * (k_dims[i] - 1) - pad[i][0],
                     dils[i] * (k_dims[i] - 1) - pad[i][1] + opad[i])
                    for i in range(n)
                ]
            # weight [I, O/g, *k] -> flip spatial, swap to [O, I/g? ...]
            w_t = jnp.flip(w, axis=tuple(range(2, 2 + n)))
            if groups == 1:
                w_t = jnp.swapaxes(w_t, 0, 1)  # [O, I, *k]
            else:
                i, og = w.shape[0], w.shape[1]
                w_g = w_t.reshape((groups, i // groups, og) + k_dims)
                w_g = jnp.swapaxes(w_g, 1, 2)  # [g, og, i/g, *k]
                w_t = w_g.reshape((groups * og, i // groups) + k_dims)
            out = jax.lax.conv_general_dilated(
                v, w_t, window_strides=(1,) * n, padding=pads,
                lhs_dilation=strides, rhs_dilation=dils, dimension_numbers=dn,
                feature_group_count=groups,
            )
            if b:
                bias_shape = [1] * out.ndim
                bias_shape[out.ndim - 1 if channel_last else 1] = b[0].shape[0]
                out = out + b[0].reshape(bias_shape)
            return out

    args = [_t(x), _t(weight)] + ([_t(bias)] if bias is not None else [])
    return apply(fn, *args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, data_format in ("NLC",))


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format == "NHWC")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format == "NDHWC")


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, data_format == "NLC", transpose=True, output_padding=output_padding)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format == "NHWC", transpose=True, output_padding=output_padding)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format == "NDHWC", transpose=True, output_padding=output_padding)
