"""Attention functionals.

Reference parity: the reference has no fused attention op (MultiHeadAttention composes
matmuls in python/paddle/nn/layer/transformer.py:83); this module goes beyond it with a
single attention entry point that can route to the Pallas flash-attention kernel
(paddle_tpu/ops/flash_attention.py) on TPU, or the naive XLA path elsewhere.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False, training=True, name=None,
    use_flash=True,
):
    """query/key/value: [batch, seq, heads, head_dim] (paddle 2.x layout).

    Routes to the Pallas flash kernel when shapes allow (TPU, no mask beyond causal);
    falls back to the naive XLA softmax(QK^T)V otherwise.
    """
    args = [_t(query), _t(key), _t(value)]
    mask_val = attn_mask._data if isinstance(attn_mask, Tensor) else attn_mask

    flash_ok = False
    try:
        from ...ops import flash_attention as fa

        q = args[0]
        flash_ok = (
            use_flash
            and mask_val is None
            and dropout_p == 0.0
            and fa.supported(tuple(q.shape), str(q.dtype))
        )
    except Exception:
        flash_ok = False

    if flash_ok:
        def fn(q, k, v):
            return fa.flash_attention(q, k, v, causal=is_causal)

        return apply(fn, *args)

    def fn(q, k, v):
        # [b, s, h, d] -> [b, h, s, d]
        q = jnp.swapaxes(q, 1, 2)
        k = jnp.swapaxes(k, 1, 2)
        v = jnp.swapaxes(v, 1, 2)
        scale = 1.0 / math.sqrt(q.shape[-1])
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        if mask_val is not None:
            m = mask_val
            if m.dtype == jnp.bool_:
                scores = jnp.where(m, scores, jnp.asarray(-1e30, scores.dtype))
            else:
                scores = scores + m.astype(scores.dtype)
        if is_causal:
            s_q, s_k = scores.shape[-2], scores.shape[-1]
            causal = jnp.tril(jnp.ones((s_q, s_k), dtype=bool))
            scores = jnp.where(causal, scores, jnp.asarray(-1e30, scores.dtype))
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        return jnp.swapaxes(out, 1, 2)

    return apply(fn, *args)
