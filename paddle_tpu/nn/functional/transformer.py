"""Attention functionals.

Reference parity: the reference has no fused attention op (MultiHeadAttention composes
matmuls in python/paddle/nn/layer/transformer.py:83); this module goes beyond it with a
single attention entry point that can route to the Pallas flash-attention kernel
(paddle_tpu/ops/flash_attention.py) on TPU, or the naive XLA path elsewhere.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False, training=True, name=None,
    use_flash=True, window=None,
):
    """query/key/value: [batch, seq, heads, head_dim] (paddle 2.x layout).

    Routes to the Pallas flash kernel when shapes allow (TPU, no mask beyond
    causal/window); falls back to the naive XLA softmax(QK^T)V otherwise.
    window=W (requires is_causal) restricts attention to the last W tokens
    (sliding window) — block-skipped in the flash kernel, masked here.
    """
    if window is not None and not is_causal:
        raise ValueError("window requires is_causal=True")
    args = [_t(query), _t(key), _t(value)]
    mask_val = attn_mask._data if isinstance(attn_mask, Tensor) else attn_mask

    flash_ok = False
    try:
        from ...ops import flash_attention as fa

        q = args[0]
        flash_ok = (
            use_flash
            and mask_val is None
            and dropout_p == 0.0
            and fa.supported(tuple(q.shape), str(q.dtype))
        )
    except Exception:
        flash_ok = False

    if flash_ok:
        def fn(q, k, v):
            return fa.flash_attention(q, k, v, causal=is_causal,
                                      window=window)

        return apply(fn, *args)

    def fn_probs(q, k):
        # [b, s, h, d] -> [b, h, s, d]
        q = jnp.swapaxes(q, 1, 2)
        k = jnp.swapaxes(k, 1, 2)
        scale = 1.0 / math.sqrt(q.shape[-1])
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        if mask_val is not None:
            m = mask_val
            if m.dtype == jnp.bool_:
                scores = jnp.where(m, scores, jnp.asarray(-1e30, scores.dtype))
            else:
                scores = scores + m.astype(scores.dtype)
        if is_causal:
            s_q, s_k = scores.shape[-2], scores.shape[-1]
            keep = jnp.tril(jnp.ones((s_q, s_k), dtype=bool))
            if window is not None:
                qp = jnp.arange(s_q)[:, None]
                kp = jnp.arange(s_k)[None, :]
                keep &= (qp - kp) < window
            scores = jnp.where(keep, scores, jnp.asarray(-1e30, scores.dtype))
        return jax.nn.softmax(scores, axis=-1)

    def fn_out(p_, v):
        return jnp.swapaxes(
            jnp.einsum("bhqk,bhkd->bhqd", p_, jnp.swapaxes(v, 1, 2)), 1, 2)

    if dropout_p and training:
        # attention dropout on the probabilities (reference semantics);
        # routed through F.dropout so the framework RNG (and per-step keys
        # under a jitted trainer) governs the mask
        from .common import dropout as f_dropout

        probs = apply(fn_probs, args[0], args[1])
        probs = f_dropout(probs, p=dropout_p, training=True)
        return apply(fn_out, _t(probs), args[2])
    probs = apply(fn_probs, args[0], args[1])
    return apply(fn_out, _t(probs), args[2])
