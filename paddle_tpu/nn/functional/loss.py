"""Loss functionals.

Reference parity: python/paddle/nn/functional/loss.py backed by
operators/{cross_entropy_op,softmax_with_cross_entropy_op,bce_loss_op,smooth_l1_loss_op,
kldiv_loss_op,margin_rank_loss_op,nll_loss_op,ctc_align_op/warpctc_op,hinge_loss_op}.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(
    input,
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    """softmax_with_cross_entropy_op.cc parity (fused log-softmax + NLL on TPU)."""
    args = [_t(input), _t(label) if soft_label else _t(label).detach()]
    if weight is not None:
        args.append(_t(weight).detach())

    def fn(logits, label_v, *w):
        n_classes = logits.shape[axis]
        if soft_label or (label_v.ndim == logits.ndim and label_v.shape == logits.shape):
            logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else jnp.log(jnp.clip(logits, 1e-30, None))
            soft = label_v
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(soft * logp, axis=axis)
            return _reduce(loss, reduction)
        ids = label_v
        if ids.ndim == logits.ndim and ids.shape[axis] == 1:
            ids = jnp.squeeze(ids, axis=axis)
        ids = ids.astype(jnp.int32)
        valid = ids != ignore_index
        safe_ids = jnp.where(valid, ids, 0)
        # Hard labels: never materialize log_softmax / one_hot over the class
        # dim — at LM scale that's an [N, vocab] round-trip through HBM (the
        # one_hot alone dominated GPT-2 step time: 62.3k -> 70.4k tok/s on one
        # v5e chip from this rewrite). -logp[id] = logsumexp - logits[id];
        # reductions/gathers fuse into the logits producer. fp32 accumulation
        # for bf16 logits (the convert fuses into the reduce, no HBM copy).
        lf = logits.astype(jnp.float32)
        if use_softmax:
            picked = jnp.squeeze(
                jnp.take_along_axis(lf, jnp.expand_dims(safe_ids, axis), axis=axis),
                axis=axis)
            lse = jax.nn.logsumexp(lf, axis=axis)
            loss = lse - picked
            if label_smoothing > 0:
                # -sum(logp)/n = lse - mean(logits)
                loss = ((1 - label_smoothing) * loss
                        + label_smoothing * (lse - jnp.mean(lf, axis=axis)))
        else:
            loglf = jnp.log(jnp.clip(lf, 1e-30, None))
            loss = -jnp.squeeze(
                jnp.take_along_axis(loglf, jnp.expand_dims(safe_ids, axis), axis=axis),
                axis=axis)
            if label_smoothing > 0:
                loss = ((1 - label_smoothing) * loss
                        - label_smoothing * jnp.mean(loglf, axis=axis))
        loss = jnp.where(valid, loss, 0.0)
        # fp32 accumulation, but return the logits dtype (reference output-
        # dtype parity for bf16/fp16 inputs)
        out_dtype = logits.dtype
        if w:
            wt = jnp.take(w[0], safe_ids, axis=0) * valid
            loss = loss * wt
            if reduction == "mean":
                return (jnp.sum(loss)
                        / jnp.maximum(jnp.sum(wt), 1e-12)).astype(out_dtype)
        if reduction == "mean":
            return (jnp.sum(loss) / jnp.maximum(
                jnp.sum(valid.astype(loss.dtype)), 1.0)).astype(out_dtype)
        return _reduce(loss, reduction).astype(out_dtype)

    return apply(fn, *args)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index, reduction="none", axis=axis)
    from .activation import softmax as _softmax

    loss = loss.unsqueeze(axis) if loss.ndim < _t(logits).ndim else loss
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    return cross_entropy(input, label, weight=weight, ignore_index=ignore_index, reduction=reduction, use_softmax=False, soft_label=False) if False else _nll(input, label, weight, ignore_index, reduction)


def _nll(input, label, weight, ignore_index, reduction):
    args = [_t(input), _t(label).detach()]
    if weight is not None:
        args.append(_t(weight).detach())

    def fn(logp, ids, *w):
        ids = ids.astype(jnp.int32)
        valid = ids != ignore_index
        safe = jnp.where(valid, ids, 0)
        picked = jnp.take_along_axis(logp, safe[..., None] if logp.ndim == ids.ndim + 1 else safe, axis=1 if logp.ndim > 1 else 0)
        if logp.ndim == ids.ndim + 1:
            picked = jnp.squeeze(picked, axis=1)
        loss = -picked * valid
        if w:
            wt = jnp.take(w[0], safe, axis=0) * valid
            loss = loss * wt
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(wt), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
        return _reduce(loss, reduction)

    return apply(fn, *args)


def mse_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce((a - b) ** 2, reduction), _t(input), _t(label))


def l1_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.abs(a - b), reduction), _t(input), _t(label))


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        # paddle multiplies by delta
        return _reduce(loss * delta, reduction)

    return apply(fn, _t(input), _t(label))


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight).detach())

    def fn(p, y, *w):
        p = jnp.clip(p, 1e-12, 1 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    return apply(fn, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    args = [_t(logit), _t(label)]
    if weight is not None:
        args.append(_t(weight).detach())

    def fn(z, y, *w):
        pw = pos_weight._data if isinstance(pos_weight, Tensor) else pos_weight
        # numerically-stable BCE-with-logits
        log_sig = jax.nn.log_sigmoid(z)
        log_sig_neg = jax.nn.log_sigmoid(-z)
        if pw is not None:
            loss = -(pw * y * log_sig + (1 - y) * log_sig_neg)
        else:
            loss = -(y * log_sig + (1 - y) * log_sig_neg)
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    return apply(fn, *args)


def kl_div(input, label, reduction="mean", name=None):
    def fn(logp, y):
        loss = y * (jnp.log(jnp.clip(y, 1e-12, None)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return apply(fn, _t(input), _t(label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return apply(
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        _t(input), _t(other), _t(label),
    )


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return apply(
        lambda a, y: _reduce(jnp.where(y == 1, a, jnp.maximum(0.0, margin - a)), reduction),
        _t(input), _t(label).detach(),
    )


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / (jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return apply(fn, _t(input1), _t(input2), _t(label).detach())


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)

    return apply(fn, _t(input), _t(positive), _t(negative))


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """warpctc_op parity — forward-backward in pure XLA (scan over time).

    log_probs: [T, B, C] (paddle layout), labels: [B, S] int32.
    """
    args = [_t(log_probs), _t(labels).detach(), _t(input_lengths).detach(), _t(label_lengths).detach()]

    def fn(lp, lab, in_len, lab_len):
        lp = jax.nn.log_softmax(lp, axis=-1)
        T, B, C = lp.shape
        S = lab.shape[1]
        # extended label sequence with blanks: length 2S+1
        ext = jnp.full((B, 2 * S + 1), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        ext_len = 2 * lab_len.astype(jnp.int32) + 1
        neg_inf = jnp.asarray(-1e30, dtype=lp.dtype)
        # allow skip when ext[s] != blank and ext[s] != ext[s-2]
        can_skip = jnp.concatenate(
            [jnp.zeros((B, 2), dtype=bool), (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2])], axis=1
        )
        alpha0 = jnp.full((B, 2 * S + 1), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first_lab = jnp.take_along_axis(lp[0], ext[:, 1:2], axis=1)[:, 0]
        alpha0 = alpha0.at[:, 1].set(first_lab)

        def step(alpha, lp_t):
            a_prev = alpha
            a_shift1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(can_skip, a_shift2, neg_inf)
            merged = jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1), a_shift2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        def scan_step(carry, t):
            alpha = carry
            new_alpha, _ = step(alpha, lp[t])
            # freeze beyond input length
            new_alpha = jnp.where((t < in_len)[:, None], new_alpha, alpha)
            return new_alpha, None

        alpha, _ = jax.lax.scan(scan_step, alpha0, jnp.arange(1, T))
        idx_last = ext_len - 1
        a1 = jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0]
        a2 = jnp.take_along_axis(alpha, jnp.maximum(idx_last - 1, 0)[:, None], axis=1)[:, 0]
        ll = jnp.logaddexp(a1, a2)
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(loss.dtype), 1.0))
        return _reduce(loss, reduction)

    return apply(fn, *args)


def square_error_cost(input, label):
    return apply(lambda a, b: (a - b) ** 2, _t(input), _t(label))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    args = [_t(logit), _t(label)]

    def fn(z, y):
        p = jax.nn.sigmoid(z)
        ce = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if normalizer is not None:
            nv = normalizer._data if isinstance(normalizer, Tensor) else normalizer
            loss = loss / nv
        return _reduce(loss, reduction)

    return apply(fn, *args)


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply(
        lambda p, y: -(y * jnp.log(p + epsilon) + (1 - y) * jnp.log(1 - p + epsilon)),
        _t(input), _t(label),
    )


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def fn(a, p, y):
        batch = a.shape[0]
        sim = a @ p.T
        y = y.reshape(-1, 1)
        tgt = (y == y.T).astype(sim.dtype)
        tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
        xent = -jnp.mean(jnp.sum(tgt * jax.nn.log_softmax(sim, axis=1), axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1)) + jnp.mean(jnp.sum(p * p, axis=1))) * 0.25
        return xent + reg * 2

    return apply(fn, _t(anchor), _t(positive), _t(labels).detach())


def hsigmoid_loss(input, label, num_classes, weight, bias=None, path_table=None,
                  path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid (hierarchical_sigmoid_op.cc parity).

    Default complete binary tree over `num_classes` leaves: class c's root-to-leaf
    path is read off the binary expansion of (c + num_classes) — node ids are the
    Huffman-style heap indices, codes are the branch bits. Custom trees pass
    path_table [N, L] (node ids, -1 padded) and path_code [N, L] (0/1 bits).
    TPU design: the whole path is gathered at once ([N, L, D] weight slices) and
    reduced — no per-node host loop; -1 padding is masked, not branched on.
    """
    x = _t(input)
    lab = _t(label).detach()
    w = _t(weight)
    args = [x, lab, w]
    if bias is not None:
        args.append(_t(bias))
    use_custom = path_table is not None
    if use_custom:
        args.append(_t(path_table).detach())
        args.append(_t(path_code).detach())

    max_depth = int(np.ceil(np.log2(max(num_classes, 2))))

    def fn(xv, labv, wv, *rest):
        rest = list(rest)
        bv = rest.pop(0) if bias is not None else None
        if use_custom:
            table, code = rest[0].astype(jnp.int32), rest[1]
            mask = (table >= 0).astype(xv.dtype)
            nodes = jnp.maximum(table, 0)
            bits = code.astype(xv.dtype)
        else:
            labi = labv.astype(jnp.int32).reshape(-1)
            # heap path of leaf (label + num_classes) in a complete binary tree:
            # ancestors top-down are (leaf >> d) for d = depth..1; branch bit is
            # the child's parity. Internal node i maps to weight row i - 1.
            leaf = labi + num_classes
            ds = jnp.arange(max_depth, 0, -1)
            anc = leaf[:, None] >> ds[None, :]            # [N, L] internal nodes
            child = leaf[:, None] >> (ds - 1)[None, :]
            mask = (anc >= 1).astype(xv.dtype)
            nodes = jnp.maximum(anc - 1, 0)               # weight row ids
            bits = (child & 1).astype(xv.dtype)
        wsel = wv[nodes]                                   # [N, L, D]
        logits = jnp.einsum("nld,nd->nl", wsel, xv)
        if bv is not None:
            logits = logits + bv.reshape(-1)[nodes]
        # sigmoid CE with target = bit, masked over padded path entries
        per_node = jnp.maximum(logits, 0) - logits * bits + jnp.log1p(
            jnp.exp(-jnp.abs(logits)))
        return jnp.sum(per_node * mask, axis=1, keepdims=True)

    return apply(fn, *args)


def hinge_loss(input, label, name=None):
    """hinge_loss_op.cc parity: max(0, 1 - (2*label - 1) * input)."""
    def fn(x, y):
        return jnp.maximum(0.0, 1.0 - (2.0 * y - 1.0) * x)

    return apply(fn, _t(input), _t(label).detach())


def rank_loss(label, left, right, name=None):
    """rank_loss_op.cc parity (RankNet): log(1 + e^(l-r)) - label*(l-r)."""
    def fn(y, l, r):
        d = l - r
        # stable softplus(d) - y*d
        return jnp.maximum(d, 0) + jnp.log1p(jnp.exp(-jnp.abs(d))) - y * d

    return apply(fn, _t(label).detach(), _t(left), _t(right))


def dice_loss(input, label, epsilon=1e-5, name=None):
    """dice_loss (fluid.layers.dice_loss parity): 1 - 2|X∩Y| / (|X|+|Y|).

    input [N, ..., C] probabilities, label [N, ..., 1] class ids; the label is
    one-hot encoded over the trailing class dim like the reference.
    """
    def fn(x, y):
        ids = jnp.squeeze(y, -1).astype(jnp.int32)
        oh = jax.nn.one_hot(ids, x.shape[-1], dtype=x.dtype)
        reduce_dims = tuple(range(1, x.ndim))
        inter = 2.0 * jnp.sum(x * oh, axis=reduce_dims)
        union = jnp.sum(x, axis=reduce_dims) + jnp.sum(oh, axis=reduce_dims)
        return jnp.mean(1.0 - inter / (union + epsilon))

    return apply(fn, _t(input), _t(label).detach())


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """teacher_student_sigmoid_loss_op.cc parity (CTR distillation). Label
    encodes (click z, teacher score z'): -2 -> (0, none), -1 -> (1, none),
    z' in [0,1) -> (0, z'), 1+z' -> (1, z'). Loss = softplus(x) - x*z
    [+ softplus(x) - x*z' when a teacher score exists] — branchless here."""
    def fn(x, y):
        # reference grad kernel clamps x to the soft_max bounds and zeroes dx
        # outside them; value-preserving clamp with clip's gradient
        xc = jnp.clip(x, soft_max_lower_bound, soft_max_up_bound)
        x = xc + jax.lax.stop_gradient(x - xc)
        sp = jnp.maximum(x, 0) + jnp.log1p(jnp.exp(-jnp.abs(x)))
        clk = (((y >= -1.0) & (y < 0.0)) | (y >= 1.0)).astype(x.dtype)
        has_teacher = (y >= 0.0).astype(x.dtype)
        zprime = y - (y >= 1.0).astype(x.dtype)
        return (sp - x * clk) + has_teacher * (sp - x * zprime)

    return apply(fn, _t(input), _t(label).detach())


def bpr_loss(input, label, name=None):
    """bpr_loss_op.h parity (Bayesian Personalized Ranking): per row,
    -mean over j != label of log(sigmoid(x[label] - x[j]))."""
    def fn(x, y):
        N, C = x.shape
        y = y.reshape(-1).astype(jnp.int32)
        pos = jnp.take_along_axis(x, y[:, None], axis=1)       # [N, 1]
        d = pos - x                                            # [N, C]
        # -log(sigmoid(d)) = softplus(-d)
        sp = jnp.maximum(-d, 0) + jnp.log1p(jnp.exp(-jnp.abs(d)))
        mask = jax.nn.one_hot(y, C, dtype=x.dtype)
        return (jnp.sum(sp * (1 - mask), axis=1) / (C - 1))[:, None]

    return apply(fn, _t(input), _t(label).detach())


def modified_huber_loss(input, label, name=None):
    """modified_huber_loss_op.h parity: v = x*(2y-1);
    loss = -4v if v < -1 else (1-v)^2 if v < 1 else 0."""
    def fn(x, y):
        v = x * (2.0 * y - 1.0)
        return jnp.where(v < -1.0, -4.0 * v,
                         jnp.where(v < 1.0, (1.0 - v) ** 2, 0.0))

    return apply(fn, _t(input), _t(label).detach())


def center_loss(input, label, num_classes, alpha, centers, update_center=True,
                name=None):
    """center_loss_op.h parity: loss = 0.5*||x - centers[label]||^2 per row;
    when update_center, centers[c] -= alpha * sum_{i:y=c}(centers[c]-x_i) /
    (1 + count_c). Returns (loss [N, 1], centers_out [num_classes, D])."""
    x = _t(input)
    lab = _t(label).detach()
    orig = _t(centers)
    # detached view: the reference CenterLossGradKernel emits no Centers grad
    # — centers move ONLY through the explicit alpha update below
    cen = orig.detach()

    def fn(xv, yv, cv):
        yv = yv.reshape(-1).astype(jnp.int32)
        sel = cv[yv]                                           # [N, D]
        diff = sel - xv
        loss = 0.5 * jnp.sum(diff * diff, axis=1, keepdims=True)
        cnt = jnp.zeros((num_classes,), xv.dtype).at[yv].add(1.0)
        acc = jnp.zeros_like(cv).at[yv].add(diff)
        new_c = cv - alpha * acc / (1.0 + cnt[:, None])
        return loss, new_c

    loss, new_centers = apply(fn, x, lab, cen)
    if update_center:
        orig._data = new_centers._data.astype(orig._data.dtype)
    return loss, new_centers


def nce(input, label, weight, bias=None, num_total_classes=None,
        num_neg_samples=10, sampler="uniform", custom_dist=None, seed=0,
        sample_weight=None, name=None):
    """nce_op.h parity (noise-contrastive estimation): o = sigmoid(w_c·x+b_c),
    noise mass b = k*P(c); cost = -log(o/(o+b)) for the true class and
    -log(b/(o+b)) for each sampled negative (:202-205). Negatives are drawn
    host-side with RandomState(seed) — the reference kernel reseeds its
    sampler from the `seed` attribute on every Compute, so a fixed seed
    yields the same draw per call there too. Under jit the draw happens at
    trace time (sample fresh per step by rebuilding the loss eagerly)."""
    x = _t(input)
    lab = _t(label).detach()
    w = _t(weight)
    R = num_total_classes if num_total_classes is not None else w.shape[0]
    B = x.shape[0]

    if isinstance(x._data, jax.core.Tracer):
        import warnings

        warnings.warn(
            "nce() called under a jit trace: negative samples are drawn "
            "host-side at trace time and BAKED into the compiled program — "
            "every step reuses the same negatives. Build the loss eagerly "
            "(or re-trace per epoch) to resample.", stacklevel=2)
    rng_ = np.random.RandomState(seed)  # lint: allow(np-random-in-traced-code) — warns under trace above
    if sampler == "uniform":
        neg = rng_.randint(0, R, size=(B, num_neg_samples))
        probs = np.full(R, 1.0 / R)
    elif sampler == "log_uniform":
        u = rng_.rand(B, num_neg_samples)
        neg = (np.exp(u * np.log(R + 1.0)) - 1.0).astype(np.int64) % R
        ranks = np.arange(R, dtype=np.float64)
        probs = (np.log((ranks + 2.0) / (ranks + 1.0))) / np.log(R + 1.0)
    elif sampler == "custom_dist":
        probs = np.asarray(custom_dist, np.float64)
        probs = probs / probs.sum()
        neg = np.stack([rng_.choice(R, size=num_neg_samples, p=probs)
                        for _ in range(B)])
    else:
        raise ValueError(f"unknown sampler {sampler}")
    probs_j = jnp.asarray(probs.astype(np.float32))
    neg_j = jnp.asarray(neg.astype(np.int32))

    args = [x, lab, w]
    if bias is not None:
        args.append(_t(bias))

    def fn(xv, yv, wv, *bb):
        yv = yv.reshape(-1).astype(jnp.int32)
        ids = jnp.concatenate([yv[:, None], neg_j], axis=1)   # [B, 1+k]
        logits = jnp.einsum("bkd,bd->bk", wv[ids], xv)
        if bb:
            logits = logits + bb[0].reshape(-1)[ids]
        o = jax.nn.sigmoid(logits)
        noise = num_neg_samples * probs_j[ids]
        cost_true = -jnp.log(o[:, :1] / (o[:, :1] + noise[:, :1]))
        cost_neg = -jnp.log(noise[:, 1:] / (o[:, 1:] + noise[:, 1:]))
        total = jnp.sum(cost_true, axis=1) + jnp.sum(cost_neg, axis=1)
        return total[:, None]

    return apply(fn, *args)
