"""Activation functionals.

Reference parity: python/paddle/nn/functional/activation.py backed by
operators/activation_op.cc. All map to jax.nn / jnp primitives; XLA fuses them into
surrounding matmuls (replacing operators/fused/fused_elemwise_activation_op.cc).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def relu(x, name=None):
    return apply(jax.nn.relu, _t(x))


def relu_(x, name=None):
    from ...core.dispatch import apply_inplace

    return apply_inplace(jax.nn.relu, x)


def relu6(x, name=None):
    return apply(jax.nn.relu6, _t(x))


def sigmoid(x, name=None):
    return apply(jax.nn.sigmoid, _t(x))


def tanh(x, name=None):
    return apply(jnp.tanh, _t(x))


def gelu(x, approximate=False, name=None):
    return apply(lambda v: jax.nn.gelu(v, approximate=approximate), _t(x))


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(lambda v: jax.nn.leaky_relu(v, negative_slope), _t(x))


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(v, w):
        if w.size == 1:
            return jnp.where(v >= 0, v, w.reshape(()) * v)
        ch_axis = 1 if data_format == "NCHW" else v.ndim - 1
        shape = [1] * v.ndim
        shape[ch_axis] = w.size
        return jnp.where(v >= 0, v, w.reshape(shape) * v)

    return apply(fn, _t(x), _t(weight))


def rrelu(x, lower=0.125, upper=0.333, training=False, name=None):
    mid = (lower + upper) / 2.0
    return leaky_relu(x, mid)


def elu(x, alpha=1.0, name=None):
    return apply(lambda v: jax.nn.elu(v, alpha), _t(x))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)), _t(x))


def celu(x, alpha=1.0, name=None):
    return apply(lambda v: jax.nn.celu(v, alpha), _t(x))


def silu(x, name=None):
    return apply(jax.nn.silu, _t(x))


def swish(x, name=None):
    return apply(jax.nn.silu, _t(x))


def mish(x, name=None):
    return apply(lambda v: v * jnp.tanh(jax.nn.softplus(v)), _t(x))


def hardswish(x, name=None):
    return apply(lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, _t(x))


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply(lambda v: jnp.clip(v * slope + offset, 0.0, 1.0), _t(x))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(lambda v: jnp.clip(v, min, max), _t(x))


def hardshrink(x, threshold=0.5, name=None):
    return apply(lambda v: jnp.where(jnp.abs(v) > threshold, v, jnp.zeros_like(v)), _t(x))


def softshrink(x, threshold=0.5, name=None):
    return apply(
        lambda v: jnp.where(v > threshold, v - threshold, jnp.where(v < -threshold, v + threshold, jnp.zeros_like(v))),
        _t(x),
    )


def tanhshrink(x, name=None):
    return apply(lambda v: v - jnp.tanh(v), _t(x))


def thresholded_relu(x, threshold=1.0, name=None):
    return apply(lambda v: jnp.where(v > threshold, v, jnp.zeros_like(v)), _t(x))


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(
        lambda v: jnp.where(v * beta > threshold, v, jax.nn.softplus(v * beta) / beta), _t(x)
    )


def softsign(x, name=None):
    return apply(jax.nn.soft_sign, _t(x))


def log_sigmoid(x, name=None):
    return apply(jax.nn.log_sigmoid, _t(x))


def softmax(x, axis=-1, dtype=None, name=None):
    from ...core import dtype as dtype_mod

    d = dtype_mod.convert_dtype(dtype)

    def fn(v):
        if d is not None:
            v = v.astype(d)
        return jax.nn.softmax(v, axis=axis)

    return apply(fn, _t(x))


def softmax_(x, axis=-1, dtype=None, name=None):
    from ...core.dispatch import apply_inplace

    return apply_inplace(lambda v: jax.nn.softmax(v, axis=axis), x)


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...core import dtype as dtype_mod

    d = dtype_mod.convert_dtype(dtype)

    def fn(v):
        if d is not None:
            v = v.astype(d)
        return jax.nn.log_softmax(v, axis=axis)

    return apply(fn, _t(x))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core.generator import default_generator

    key = default_generator().split()

    def fn(v):
        g = jax.random.gumbel(key, v.shape, dtype=v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
            y = y_hard - jax.lax.stop_gradient(y) + y
        return y

    return apply(fn, _t(x))


def maxout(x, groups, axis=1, name=None):
    def fn(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        new_shape = v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1 :]
        return jnp.max(v.reshape(new_shape), axis=ax + 1)

    return apply(fn, _t(x))


def glu(x, axis=-1, name=None):
    return apply(lambda v: jax.nn.glu(v, axis=axis), _t(x))


def tanh_(x, name=None):
    from ...core.dispatch import apply_inplace

    return apply_inplace(jnp.tanh, x)


def elu_(x, alpha=1.0, name=None):
    from ...core.dispatch import apply_inplace

    return apply_inplace(
        lambda v: jnp.where(v > 0, v, alpha * (jnp.exp(v) - 1)), _t(x))
