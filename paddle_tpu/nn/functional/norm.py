"""Normalization functionals.

Reference parity: python/paddle/nn/functional/norm.py backed by
operators/{batch_norm,layer_norm,instance_norm,group_norm}_op.cc.
BatchNorm keeps running stats on the host-side Layer (buffers); inside jit the update is
functional (new stats returned via buffer rebinding).
"""
import numpy as np
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-5,
    data_format="NCHW",
    use_global_stats=None,
    name=None,
):
    x = _t(x)
    ch_axis = x.ndim - 1 if data_format.endswith("C") and data_format != "NC" else 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]

    use_batch_stats = training and not use_global_stats

    if use_batch_stats:
        # compute batch stats eagerly; update running buffers (momentum convention:
        # running = momentum*running + (1-momentum)*batch, operators/batch_norm_op.cc)
        def stats(v):
            m = jnp.mean(v, axis=reduce_axes)
            var = jnp.var(v, axis=reduce_axes)
            return m, var

        m_t, v_t = apply(stats, x)
        running_mean._data = momentum * running_mean._data + (1 - momentum) * jnp.asarray(m_t._data, dtype=running_mean.dtype)
        running_var._data = momentum * running_var._data + (1 - momentum) * jnp.asarray(v_t._data, dtype=running_var.dtype)

        def fn(v, m, var, *wb):
            out = (v - m.reshape(shape)) / jnp.sqrt(var.reshape(shape) + epsilon)
            if len(wb) >= 1:
                out = out * wb[0].reshape(shape)
            if len(wb) == 2:
                out = out + wb[1].reshape(shape)
            return out

        args = [x, m_t, v_t]
    else:
        def fn(v, m, var, *wb):
            out = (v - m.reshape(shape)) / jnp.sqrt(var.reshape(shape) + epsilon)
            if len(wb) >= 1:
                out = out * wb[0].reshape(shape)
            if len(wb) == 2:
                out = out + wb[1].reshape(shape)
            return out

        args = [x, _t(running_mean).detach(), _t(running_var).detach()]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    return apply(fn, *args)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n = len(normalized_shape)

    def fn(v, *wb):
        axes = tuple(range(v.ndim - n, v.ndim))
        m = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - m) / jnp.sqrt(var + epsilon)
        if len(wb) >= 1:
            out = out * wb[0]
        if len(wb) == 2:
            out = out + wb[1]
        return out

    args = [_t(x)]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    return apply(fn, *args)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None, use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    def fn(v, *wb):
        axes = tuple(range(2, v.ndim))
        m = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - m) / jnp.sqrt(var + eps)
        c = v.shape[1]
        shape = [1, c] + [1] * (v.ndim - 2)
        if len(wb) >= 1:
            out = out * wb[0].reshape(shape)
        if len(wb) == 2:
            out = out + wb[1].reshape(shape)
        return out

    args = [_t(x)]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    return apply(fn, *args)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None, data_format="NCHW", name=None):
    def fn(v, *wb):
        b, c = v.shape[0], v.shape[1]
        spatial = v.shape[2:]
        g = v.reshape((b, num_groups, c // num_groups) + spatial)
        axes = tuple(range(2, g.ndim))
        m = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - m) / jnp.sqrt(var + epsilon)).reshape(v.shape)
        shape = [1, c] + [1] * (v.ndim - 2)
        if len(wb) >= 1:
            out = out * wb[0].reshape(shape)
        if len(wb) == 2:
            out = out + wb[1].reshape(shape)
        return out

    args = [_t(x)]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    return apply(fn, *args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def fn(v):
        sq = v * v
        half = size // 2
        c = v.shape[1]
        pad_width = [(0, 0)] * v.ndim
        pad_width[1] = (half, size - half - 1)
        padded = jnp.pad(sq, pad_width)
        acc = jnp.zeros_like(v)
        for i in range(size):
            acc = acc + jnp.take(padded, jnp.arange(i, i + c), axis=1)
        return v / jnp.power(k + alpha * acc / size, beta)

    return apply(fn, _t(x))


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(v):
        norm_v = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(norm_v, epsilon)

    return apply(fn, _t(x))
