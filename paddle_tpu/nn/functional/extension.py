"""Extension functionals (python/paddle/nn/functional/extension.py + vision.py parity):
sequence_mask, temporal_shift, affine_grid, grid_sample, diag_embed."""
import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ...core import dtype as dtype_mod

    x = _t(x)
    ml = maxlen if maxlen is not None else int(np.asarray(x._data).max())
    d = dtype_mod.convert_dtype(dtype)

    def fn(v):
        rng = jnp.arange(ml)
        return (rng[None, :] < v[..., None]).astype(d)

    out = apply(fn, x.detach())
    out.stop_gradient = True
    return out


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def fn(v):
        nt, c, h, w = v.shape
        n = nt // seg_num
        v = v.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(v[:, :1, fold : 2 * fold]), v[:, :-1, fold : 2 * fold]], axis=1)
        rest = v[:, :, 2 * fold :]
        return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)

    return apply(fn, _t(x))


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    def fn(v):
        n = v.shape[-1]
        size = n + abs(offset)
        out = jnp.zeros(v.shape[:-1] + (size, size), dtype=v.dtype)
        idx = jnp.arange(n)
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out.at[..., r, c].set(v)
        if dim1 != -2 or dim2 != -1:
            out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
        return out

    return apply(fn, _t(input))


def affine_grid(theta, out_shape, align_corners=True, name=None):
    if isinstance(out_shape, Tensor):
        out_shape = out_shape.tolist()
    n, c, h, w = [int(s) for s in out_shape]

    def fn(th):
        if align_corners:
            ys = jnp.linspace(-1, 1, h)
            xs = jnp.linspace(-1, 1, w)
        else:
            ys = (jnp.arange(h) + 0.5) / h * 2 - 1
            xs = (jnp.arange(w) + 0.5) / w * 2 - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # [h*w, 3]
        out = jnp.einsum("nij,kj->nki", th, base)  # [n, h*w, 2]
        return out.reshape(n, h, w, 2)

    return apply(fn, _t(theta))


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    def fn(v, g):
        n, c, h, w = v.shape
        gx = g[..., 0]
        gy = g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def sample(ix, iy):
            ixc = jnp.clip(ix, 0, w - 1)
            iyc = jnp.clip(iy, 0, h - 1)
            val = v[jnp.arange(n)[:, None, None], :, iyc, ixc]  # [n, gh, gw, c]
            if padding_mode == "zeros":
                ok = ((ix >= 0) & (ix <= w - 1) & (iy >= 0) & (iy <= h - 1))[..., None]
                val = val * ok.astype(val.dtype)
            return val

        if mode == "nearest":
            out = sample(jnp.round(fx).astype(jnp.int32), jnp.round(fy).astype(jnp.int32))
        else:
            x0 = jnp.floor(fx).astype(jnp.int32)
            y0 = jnp.floor(fy).astype(jnp.int32)
            x1, y1 = x0 + 1, y0 + 1
            wx = fx - x0
            wy = fy - y0
            v00 = sample(x0, y0)
            v01 = sample(x1, y0)
            v10 = sample(x0, y1)
            v11 = sample(x1, y1)
            wx = wx[..., None]
            wy = wy[..., None]
            out = (
                v00 * (1 - wx) * (1 - wy)
                + v01 * wx * (1 - wy)
                + v10 * (1 - wx) * wy
                + v11 * wx * wy
            )
        return jnp.moveaxis(out, -1, 1)  # [n, c, gh, gw]

    return apply(fn, _t(x), _t(grid))


def npu_identity(x, op_flag=0):
    return _t(x)


def sequence_pad(x, pad_value, maxlen=None, name=None):
    """Pad a list of variable-length sequences (list of Tensors / arrays) into a
    dense [batch, maxlen, ...] tensor + a length vector. TPU-native stance on
    LoDTensor (reference framework/lod_tensor.h:114, operators/sequence_ops/
    sequence_pad_op.cc): ragged sequences live only at the data boundary; inside
    the framework everything is dense + mask."""

    seqs = [np.asarray(s._data if hasattr(s, "_data") else s) for s in x]
    lens = np.array([s.shape[0] for s in seqs], dtype=np.int64)
    ml = int(maxlen) if maxlen is not None else int(lens.max())
    pv = np.asarray(pad_value._data if hasattr(pad_value, "_data") else pad_value)
    trailing = seqs[0].shape[1:]
    out = np.full((len(seqs), ml) + trailing, pv, dtype=seqs[0].dtype)
    for i, s in enumerate(seqs):
        n = min(s.shape[0], ml)
        out[i, :n] = s[:n]
    from ...core.tensor import Tensor

    return Tensor(out), Tensor(np.minimum(lens, ml))


def sequence_unpad(x, length, name=None):
    """Inverse of sequence_pad: dense [batch, maxlen, ...] -> list of Tensors."""
    from ...core.tensor import Tensor

    data = np.asarray(x._data if hasattr(x, "_data") else x)
    lens = np.asarray(length._data if hasattr(length, "_data") else length)
    return [Tensor(data[i, : int(lens[i])]) for i in range(data.shape[0])]


def gather_tree(ids, parents):
    """Beam-search backtrace (reference operators/gather_tree_op.cc): walk parent
    pointers from the last step to recover full beams. Shapes [max_time, batch, beam]."""

    def fn(idv, parv):
        max_time = idv.shape[0]

        def step(parent, t):
            tt = max_time - 1 - t
            row = jnp.take_along_axis(idv[tt], parent, axis=-1)
            nxt = jnp.take_along_axis(parv[tt], parent, axis=-1)
            return nxt, row

        init_parent = jnp.broadcast_to(
            jnp.arange(idv.shape[2], dtype=idv.dtype), idv.shape[1:]
        )
        _, rows = jax.lax.scan(step, init_parent, jnp.arange(max_time))
        return rows[::-1]

    return apply(fn, _t(ids), _t(parents))
