"""Extension functionals (python/paddle/nn/functional/extension.py + vision.py parity):
sequence_mask, temporal_shift, affine_grid, grid_sample, diag_embed."""
import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ...core import dtype as dtype_mod

    x = _t(x)
    ml = maxlen if maxlen is not None else int(np.asarray(x._data).max())
    d = dtype_mod.convert_dtype(dtype)

    def fn(v):
        rng = jnp.arange(ml)
        return (rng[None, :] < v[..., None]).astype(d)

    out = apply(fn, x.detach())
    out.stop_gradient = True
    return out


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def fn(v):
        nt, c, h, w = v.shape
        n = nt // seg_num
        v = v.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(v[:, :1, fold : 2 * fold]), v[:, :-1, fold : 2 * fold]], axis=1)
        rest = v[:, :, 2 * fold :]
        return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)

    return apply(fn, _t(x))


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    def fn(v):
        n = v.shape[-1]
        size = n + abs(offset)
        out = jnp.zeros(v.shape[:-1] + (size, size), dtype=v.dtype)
        idx = jnp.arange(n)
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out.at[..., r, c].set(v)
        if dim1 != -2 or dim2 != -1:
            out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
        return out

    return apply(fn, _t(input))


def affine_grid(theta, out_shape, align_corners=True, name=None):
    if isinstance(out_shape, Tensor):
        out_shape = out_shape.tolist()
    n, c, h, w = [int(s) for s in out_shape]

    def fn(th):
        if align_corners:
            ys = jnp.linspace(-1, 1, h)
            xs = jnp.linspace(-1, 1, w)
        else:
            ys = (jnp.arange(h) + 0.5) / h * 2 - 1
            xs = (jnp.arange(w) + 0.5) / w * 2 - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # [h*w, 3]
        out = jnp.einsum("nij,kj->nki", th, base)  # [n, h*w, 2]
        return out.reshape(n, h, w, 2)

    return apply(fn, _t(theta))


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    def fn(v, g):
        n, c, h, w = v.shape
        gx = g[..., 0]
        gy = g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def sample(ix, iy):
            ixc = jnp.clip(ix, 0, w - 1)
            iyc = jnp.clip(iy, 0, h - 1)
            val = v[jnp.arange(n)[:, None, None], :, iyc, ixc]  # [n, gh, gw, c]
            if padding_mode == "zeros":
                ok = ((ix >= 0) & (ix <= w - 1) & (iy >= 0) & (iy <= h - 1))[..., None]
                val = val * ok.astype(val.dtype)
            return val

        if mode == "nearest":
            out = sample(jnp.round(fx).astype(jnp.int32), jnp.round(fy).astype(jnp.int32))
        else:
            x0 = jnp.floor(fx).astype(jnp.int32)
            y0 = jnp.floor(fy).astype(jnp.int32)
            x1, y1 = x0 + 1, y0 + 1
            wx = fx - x0
            wy = fy - y0
            v00 = sample(x0, y0)
            v01 = sample(x1, y0)
            v10 = sample(x0, y1)
            v11 = sample(x1, y1)
            wx = wx[..., None]
            wy = wy[..., None]
            out = (
                v00 * (1 - wx) * (1 - wy)
                + v01 * wx * (1 - wy)
                + v10 * (1 - wx) * wy
                + v11 * wx * wy
            )
        return jnp.moveaxis(out, -1, 1)  # [n, c, gh, gw]

    return apply(fn, _t(x), _t(grid))


def npu_identity(x, op_flag=0):
    return _t(x)


def sequence_pad(x, pad_value, maxlen=None, name=None):
    """Pad a list of variable-length sequences (list of Tensors / arrays) into a
    dense [batch, maxlen, ...] tensor + a length vector. TPU-native stance on
    LoDTensor (reference framework/lod_tensor.h:114, operators/sequence_ops/
    sequence_pad_op.cc): ragged sequences live only at the data boundary; inside
    the framework everything is dense + mask."""

    seqs = [np.asarray(s._data if hasattr(s, "_data") else s) for s in x]
    lens = np.array([s.shape[0] for s in seqs], dtype=np.int64)
    ml = int(maxlen) if maxlen is not None else int(lens.max())
    pv = np.asarray(pad_value._data if hasattr(pad_value, "_data") else pad_value)
    trailing = seqs[0].shape[1:]
    out = np.full((len(seqs), ml) + trailing, pv, dtype=seqs[0].dtype)
    for i, s in enumerate(seqs):
        n = min(s.shape[0], ml)
        out[i, :n] = s[:n]
    from ...core.tensor import Tensor

    return Tensor(out), Tensor(np.minimum(lens, ml))


def sequence_unpad(x, length, name=None):
    """Inverse of sequence_pad: dense [batch, maxlen, ...] -> list of Tensors."""
    from ...core.tensor import Tensor

    data = np.asarray(x._data if hasattr(x, "_data") else x)
    lens = np.asarray(length._data if hasattr(length, "_data") else length)
    return [Tensor(data[i, : int(lens[i])]) for i in range(data.shape[0])]


def gather_tree(ids, parents):
    """Beam-search backtrace (reference operators/gather_tree_op.cc): walk parent
    pointers from the last step to recover full beams. Shapes [max_time, batch, beam]."""

    def fn(idv, parv):
        max_time = idv.shape[0]

        def step(parent, t):
            tt = max_time - 1 - t
            row = jnp.take_along_axis(idv[tt], parent, axis=-1)
            nxt = jnp.take_along_axis(parv[tt], parent, axis=-1)
            return nxt, row

        init_parent = jnp.broadcast_to(
            jnp.arange(idv.shape[2], dtype=idv.dtype), idv.shape[1:]
        )
        _, rows = jax.lax.scan(step, init_parent, jnp.arange(max_time))
        return rows[::-1]

    return apply(fn, _t(ids), _t(parents))


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Batched Levenshtein distance (reference operators/edit_distance_op.cc).

    input/label: [B, Th]/[B, Tr] padded int token tensors with
    input_length/label_length [B]; without lengths the full padded rows count.
    TPU design: one lax.scan over hypothesis positions carrying the whole
    [B, Tr+1] DP row — batch and reference dims stay vectorized; ignored
    tokens are compacted out host-side (they change sequence lengths).
    Returns (distances [B, 1] float32, sequence_num [1] int64).
    """
    hyp = np.asarray(_t(input)._data)
    ref = np.asarray(_t(label)._data)
    hl = (np.asarray(_t(input_length)._data) if input_length is not None
          else np.full(hyp.shape[0], hyp.shape[1]))
    rl = (np.asarray(_t(label_length)._data) if label_length is not None
          else np.full(ref.shape[0], ref.shape[1]))
    if ignored_tokens:
        ig = set(int(t) for t in np.atleast_1d(ignored_tokens))

        def compact(mat, lens):
            out = np.zeros_like(mat)
            new_lens = np.zeros_like(lens)
            for i in range(mat.shape[0]):
                row = [t for t in mat[i, : int(lens[i])] if int(t) not in ig]
                out[i, : len(row)] = row
                new_lens[i] = len(row)
            return out, new_lens

        hyp, hl = compact(hyp, hl)
        ref, rl = compact(ref, rl)

    B, Th = hyp.shape
    Tr = ref.shape[1]
    hyp_j = jnp.asarray(hyp)
    ref_j = jnp.asarray(ref)
    hl_j = jnp.asarray(hl.astype(np.int32))
    rl_j = jnp.asarray(rl.astype(np.int32))

    def fn(hv, rv, hlen, rlen):
        cols = jnp.arange(Tr + 1, dtype=jnp.float32)
        # dp row for 0 hyp tokens: distance = min(j, rlen) capped at valid region
        row0 = jnp.broadcast_to(cols, (B, Tr + 1))

        def step(row, i):
            # new_row[0] = i+1
            sub_cost = (hv[:, i][:, None] != rv).astype(jnp.float32)  # [B, Tr]
            # scan over columns is inherent to Levenshtein; do the standard
            # trick: new[j] = min(row[j]+1, new[j-1]+1, row[j-1]+cost) needs the
            # sequential new[j-1]; use associative min-plus prefix instead:
            # new[j] >= min over k<=j of (base[k] + (j-k)) where
            # base[k] = min(row[k]+1 [del], row[k-1]+cost[k] [sub]) at column k
            del_or_sub = jnp.minimum(row[:, 1:] + 1.0, row[:, :-1] + sub_cost)
            base = jnp.concatenate(
                [jnp.full((B, 1), i + 1.0), del_or_sub], axis=1)  # [B, Tr+1]
            # prefix min of (base[k] - k), then add j  == min-plus with ins cost
            shifted = base - cols[None, :]
            prefix = jax.lax.associative_scan(jnp.minimum, shifted, axis=1)
            new_row = prefix + cols[None, :]
            keep = (i < hlen)[:, None]
            return jnp.where(keep, new_row, row), None

        row_final, _ = jax.lax.scan(step, row0, jnp.arange(Th))
        dist = jnp.take_along_axis(row_final, rlen[:, None].astype(jnp.int32),
                                   axis=1)[:, 0]
        if normalized:
            dist = dist / jnp.maximum(rlen.astype(jnp.float32), 1.0)
        return dist[:, None]

    out = apply(fn, Tensor(hyp_j).detach(), Tensor(ref_j).detach(),
                Tensor(hl_j).detach(), Tensor(rl_j).detach())
    out.stop_gradient = True
    from ...core.tensor import Tensor as _T

    return out, _T(jnp.asarray([B], dtype=jnp.int64))


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    """channel_shuffle_op parity: regroup channels [N, g*c, H, W] ->
    interleave across groups (transpose trick)."""
    def fn(v):
        if data_format == "NCHW":
            n, ch, h, w = v.shape
            v = v.reshape(n, groups, ch // groups, h, w)
            v = jnp.swapaxes(v, 1, 2)
            return v.reshape(n, ch, h, w)
        n, h, w, ch = v.shape
        v = v.reshape(n, h, w, groups, ch // groups)
        v = jnp.swapaxes(v, 3, 4)
        return v.reshape(n, h, w, ch)

    return apply(fn, _t(x))
