"""Extension functionals (python/paddle/nn/functional/extension.py + vision.py parity):
sequence_mask, temporal_shift, affine_grid, grid_sample, diag_embed."""
import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ...core import dtype as dtype_mod

    x = _t(x)
    ml = maxlen if maxlen is not None else int(np.asarray(x._data).max())
    d = dtype_mod.convert_dtype(dtype)

    def fn(v):
        rng = jnp.arange(ml)
        return (rng[None, :] < v[..., None]).astype(d)

    out = apply(fn, x.detach())
    out.stop_gradient = True
    return out


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def fn(v):
        nt, c, h, w = v.shape
        n = nt // seg_num
        v = v.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(v[:, :1, fold : 2 * fold]), v[:, :-1, fold : 2 * fold]], axis=1)
        rest = v[:, :, 2 * fold :]
        return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)

    return apply(fn, _t(x))


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    def fn(v):
        n = v.shape[-1]
        size = n + abs(offset)
        out = jnp.zeros(v.shape[:-1] + (size, size), dtype=v.dtype)
        idx = jnp.arange(n)
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out.at[..., r, c].set(v)
        if dim1 != -2 or dim2 != -1:
            out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
        return out

    return apply(fn, _t(input))


def affine_grid(theta, out_shape, align_corners=True, name=None):
    if isinstance(out_shape, Tensor):
        out_shape = out_shape.tolist()
    n, c, h, w = [int(s) for s in out_shape]

    def fn(th):
        if align_corners:
            ys = jnp.linspace(-1, 1, h)
            xs = jnp.linspace(-1, 1, w)
        else:
            ys = (jnp.arange(h) + 0.5) / h * 2 - 1
            xs = (jnp.arange(w) + 0.5) / w * 2 - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # [h*w, 3]
        out = jnp.einsum("nij,kj->nki", th, base)  # [n, h*w, 2]
        return out.reshape(n, h, w, 2)

    return apply(fn, _t(theta))


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    def fn(v, g):
        n, c, h, w = v.shape
        gx = g[..., 0]
        gy = g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def sample(ix, iy):
            ixc = jnp.clip(ix, 0, w - 1)
            iyc = jnp.clip(iy, 0, h - 1)
            val = v[jnp.arange(n)[:, None, None], :, iyc, ixc]  # [n, gh, gw, c]
            if padding_mode == "zeros":
                ok = ((ix >= 0) & (ix <= w - 1) & (iy >= 0) & (iy <= h - 1))[..., None]
                val = val * ok.astype(val.dtype)
            return val

        if mode == "nearest":
            out = sample(jnp.round(fx).astype(jnp.int32), jnp.round(fy).astype(jnp.int32))
        else:
            x0 = jnp.floor(fx).astype(jnp.int32)
            y0 = jnp.floor(fy).astype(jnp.int32)
            x1, y1 = x0 + 1, y0 + 1
            wx = fx - x0
            wy = fy - y0
            v00 = sample(x0, y0)
            v01 = sample(x1, y0)
            v10 = sample(x0, y1)
            v11 = sample(x1, y1)
            wx = wx[..., None]
            wy = wy[..., None]
            out = (
                v00 * (1 - wx) * (1 - wy)
                + v01 * wx * (1 - wy)
                + v10 * (1 - wx) * wy
                + v11 * wx * wy
            )
        return jnp.moveaxis(out, -1, 1)  # [n, c, gh, gw]

    return apply(fn, _t(x), _t(grid))


def npu_identity(x, op_flag=0):
    return _t(x)


def sequence_pad(x, pad_value, maxlen=None, name=None):
    """Pad a list of variable-length sequences (list of Tensors / arrays) into a
    dense [batch, maxlen, ...] tensor + a length vector. TPU-native stance on
    LoDTensor (reference framework/lod_tensor.h:114, operators/sequence_ops/
    sequence_pad_op.cc): ragged sequences live only at the data boundary; inside
    the framework everything is dense + mask."""

    seqs = [np.asarray(s._data if hasattr(s, "_data") else s) for s in x]
    lens = np.array([s.shape[0] for s in seqs], dtype=np.int64)
    ml = int(maxlen) if maxlen is not None else int(lens.max())
    pv = np.asarray(pad_value._data if hasattr(pad_value, "_data") else pad_value)
    trailing = seqs[0].shape[1:]
    out = np.full((len(seqs), ml) + trailing, pv, dtype=seqs[0].dtype)
    for i, s in enumerate(seqs):
        n = min(s.shape[0], ml)
        out[i, :n] = s[:n]
    from ...core.tensor import Tensor

    return Tensor(out), Tensor(np.minimum(lens, ml))


def sequence_unpad(x, length, name=None):
    """Inverse of sequence_pad: dense [batch, maxlen, ...] -> list of Tensors."""
    from ...core.tensor import Tensor

    data = np.asarray(x._data if hasattr(x, "_data") else x)
    lens = np.asarray(length._data if hasattr(length, "_data") else length)
    return [Tensor(data[i, : int(lens[i])]) for i in range(data.shape[0])]


def gather_tree(ids, parents):
    """Beam-search backtrace (reference operators/gather_tree_op.cc): walk parent
    pointers from the last step to recover full beams. Shapes [max_time, batch, beam]."""

    def fn(idv, parv):
        max_time = idv.shape[0]

        def step(parent, t):
            tt = max_time - 1 - t
            row = jnp.take_along_axis(idv[tt], parent, axis=-1)
            nxt = jnp.take_along_axis(parv[tt], parent, axis=-1)
            return nxt, row

        init_parent = jnp.broadcast_to(
            jnp.arange(idv.shape[2], dtype=idv.dtype), idv.shape[1:]
        )
        _, rows = jax.lax.scan(step, init_parent, jnp.arange(max_time))
        return rows[::-1]

    return apply(fn, _t(ids), _t(parents))


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Batched Levenshtein distance (reference operators/edit_distance_op.cc).

    input/label: [B, Th]/[B, Tr] padded int token tensors with
    input_length/label_length [B]; without lengths the full padded rows count.
    TPU design: one lax.scan over hypothesis positions carrying the whole
    [B, Tr+1] DP row — batch and reference dims stay vectorized; ignored
    tokens are compacted out host-side (they change sequence lengths).
    Returns (distances [B, 1] float32, sequence_num [1] int64).
    """
    hyp = np.asarray(_t(input)._data)
    ref = np.asarray(_t(label)._data)
    hl = (np.asarray(_t(input_length)._data) if input_length is not None
          else np.full(hyp.shape[0], hyp.shape[1]))
    rl = (np.asarray(_t(label_length)._data) if label_length is not None
          else np.full(ref.shape[0], ref.shape[1]))
    if ignored_tokens:
        ig = set(int(t) for t in np.atleast_1d(ignored_tokens))

        def compact(mat, lens):
            out = np.zeros_like(mat)
            new_lens = np.zeros_like(lens)
            for i in range(mat.shape[0]):
                row = [t for t in mat[i, : int(lens[i])] if int(t) not in ig]
                out[i, : len(row)] = row
                new_lens[i] = len(row)
            return out, new_lens

        hyp, hl = compact(hyp, hl)
        ref, rl = compact(ref, rl)

    B, Th = hyp.shape
    Tr = ref.shape[1]
    hyp_j = jnp.asarray(hyp)
    ref_j = jnp.asarray(ref)
    hl_j = jnp.asarray(hl.astype(np.int32))
    rl_j = jnp.asarray(rl.astype(np.int32))

    def fn(hv, rv, hlen, rlen):
        cols = jnp.arange(Tr + 1, dtype=jnp.float32)
        # dp row for 0 hyp tokens: distance = min(j, rlen) capped at valid region
        row0 = jnp.broadcast_to(cols, (B, Tr + 1))

        def step(row, i):
            # new_row[0] = i+1
            sub_cost = (hv[:, i][:, None] != rv).astype(jnp.float32)  # [B, Tr]
            # scan over columns is inherent to Levenshtein; do the standard
            # trick: new[j] = min(row[j]+1, new[j-1]+1, row[j-1]+cost) needs the
            # sequential new[j-1]; use associative min-plus prefix instead:
            # new[j] >= min over k<=j of (base[k] + (j-k)) where
            # base[k] = min(row[k]+1 [del], row[k-1]+cost[k] [sub]) at column k
            del_or_sub = jnp.minimum(row[:, 1:] + 1.0, row[:, :-1] + sub_cost)
            base = jnp.concatenate(
                [jnp.full((B, 1), i + 1.0), del_or_sub], axis=1)  # [B, Tr+1]
            # prefix min of (base[k] - k), then add j  == min-plus with ins cost
            shifted = base - cols[None, :]
            prefix = jax.lax.associative_scan(jnp.minimum, shifted, axis=1)
            new_row = prefix + cols[None, :]
            keep = (i < hlen)[:, None]
            return jnp.where(keep, new_row, row), None

        row_final, _ = jax.lax.scan(step, row0, jnp.arange(Th))
        dist = jnp.take_along_axis(row_final, rlen[:, None].astype(jnp.int32),
                                   axis=1)[:, 0]
        if normalized:
            dist = dist / jnp.maximum(rlen.astype(jnp.float32), 1.0)
        return dist[:, None]

    out = apply(fn, Tensor(hyp_j).detach(), Tensor(ref_j).detach(),
                Tensor(hl_j).detach(), Tensor(rl_j).detach())
    out.stop_gradient = True
    from ...core.tensor import Tensor as _T

    return out, _T(jnp.asarray([B], dtype=jnp.int64))


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    """channel_shuffle_op parity: regroup channels [N, g*c, H, W] ->
    interleave across groups (transpose trick)."""
    def fn(v):
        if data_format == "NCHW":
            n, ch, h, w = v.shape
            v = v.reshape(n, groups, ch // groups, h, w)
            v = jnp.swapaxes(v, 1, 2)
            return v.reshape(n, ch, h, w)
        n, h, w, ch = v.shape
        v = v.reshape(n, h, w, groups, ch // groups)
        v = jnp.swapaxes(v, 3, 4)
        return v.reshape(n, h, w, ch)

    return apply(fn, _t(x))


def l1_norm(x, name=None):
    """l1_norm_op.cc parity: sum of absolute values (scalar)."""
    return apply(lambda v: jnp.sum(jnp.abs(v)), _t(x))


def squared_l2_norm(x, name=None):
    """squared_l2_norm_op.cc parity: sum of squares (scalar)."""
    return apply(lambda v: jnp.sum(v * v), _t(x))


def cos_sim(x, y, name=None):
    """cos_sim_op.cc parity: per-row cosine similarity [N, 1] (y may be a
    single row broadcast against every row of x)."""
    def fn(a, b):
        if b.shape[0] == 1 and a.shape[0] != 1:
            b = jnp.broadcast_to(b, a.shape)
        num = jnp.sum(a * b, axis=-1)
        den = jnp.sqrt(jnp.sum(a * a, axis=-1)) * jnp.sqrt(jnp.sum(b * b, axis=-1))
        return (num / jnp.maximum(den, 1e-12))[:, None]

    return apply(fn, _t(x), _t(y))


def space_to_depth(x, blocksize, name=None):
    """space_to_depth_op.cc parity: [N, C, H, W] -> [N, C*b*b, H/b, W/b]."""
    def fn(v):
        n, c, h, w = v.shape
        b = blocksize
        v = v.reshape(n, c, h // b, b, w // b, b)
        v = jnp.transpose(v, (0, 3, 5, 1, 2, 4))
        return v.reshape(n, c * b * b, h // b, w // b)

    return apply(fn, _t(x))


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """pad_constant_like_op.cc parity: pad y up to x's shape with pad_value."""
    def fn(xv, yv):
        pads = [(0, xv.shape[i] - yv.shape[i]) for i in range(yv.ndim)]
        return jnp.pad(yv, pads, constant_values=pad_value)

    return apply(fn, _t(x).detach(), _t(y))


def add_position_encoding(x, alpha=1.0, beta=1.0, name=None):
    """add_position_encoding_op.cc parity: out = alpha*x + beta*PE with the
    transformer sinusoid table (first half sin, second half cos)."""
    def fn(v):
        B, T, D = v.shape
        half = D // 2
        pos = jnp.arange(T, dtype=jnp.float32)[:, None]
        div = jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32) / half)
        ang = pos / div[None, :]
        pe = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=1)
        if pe.shape[1] < D:
            pe = jnp.pad(pe, ((0, 0), (0, D - pe.shape[1])))
        return alpha * v + beta * pe[None, :, :].astype(v.dtype)

    return apply(fn, _t(x))


def bilinear_tensor_product(x, y, weight, bias=None, name=None):
    """bilinear_tensor_product_op.cc parity: out[:, k] = x W_k y^T.
    x [N, D1], y [N, D2], weight [K, D1, D2] -> [N, K]."""
    args = [_t(x), _t(y), _t(weight)]
    if bias is not None:
        args.append(_t(bias))

    def fn(a, b, w, *bb):
        out = jnp.einsum("nd,kde,ne->nk", a, w, b)
        if bb:
            out = out + bb[0]
        return out

    return apply(fn, *args)


def conv_shift(x, y, name=None):
    """conv_shift_op.cc parity (NTM circular correlation): x [B, M], y [B, N]
    (N odd, N <= M): out[b, i] = sum_j x[b, (i + j - N//2) mod M] * y[b, j]."""
    def fn(a, b):
        B, M = a.shape
        N = b.shape[1]
        half = N // 2
        idx = (jnp.arange(M)[:, None] + jnp.arange(N)[None, :] - half) % M
        ax = a[:, idx]                                      # [B, M, N]
        return jnp.einsum("bmn,bn->bm", ax, b)

    return apply(fn, _t(x), _t(y))


def row_conv(x, weight, length=None, name=None):
    """row_conv_op.cc parity (Deep Speech lookahead conv): x [B, T, D],
    weight [future_context, D]: out[t] = sum_c w[c] * x[t + c] (zero past the
    end / sequence length)."""
    def fn(v, w, *rest):
        B, T, D = v.shape
        ctx = w.shape[0]
        ln = rest[0].astype(jnp.int32) if rest else jnp.full((B,), T, jnp.int32)
        valid = jnp.arange(T)[None, :] < ln[:, None]
        out = jnp.zeros_like(v)
        for c in range(ctx):
            pos = jnp.arange(T) + c
            inb = pos < T
            src = jnp.clip(pos, 0, T - 1).astype(jnp.int32)
            tap = v[:, src] * (inb[None, :] & jnp.take(valid, src, axis=1))[:, :, None]
            out = out + tap * w[c][None, None, :]
        return out * valid[:, :, None]

    args = [_t(x), _t(weight)]
    if length is not None:
        args.append(_t(length).detach())
    return apply(fn, *args)


def sampling_id(x, min=0.0, max=1.0, seed=0, name=None):
    """sampling_id_op.cc parity: sample a column index per row of the
    probability matrix x [B, C] (inverse-CDF on uniform draws)."""
    from ...core.generator import default_generator

    # seed=0 means fresh randomness per call (reference semantics); a nonzero
    # seed is deterministic
    key = (default_generator().split() if not seed
           else default_generator().fold_in(seed))

    def fn(v):
        u = jax.random.uniform(key, (v.shape[0], 1), dtype=v.dtype)
        cdf = jnp.cumsum(v, axis=1) / jnp.sum(v, axis=1, keepdims=True)
        return jnp.sum((u > cdf).astype(jnp.int64), axis=1)

    out = apply(fn, _t(x).detach())
    out.stop_gradient = True
    return out


def partial_concat(xs, start_index=0, length=-1, name=None):
    """partial_concat_op.cc parity: concat the [start, start+length) column
    slice of each [B, D] input."""
    def fn(*vs):
        outs = []
        for v in vs:
            start = start_index if start_index >= 0 else v.shape[1] + start_index
            end = v.shape[1] if length < 0 else start + length
            outs.append(v[:, start:end])
        return jnp.concatenate(outs, axis=1)

    return apply(fn, *[_t(x) for x in xs])


def partial_sum(xs, start_index=0, length=-1, name=None):
    """partial_sum_op.cc parity: elementwise sum of the column slices."""
    def fn(*vs):
        acc = None
        for v in vs:
            start = start_index if start_index >= 0 else v.shape[1] + start_index
            end = v.shape[1] if length < 0 else start + length
            sl = v[:, start:end]
            acc = sl if acc is None else acc + sl
        return acc

    return apply(fn, *[_t(x) for x in xs])


def im2sequence(x, filter_size=1, stride=1, padding=0, name=None):
    """im2sequence_op.cc parity: [N, C, H, W] -> patch rows
    [N, oh*ow, C*fh*fw] (per-image patch sequence; LoD -> fixed oh*ow rows)."""
    from .common import _norm_pad4

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    fh, fw = _pair(filter_size)
    sh, sw = _pair(stride)
    pt, pl, pb, pr = _norm_pad4(padding)

    def fn(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, ((0, 0), (0, 0), (pt, pb), (pl, pr)))
        oh = (h + pt + pb - fh) // sh + 1
        ow = (w + pl + pr - fw) // sw + 1
        taps = []
        for i in range(fh):
            for j in range(fw):
                taps.append(v[:, :, i : i + oh * sh : sh, j : j + ow * sw : sw])
        pat = jnp.stack(taps, axis=2)          # [n, c, fh*fw, oh, ow]
        pat = jnp.transpose(pat, (0, 3, 4, 1, 2))  # [n, oh, ow, c, fh*fw]
        return pat.reshape(n, oh * ow, c * fh * fw)

    return apply(fn, _t(x))


def shuffle_batch(x, seed=0, name=None):
    """shuffle_batch_op.cc parity: random permutation of rows. Eager (the
    permutation is data-independent host randomness, like the reference)."""
    from ...core.generator import default_generator

    v = _t(x)
    # seed=0 -> fresh permutation every call (reference semantics)
    key = (default_generator().split() if not seed
           else default_generator().fold_in(seed))
    perm = jax.random.permutation(key, v.shape[0])
    out = apply(lambda a: a[perm], v)
    return out


def cvm(input, cvm_info, use_cvm=True, name=None):
    """cvm_op.h parity (CTR show/click features): with use_cvm the first two
    columns become log(show+1) and log(click+1)-log(show+1); without it they
    are dropped."""
    def fn(x):
        c0 = jnp.log(x[:, 0:1] + 1.0)
        c1 = jnp.log(x[:, 1:2] + 1.0) - c0
        if use_cvm:
            return jnp.concatenate([c0, c1, x[:, 2:]], axis=1)
        return x[:, 2:]

    return apply(fn, _t(input))


def data_norm(x, batch_size, batch_sum, batch_square_sum, name=None):
    """data_norm_op.cc parity (:302-330): y = (x - batch_sum/batch_size) *
    sqrt(batch_size / batch_square_sum) — the PS-CTR running-stat normalizer."""
    def fn(v, bsz, bsum, bsq):
        mean = bsum / bsz
        scale = jnp.sqrt(bsz / bsq)
        return (v - mean[None, :]) * scale[None, :]

    return apply(fn, _t(x), _t(batch_size).detach(), _t(batch_sum).detach(),
                 _t(batch_square_sum).detach())


def affine_channel(x, scale, bias, data_format="NCHW", name=None):
    """affine_channel_op.cc parity: per-channel y = x*scale[c] + bias[c]."""
    def fn(v, s, b):
        if data_format == "NCHW":
            shape = (1, -1) + (1,) * (v.ndim - 2)
        else:
            shape = (1,) * (v.ndim - 1) + (-1,)
        return v * s.reshape(shape) + b.reshape(shape)

    return apply(fn, _t(x), _t(scale), _t(bias))


def ctc_align(input, input_length, blank=0, merge_repeated=True,
              padding_value=0, name=None):
    """ctc_align_op.h parity (CTC greedy-decode postprocess): drop blanks,
    optionally merge repeats, left-compact; returns (ids, lengths)."""
    def fn(v, ln):
        B, T = v.shape
        ln = ln.reshape(-1).astype(jnp.int32)
        valid = jnp.arange(T)[None, :] < ln[:, None]
        prev = jnp.concatenate([jnp.full((B, 1), -1, v.dtype), v[:, :-1]],
                               axis=1)
        keep = valid & (v != blank)
        if merge_repeated:
            keep = keep & (v != prev)
        dest = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
        dest = jnp.where(keep, dest, T)
        bidx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
        out = jnp.full((B, T + 1), padding_value, v.dtype).at[
            bidx.reshape(-1), dest.reshape(-1)].set(v.reshape(-1))[:, :T]
        # .at[].set on the dump column may leave stale values; re-fill padding
        newlen = jnp.sum(keep, axis=1)
        pad_mask = jnp.arange(T)[None, :] >= newlen[:, None]
        out = jnp.where(pad_mask, padding_value, out)
        return out, newlen

    ids, lens = apply(fn, _t(input).detach(), _t(input_length).detach())
    ids.stop_gradient = True
    lens.stop_gradient = True
    return ids, lens


def fsp_matrix(x, y, name=None):
    """fsp_op.h parity (flow-of-solution-procedure distillation matrix):
    x [B, Cx, H, W], y [B, Cy, H, W] -> [B, Cx, Cy] = x·y^T / (H*W)."""
    def fn(a, b):
        B, Cx, H, W = a.shape
        return jnp.einsum("bchw,bdhw->bcd", a, b) / (H * W)

    return apply(fn, _t(x), _t(y))


def tdm_child(x, tree_info, child_nums, dtype="int32", name=None):
    """tdm_child_op.h parity (tree-based deep match): per input node id,
    return its `child_nums` children from tree_info rows
    [item_id, layer_id, ancestor_id, child_0, ..] and a leaf mask
    (child is an item <=> tree_info[child][0] != 0). Eager host op."""
    ids = np.asarray(_t(x)._data).astype(np.int64)
    info = np.asarray(_t(tree_info)._data).astype(np.int64)
    flat = ids.reshape(-1)
    child = np.zeros((flat.size, child_nums), np.int64)
    mask = np.zeros((flat.size, child_nums), np.int64)
    for k, nid in enumerate(flat):
        if nid == 0 or info[nid, 3] == 0:
            continue
        for c in range(child_nums):
            cid = info[nid, 3 + c]
            child[k, c] = cid
            mask[k, c] = 1 if info[cid, 0] != 0 else 0
    shape = list(ids.shape) + [child_nums]
    out_c = Tensor(jnp.asarray(child.reshape(shape)))
    out_m = Tensor(jnp.asarray(mask.reshape(shape)))
    out_c.stop_gradient = True
    out_m.stop_gradient = True
    return out_c, out_m


def tdm_sampler(x, travel, layer, neg_samples_num_list, layer_offset_lod,
                output_positive=True, output_list=False, seed=0,
                tree_dtype="int32", dtype="int32", name=None):
    """tdm_sampler_op.h parity: per leaf, walk its root-to-leaf travel path;
    at each tree layer emit [positive +] N uniformly-sampled negatives from
    that layer (positive excluded), with 1/0 labels and a padding mask
    (travel id 0 = padded layer -> mask 0). Eager host op."""
    ids = np.asarray(_t(x)._data).astype(np.int64).reshape(-1)
    trav = np.asarray(_t(travel)._data).astype(np.int64)
    lay = np.asarray(_t(layer)._data).astype(np.int64).reshape(-1)
    rng_ = np.random.RandomState(seed if seed else None)  # lint: allow(np-random-in-traced-code) — documented eager host op
    L = len(neg_samples_num_list)
    per = [n + (1 if output_positive else 0) for n in neg_samples_num_list]
    width = sum(per)
    out = np.zeros((ids.size, width), np.int64)
    lab = np.zeros((ids.size, width), np.int64)
    msk = np.ones((ids.size, width), np.int64)
    for i, leaf in enumerate(ids):
        off = 0
        for li in range(L):
            pos = trav[leaf, li]
            lo, hi = layer_offset_lod[li], layer_offset_lod[li + 1]
            nodes = lay[lo:hi]
            if output_positive:
                out[i, off] = pos
                lab[i, off] = 1
                if pos == 0:  # padded ancestor
                    msk[i, off] = 0
                off += 1
            n_neg = neg_samples_num_list[li]
            cand = nodes[nodes != pos]
            if len(cand) >= n_neg:
                neg = rng_.choice(cand, n_neg, replace=False)
            else:
                neg = np.resize(cand, n_neg) if len(cand) else np.zeros(n_neg, np.int64)
            out[i, off: off + n_neg] = neg
            if pos == 0:
                msk[i, off: off + n_neg] = 0
                out[i, off: off + n_neg] = 0
            off += n_neg
    outs = (Tensor(jnp.asarray(out)), Tensor(jnp.asarray(lab)),
            Tensor(jnp.asarray(msk)))
    for t in outs:
        t.stop_gradient = True
    return outs


def match_matrix_tensor(x, y, w, x_length=None, y_length=None, dim_t=1,
                        name=None):
    """match_matrix_tensor_op.cc parity (text-matching bilinear tensor):
    out[b, t, i, j] = x[b, i] @ W[:, t, :] @ y[b, j] with positions past each
    sequence's length masked to 0. Padded form of the reference's LoD op:
    x [B, Lx, D1], y [B, Ly, D2], w [D1, dim_t, D2] -> [B, dim_t, Lx, Ly]."""
    args = [_t(x), _t(y), _t(w)]
    if x_length is not None:
        args.append(_t(x_length).detach())
    if y_length is not None:
        args.append(_t(y_length).detach())

    def fn(xv, yv, wv, *lens):
        out = jnp.einsum("bid,dte,bje->btij", xv, wv, yv)
        B, _, Lx, Ly = out.shape
        if lens:
            lx = lens[0].astype(jnp.int32)
            mask_x = (jnp.arange(Lx)[None, :] < lx[:, None])
            out = out * mask_x[:, None, :, None]
            if len(lens) > 1:
                ly = lens[1].astype(jnp.int32)
                mask_y = (jnp.arange(Ly)[None, :] < ly[:, None])
                out = out * mask_y[:, None, None, :]
        return out

    return apply(fn, *args)


def similarity_focus(input, axis, indexes, name=None):
    """similarity_focus_op.cc parity: for each selected slice along `axis`,
    greedily pick min(rows, cols) maxima with distinct rows/columns (same
    greedy-global-max scan as bipartite matching), OR the masks over indexes,
    and broadcast over `axis`. The OUTPUT IS THE 0/1 MASK (input-shaped),
    like the reference — not the gated input. x [B, d1, d2, d3]."""
    def fn(v):
        B = v.shape[0]
        vm = jnp.moveaxis(v, axis, 1)                     # [B, A, R, C]
        A, Rr, Cc = vm.shape[1], vm.shape[2], vm.shape[3]

        def greedy_mask(T):
            def step(carry, _):
                live, m = carry
                masked = jnp.where(live, T, -jnp.inf)
                flat = jnp.argmax(masked)
                i, j = flat // Cc, flat % Cc
                m = m.at[i, j].set(1.0)
                live = live & (jnp.arange(Rr)[:, None] != i) \
                    & (jnp.arange(Cc)[None, :] != j)
                return (live, m), None

            init = (jnp.ones((Rr, Cc), bool), jnp.zeros((Rr, Cc), T.dtype))
            (_, m), _ = jax.lax.scan(step, init, None, length=min(Rr, Cc))
            return m

        mask = jnp.zeros((B, Rr, Cc), v.dtype)
        for a in indexes:
            mask = jnp.maximum(mask, jax.vmap(greedy_mask)(vm[:, a]))
        out = jnp.broadcast_to(mask[:, None, :, :], vm.shape)
        return jnp.moveaxis(out, 1, axis)

    return apply(fn, _t(input))


def var_conv_2d(x, row_length, col_length, weight, input_channel,
                output_channel, filter_size, stride=1, name=None):
    """var_conv_2d_op parity (text-matching variable-size conv): each sample's
    image has its own valid (rows, cols) region; positions outside are zero
    before AND after the conv (the reference computes per-sample on exact
    sizes — padded+mask is numerically identical for interior positions).
    x [B, C_in, H, W]; weight [C_out, C_in*kh*kw]."""
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    kh, kw = _pair(filter_size)
    sh, sw = _pair(stride)

    def fn(v, rl, cl, w):
        B, Cin, H, W = v.shape
        rl = rl.astype(jnp.int32)
        cl = cl.astype(jnp.int32)
        rmask = (jnp.arange(H)[None, :] < rl[:, None]).astype(v.dtype)
        cmask = (jnp.arange(W)[None, :] < cl[:, None]).astype(v.dtype)
        vm = v * rmask[:, None, :, None] * cmask[:, None, None, :]
        wk = w.reshape(output_channel, Cin, kh, kw)
        out = jax.lax.conv_general_dilated(
            vm, wk, (sh, sw), [(kh // 2, kh // 2), (kw // 2, kw // 2)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        Ho, Wo = out.shape[2], out.shape[3]
        ro = jnp.maximum((rl + sh - 1) // sh, 1)
        co = jnp.maximum((cl + sw - 1) // sw, 1)
        rm = (jnp.arange(Ho)[None, :] < ro[:, None]).astype(v.dtype)
        cm = (jnp.arange(Wo)[None, :] < co[:, None]).astype(v.dtype)
        return out * rm[:, None, :, None] * cm[:, None, None, :]

    return apply(fn, _t(x), _t(row_length).detach(), _t(col_length).detach(),
                 _t(weight))


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1, max_depth=2,
              act="tanh", filter=None, name=None):
    """tree_conv_op (TBCNN, math/tree2col.cc parity): per node, gather its
    subtree within max_depth; each patch member contributes its feature
    weighted by the continuous binary-tree coefficients (eta_l, eta_r, eta_t)
    (:35-52), then one matmul against filter [F, 3, output_size, num_filters].
    Eager tree walk (data-dependent structure), XLA matmul + autodiff for the
    compute. nodes_vector [batch, N, F] (or unbatched [N, F]); node ids are
    1-based in edge_set [batch, E, 2] (or [E, 2]), (0, 0)-terminated; act
    defaults to tanh like fluid.contrib.layers.tree_conv. Returns
    [batch, P, output_size, M] (or unbatched [P, output_size, M])."""
    feats = _t(nodes_vector)
    edges_all = np.asarray(_t(edge_set)._data).astype(np.int64)
    w = _t(filter)
    F_ = feats.shape[-1]

    if feats.ndim == 3:  # batched: per-sample trees, stacked results
        outs = [tree_conv(feats[b], edges_all[b], output_size, num_filters,
                          max_depth, act, filter)
                for b in range(feats.shape[0])]
        from ...tensor.manipulation import stack as _stack

        return _stack(outs, axis=0)
    edges = edges_all.reshape(-1, 2)

    tr = {}
    node_count = 0
    for u, v in edges:
        if u == 0 or v == 0:
            break
        tr.setdefault(int(u), []).append(int(v))
        node_count += 1
    node_count += 1

    # weights[p] : list of (node_id, eta_l, eta_r, eta_t)
    d = float(max_depth)
    patches = []
    for root in range(1, node_count + 1):
        visited = {root}
        # (node, index, pclen, depth)
        stack = [(root, 1, 1, 0)]
        patch = [(root, 1, 1, 0)]
        while stack:
            node, _, _, depth = stack[-1]
            children = tr.get(node, [])
            advanced = False
            for i, v in enumerate(children):
                if v not in visited and depth + 1 < max_depth:
                    visited.add(v)
                    stack.append((v, i, len(children), depth + 1))
                    patch.append((v, i + 1, len(children), depth + 1))
                    advanced = True
            if not advanced:
                stack.pop()
        rows = []
        for node, index, pclen, depth in patch:
            eta_t = (d - depth) / d
            tmp = 0.5 if pclen == 1 else (index - 1.0) / (pclen - 1.0)
            eta_l = (1.0 - eta_t) * tmp
            eta_r = (1.0 - eta_t) * (1.0 - eta_l)
            rows.append((node - 1, eta_l, eta_r, eta_t))
        patches.append(rows)

    P = len(patches)
    # sparse gather plan -> dense [P, N, 3] coefficient tensor (small trees)
    N = feats.shape[0]
    coef = np.zeros((P, N, 3), np.float32)
    for p, rows in enumerate(patches):
        for nid, el, er, et in rows:
            coef[p, nid, 0] += el
            coef[p, nid, 1] += er
            coef[p, nid, 2] += et
    coef_j = jnp.asarray(coef)

    def fn(fv, wv):
        # patch [P, F, 3] = coef^T gathered features; flatten matches the
        # filter's [F, 3, O, M] row-major layout
        patch = jnp.einsum("pnk,nf->pfk", coef_j, fv)
        O, M = wv.shape[2], wv.shape[3]
        out = patch.reshape(P, 3 * F_) @ wv.reshape(3 * F_, O * M)
        return out.reshape(P, O, M)

    out = apply(fn, feats, w)
    if act == "tanh":
        from ...tensor.math import tanh as _tanh

        out = _tanh(out)
    return out


def correlation(x, y, pad_size, kernel_size, max_displacement, stride1,
                stride2, corr_type_multiply=1, name=None):
    """correlation_op.cu parity (FlowNet cost volume): for each displacement
    (ti, tj) in a (2*max_displacement/stride2+1)^2 grid, the mean over a
    kernel window and channels of x[h1, w1] * y[h1+tj*s2, w1+ti*s2] on the
    zero-padded inputs. TPU design: one jnp.roll + windowed mean per
    displacement — each is an XLA reduce the compiler fuses; no per-pixel
    loops. Returns [N, D*D, Ho, Wo]."""
    def fn(a, b):
        N, C, H, W = a.shape
        kr = (kernel_size - 1) // 2
        border = max_displacement + kr          # border_radius (:33)
        drad = max_displacement // stride2
        D = 2 * drad + 1
        # extra zero margin so displacement+kernel shifts slice, never wrap
        m = max_displacement + kr
        ap = jnp.pad(a, ((0, 0), (0, 0), (pad_size + m, pad_size + m),
                         (pad_size + m, pad_size + m)))
        bp = jnp.pad(b, ((0, 0), (0, 0), (pad_size + m, pad_size + m),
                         (pad_size + m, pad_size + m)))
        Hp, Wp = H + 2 * pad_size, W + 2 * pad_size
        Ho = int(np.ceil((Hp - 2 * border) / float(stride1)))
        Wo = int(np.ceil((Wp - 2 * border) / float(stride1)))
        nelems = kernel_size * kernel_size * C
        outs = []
        for tj in range(-drad, drad + 1):
            for ti in range(-drad, drad + 1):
                acc = None
                for j in range(-kr, kr + 1):
                    for i in range(-kr, kr + 1):
                        a_sl = ap[:, :, m + j: m + j + Hp, m + i: m + i + Wp]
                        b_sl = bp[:, :,
                                  m + j + tj * stride2: m + j + tj * stride2 + Hp,
                                  m + i + ti * stride2: m + i + ti * stride2 + Wp]
                        term = a_sl * b_sl
                        acc = term if acc is None else acc + term
                summed = jnp.sum(acc, axis=1)          # [N, Hp, Wp]
                h_idx = border + stride1 * jnp.arange(Ho)
                w_idx = border + stride1 * jnp.arange(Wo)
                outs.append(summed[:, h_idx[:, None], w_idx[None, :]] / nelems)
        return jnp.stack(outs, axis=1)

    return apply(fn, _t(x), _t(y))


def bilateral_slice(x, guide, grid, has_offset=False, name=None):
    """bilateral_slice_op.cu parity (HDRNet): per pixel, trilinearly slice an
    affine-coefficient grid at (x/w, y/h, guide) and apply it to the input
    channels (+ optional offset row). x [N, Ci, H, W]; guide [N, H, W];
    grid [N, Ci'*Co, gd, gh, gw] with Ci' = Ci (+1 with offset).
    TPU design: the 8 trilinear corners become gathered tensors combined with
    one einsum over input channels — no per-pixel loops."""
    def fn(xv, gv, grid_v):
        N, Ci, H, W = xv.shape
        coeff_stride = Ci + (1 if has_offset else 0)
        Gc = grid_v.shape[1]
        Co = Gc // coeff_stride
        gd, gh, gw = grid_v.shape[2], grid_v.shape[3], grid_v.shape[4]

        gx = (jnp.arange(W, dtype=jnp.float32) + 0.5) * gw / W   # [W]
        gy = (jnp.arange(H, dtype=jnp.float32) + 0.5) * gh / H   # [H]
        gz = gv * gd                                             # [N, H, W]
        gxb = jnp.broadcast_to(gx[None, None, :], (N, H, W))
        gyb = jnp.broadcast_to(gy[None, :, None], (N, H, W))

        fx = jnp.floor(gxb - 0.5)
        fy = jnp.floor(gyb - 0.5)
        fz = jnp.floor(gz - 0.5)

        grid5 = grid_v.reshape(N, Co, coeff_stride, gd, gh, gw)
        coeff = jnp.zeros((N, Co, coeff_stride, H, W), xv.dtype)
        for dx in range(2):
            xx = fx + dx
            x_ = jnp.clip(xx, 0, gw - 1).astype(jnp.int32)
            wx = jnp.maximum(1.0 - jnp.abs(xx + 0.5 - gxb), 0.0)
            for dy in range(2):
                yy = fy + dy
                y_ = jnp.clip(yy, 0, gh - 1).astype(jnp.int32)
                wy = jnp.maximum(1.0 - jnp.abs(yy + 0.5 - gyb), 0.0)
                for dz in range(2):
                    zz = fz + dz
                    z_ = jnp.clip(zz, 0, gd - 1).astype(jnp.int32)
                    wz = jnp.maximum(1.0 - jnp.abs(zz + 0.5 - gz), 0.0)
                    # gather [N, Co, Cs, H, W] at per-pixel (z, y, x)
                    sample = grid5[jnp.arange(N)[:, None, None, None, None],
                                   jnp.arange(Co)[None, :, None, None, None],
                                   jnp.arange(coeff_stride)[None, None, :, None, None],
                                   z_[:, None, None, :, :],
                                   y_[:, None, None, :, :],
                                   x_[:, None, None, :, :]]
                    coeff = coeff + sample * (wx * wy * wz)[:, None, None, :, :]
        out = jnp.einsum("ncihw,nihw->nchw", coeff[:, :, :Ci], xv)
        if has_offset:
            out = out + coeff[:, :, Ci]
        return out

    return apply(fn, _t(x), _t(guide), _t(grid))


def batch_fc(input, w, bias=None, act=None, name=None):
    """batch_fc_op.cc parity (per-slot FC for rank models): input
    [slot_pairs_num, batch_size, in_dim], w [slot_pairs_num, in_dim, out_dim],
    bias [slot_pairs_num, out_dim]; out[s] = act(input[s] @ w[s] + bias[s]).
    One batched MXU matmul replaces the reference's per-slot GEMM loop
    (batch_fc_op.cu). The fluid wrapper created the parameters from
    param_size/bias_size attrs; here they are passed explicitly like the
    rest of this functional family."""
    args = [_t(input), _t(w)]
    if bias is not None:
        args.append(_t(bias))

    def fn(v, wv, *b):
        out = jnp.einsum("sbi,sio->sbo", v, wv)
        if b:
            out = out + b[0][:, None, :]
        if act is not None:
            if act not in ("relu", "sigmoid", "tanh"):
                raise ValueError(f"unsupported act {act!r}")
            out = getattr(jax.nn, act)(out)
        return out

    return apply(fn, *args)


def rank_attention(input, rank_offset, rank_param, max_rank=3, max_size=0,
                   name=None):
    """rank_attention_op parity (rank-aware feature crossing in CTR models,
    rank_attention.cu.h:32-95): each instance i with its own rank `lower`
    gathers up to max_rank peer instances; peer slot k contributes
    x[index_k] @ P[lower, faster_k] where P is rank_param reshaped to
    [max_rank, max_rank, in_dim, out_dim] (the reference's
    start = lower*max_rank + faster block layout). Slots with lower<0 or
    faster<0 contribute 0 (the CUDA kernels' `continue` on zeroed buffers).

    input [B, D]; rank_offset [B, 2*max_rank+1] int32 — column 0 the
    instance's 1-based rank, then (faster_rank, row_index) pairs;
    rank_param [max_rank*max_rank*D, out_dim] (the fluid wrapper's asserted
    shape). Returns [B, out_dim]. Gathers + one batched einsum instead of
    the expand-to-[B, max_rank*D] staging buffers the CUDA path builds."""
    def fn(xv, ro, pv):
        B, D = xv.shape
        O = pv.shape[-1]
        P = pv.reshape(max_rank, max_rank, D, O)
        ro = ro.astype(jnp.int32)
        lower = ro[:, 0] - 1                                  # [B]
        faster = ro[:, 1::2] - 1                              # [B, K]
        index = ro[:, 2::2]                                   # [B, K]
        valid = (lower[:, None] >= 0) & (faster >= 0)
        xk = jnp.where(valid[:, :, None],
                       xv[jnp.clip(index, 0, B - 1)], 0)      # [B, K, D]
        pk = P[jnp.clip(lower, 0)[:, None], jnp.clip(faster, 0)]
        pk = jnp.where(valid[:, :, None, None], pk, 0)        # [B, K, D, O]
        return jnp.einsum("bkd,bkdo->bo", xk, pk)

    return apply(fn, _t(input), _t(rank_offset).detach(), _t(rank_param))


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0, name=None):
    """filter_by_instag_op.cc parity: keep the instances whose tag list
    intersects filter_tag. Padded TPU form: ins [N, ...] rows, ins_tag
    [N, Tmax] int64 padded with -1 (the reference walks per-instance LoD tag
    lists), filter_tag 1-D. Instead of compacting to a shorter tensor
    (data-dependent shape), rows that fail the filter are zeroed and their
    loss_weight is 0 — downstream losses multiply by loss_weight, so the
    training math matches the reference's compacted batch. Returns
    [out [N, ...], loss_weight [N, 1] float]. out_val_if_empty (the value
    the reference writes into its single placeholder row when NOTHING
    passes) is accepted for signature parity; the padded form keeps shape,
    so it never needs to materialize that placeholder."""
    def fn(v, tags, ft):
        match = (tags[:, :, None] == ft[None, None, :])       # [N, T, F]
        match &= (tags >= 0)[:, :, None]                      # padding slots
        keep = match.any(axis=(1, 2))                         # [N]
        shaped = keep.reshape((-1,) + (1,) * (v.ndim - 1))
        out = jnp.where(shaped, v, jnp.asarray(0, v.dtype))
        lw = keep.astype(jnp.float32)[:, None]
        return out, lw

    return apply(fn, _t(ins), _t(ins_tag).detach(),
                 _t(filter_tag).detach().reshape([-1]))


def search_pyramid_hash(x, length, weights, num_emb, space_len, pyramid_layer,
                        rand_len, drop_out_percent=0.0, is_training=True,
                        seed=1, step=0, name=None):
    """pyramid_hash_op.cc parity (PyramidDNN hashed n-gram embeddings): every
    n-gram of length 2..pyramid_layer gets an embedding made of
    num_emb/rand_len strips of the weight table, strip j starting at
    hash(ngram, seed=j*rand_len) % space_len (hash_embedding_ff,
    pyramid_hash_op.cc:226-247). Padded TPU form: x [B, T] int32 token ids +
    length [B]; weights [space_len + rand_len] (same +rand_len slack row
    block as the reference's [space_len+rand_len, 1] table). Returns
    (out [B, N, num_emb], ngram_length [B]) with rows ordered ngram-size
    then start position like the reference's loop; invalid/dropped ngrams
    are zero rows instead of being compacted away (static shapes — callers
    seq-pool over ngram_length, and zero rows are no-ops under sum pooling).

    Deviations (documented, structural parity kept): the hash is a
    vectorized integer avalanche over the id window, not XXH32 of raw bytes
    (both are arbitrary fixed hashes into a LEARNED table — only
    determinism matters); train-time ngram dropout hashes (window, seed,
    `step`) rather than drawing rand_r — pass the global training step so
    a FRESH ngram subset drops each step (a fixed step would permanently
    exclude the same ngrams from training); the white/black-list
    bloom filters (use_filter path) are descoped with the PS-side filter
    tooling. Eval scales by drop_out_percent only when it is set (> 0) —
    the reference's unconditional axpy would zero eval output at the
    attr's own default of 0."""
    if num_emb % rand_len:
        raise ValueError(f"num_emb ({num_emb}) must be a multiple of "
                         f"rand_len ({rand_len})")
    n_chunks = num_emb // rand_len

    def _u32(v):
        return np.uint32(v & 0xFFFFFFFF)

    def _hash(win, salt):
        # avalanche mix of the id window [B, L, n] + salt -> uint32
        h = jnp.full(win.shape[:2], _u32(2166136261 ^ (seed * 16777619)),
                     jnp.uint32)
        for t in range(win.shape[-1]):
            h = (h ^ win[..., t].astype(jnp.uint32)) * np.uint32(16777619)
            h = h ^ (h >> 15)
        h = (h ^ _u32(salt * 2654435761)) * np.uint32(2246822519)
        return h ^ (h >> 13)

    def fn(v, ln, wv):
        wv = wv.reshape(-1)
        B, T = v.shape
        ln32 = ln.astype(jnp.int32)
        blocks, counts = [], []
        for ilayer in range(1, pyramid_layer):
            n, L = ilayer + 1, T - ilayer
            if L <= 0:
                break
            win = jnp.stack([v[:, l:l + L] for l in range(n)], -1)  # [B,L,n]
            ok = (jnp.arange(L)[None, :] + ilayer) < ln32[:, None]  # [B, L]
            if is_training and drop_out_percent > 0:
                u = _hash(win, 7919 + 104729 * int(step)) \
                    .astype(jnp.float32) / 4294967296.0
                ok &= (u >= drop_out_percent)
            pos = jnp.stack([_hash(win, j * rand_len) % np.uint32(space_len)
                             for j in range(n_chunks)], -1)  # [B, L, chunks]
            idx = (pos[..., None].astype(jnp.int32)
                   + jnp.arange(rand_len, dtype=jnp.int32))
            emb = wv[idx].reshape(B, L, num_emb)
            blocks.append(emb * ok[:, :, None].astype(wv.dtype))
            counts.append(ok.sum(axis=1).astype(jnp.int32))
        if not blocks:
            return (jnp.zeros((B, 1, num_emb), wv.dtype),
                    jnp.zeros((B,), jnp.int32))
        out = jnp.concatenate(blocks, axis=1)
        if not is_training and drop_out_percent > 0:
            out = out * drop_out_percent
        return out, sum(counts)

    return apply(fn, _t(x).detach(), _t(length).detach(), _t(weights))
