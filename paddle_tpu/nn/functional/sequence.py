"""Sequence op family (reference operators/sequence_ops/ — 6.2k LoC of LoD
kernels: sequence_pool_op.cc, sequence_conv_op.cc, sequence_expand_op.cc,
sequence_reverse_op.h, sequence_slice_op.h, sequence_softmax_op.cc,
sequence_concat_op.cc, sequence_enumerate_op.cc, sequence_erase_op.cc,
sequence_scatter_op.cc, sequence_reshape_op.cc).

TPU-native design (SURVEY hard-part #2): LoD tensors become (data [B, T, ...],
length [B]) padded batches — every op is a masked dense computation with static
shapes, so the whole family jits and fuses instead of walking LoD offsets on
the host. Ops whose output length differs per sequence (erase, enumerate with
trimming) re-pad to the input's T and return new lengths.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def _mask(T, length, dtype=jnp.float32):
    # length [B] -> [B, T] 0/1 mask
    return (jnp.arange(T)[None, :] < length[:, None]).astype(dtype)


def sequence_pool(x, length, pool_type="sum", pad_value=0.0, name=None):
    """sequence_pool_op.cc parity over padded [B, T, D] + length [B].
    pool_type: sum | average | sqrt | max | min | first | last.
    Empty sequences (length 0) yield pad_value like the reference."""
    pt = pool_type.lower()

    def fn(v, ln):
        B, T = v.shape[0], v.shape[1]
        ln = ln.astype(jnp.int32)
        m = _mask(T, ln, v.dtype)
        mex = m.reshape(B, T, *([1] * (v.ndim - 2)))
        empty = (ln == 0).reshape(B, *([1] * (v.ndim - 2)))
        if pt in ("sum", "average", "sqrt"):
            s = jnp.sum(v * mex, axis=1)
            denom = jnp.maximum(ln, 1).astype(v.dtype).reshape(
                B, *([1] * (v.ndim - 2)))
            if pt == "average":
                s = s / denom
            elif pt == "sqrt":
                s = s / jnp.sqrt(denom)
            out = s
        elif pt == "max":
            out = jnp.max(jnp.where(mex > 0, v, -jnp.inf), axis=1)
        elif pt == "min":
            out = jnp.min(jnp.where(mex > 0, v, jnp.inf), axis=1)
        elif pt == "first":
            out = v[:, 0]
        elif pt == "last":
            idx = jnp.maximum(ln - 1, 0)
            out = jnp.take_along_axis(
                v, idx.reshape(B, 1, *([1] * (v.ndim - 2))).astype(jnp.int32)
                * jnp.ones((B, 1, *v.shape[2:]), jnp.int32), axis=1)[:, 0]
        else:
            raise ValueError(f"unknown pool_type {pool_type}")
        return jnp.where(empty, jnp.asarray(pad_value, v.dtype), out)

    return apply(fn, _t(x), _t(length).detach())


def sequence_first_step(x, length, name=None):
    return sequence_pool(x, length, "first")


def sequence_last_step(x, length, name=None):
    return sequence_pool(x, length, "last")


def sequence_softmax(x, length, name=None):
    """sequence_softmax_op.cc parity: softmax over each sequence's valid steps
    (padded steps get 0 probability). x [B, T] or [B, T, 1]."""
    def fn(v, ln):
        squeeze = v.ndim == 3 and v.shape[-1] == 1
        vv = v[..., 0] if squeeze else v
        T = vv.shape[1]
        m = _mask(T, ln.astype(jnp.int32), jnp.bool_)
        z = jnp.where(m, vv, -jnp.inf)
        p = jax.nn.softmax(z, axis=1)
        p = jnp.where(m, p, 0.0)
        return p[..., None] if squeeze else p

    return apply(fn, _t(x), _t(length).detach())


def sequence_reverse(x, length, name=None):
    """sequence_reverse_op.h parity: reverse each sequence's first `length`
    steps in place; padding stays put."""
    def fn(v, ln):
        B, T = v.shape[0], v.shape[1]
        ln = ln.astype(jnp.int32)
        pos = jnp.arange(T)[None, :]
        src = jnp.where(pos < ln[:, None], ln[:, None] - 1 - pos, pos)
        src = src.astype(jnp.int32)
        idx = src.reshape(B, T, *([1] * (v.ndim - 2)))
        idx = jnp.broadcast_to(idx, v.shape)
        return jnp.take_along_axis(v, idx, axis=1)

    return apply(fn, _t(x), _t(length).detach())


def sequence_expand(x, length_x, ref_length, name=None):
    """sequence_expand_op.cc parity (padded): repeat each sequence i of x
    ref_length[i] times along a new repeat axis is LoD-specific; the padded
    equivalent used by the reference's main consumer (beam search / attention)
    tiles each row's sequence to the reference's length. Here: x [B, Tx, ...]
    is re-padded to [B, max(ref_length), ...] by cycling its valid steps,
    matching sequence_expand with per-sequence repeat.

    Deviation (PARITY.md): the output keeps x's static T — true repeat-style
    LoD growth (output longer than T) is unsupported; eager calls with
    ref_length > T raise instead of silently truncating."""
    xt, lrt = _t(x), _t(ref_length).detach()
    if not isinstance(lrt._data, jax.core.Tracer):
        T = xt.shape[1]
        if int(jnp.max(lrt._data)) > T:
            raise ValueError(
                f"sequence_expand: ref_length (max "
                f"{int(jnp.max(lrt._data))}) exceeds x's padded length {T}; "
                "repeat-style LoD growth is unsupported in the padded design "
                "— re-pad x to max(ref_length) first")

    def fn(v, lx, lr):
        B, T = v.shape[0], v.shape[1]
        lx = jnp.maximum(lx.astype(jnp.int32), 1)
        lr = jnp.minimum(lr.astype(jnp.int32), T)  # output keeps the static T
        pos = jnp.arange(T)[None, :]
        src = (pos % lx[:, None]).astype(jnp.int32)
        idx = jnp.broadcast_to(
            src.reshape(B, T, *([1] * (v.ndim - 2))), v.shape)
        out = jnp.take_along_axis(v, idx, axis=1)
        m = _mask(T, lr, v.dtype).reshape(B, T, *([1] * (v.ndim - 2)))
        return out * m

    return apply(fn, xt, _t(length_x).detach(), lrt)


def sequence_expand_as(x, length_x, y, ref_length, name=None):
    return sequence_expand(x, length_x, ref_length)


def sequence_slice(x, length, offset, out_length, name=None):
    """sequence_slice_op.h parity: per-sequence [offset, offset+out_length)
    window, left-aligned into the output padding. Returns ([B, T, ...], new
    lengths = out_length)."""
    def fn(v, ln, off, ol):
        B, T = v.shape[0], v.shape[1]
        off = off.reshape(-1).astype(jnp.int32)
        ol = ol.reshape(-1).astype(jnp.int32)
        pos = jnp.arange(T)[None, :]
        src = jnp.clip(pos + off[:, None], 0, T - 1).astype(jnp.int32)
        idx = jnp.broadcast_to(
            src.reshape(B, T, *([1] * (v.ndim - 2))), v.shape)
        shifted = jnp.take_along_axis(v, idx, axis=1)
        m = _mask(T, ol, v.dtype).reshape(B, T, *([1] * (v.ndim - 2)))
        return shifted * m

    out = apply(fn, _t(x), _t(length).detach(), _t(offset).detach(),
                _t(out_length).detach())
    return out, _t(out_length)


def sequence_concat(xs, lengths, name=None):
    """sequence_concat_op.cc parity: concatenate the i-th sequences of every
    input along time (valid steps back to back). Returns (data, lengths)."""
    xs = [_t(x) for x in xs]
    lens = [_t(l).detach() for l in lengths]
    T_out = sum(int(x.shape[1]) for x in xs)

    def fn(*args):
        n = len(args) // 2
        vs, lns = args[:n], args[n:]
        B = vs[0].shape[0]
        total = sum(ln.astype(jnp.int32) for ln in lns)
        out_shape = (B, T_out) + vs[0].shape[2:]
        out = jnp.zeros(out_shape, vs[0].dtype)
        base = jnp.zeros((B,), jnp.int32)
        for v, ln in zip(vs, lns):
            T = v.shape[1]
            ln = ln.astype(jnp.int32)
            pos = jnp.arange(T)[None, :]
            valid = pos < ln[:, None]
            dest = jnp.where(valid, base[:, None] + pos, T_out)  # T_out = dump
            bidx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
            out = jnp.zeros((B, T_out + 1) + v.shape[2:], v.dtype).at[
                bidx.reshape(-1), dest.reshape(-1)].add(
                    v.reshape((-1,) + v.shape[2:]))[:, :T_out] + out
            base = base + ln
        return out, total

    flat = list(xs) + list(lens)
    out, total = apply(fn, *flat)
    return out, total


def sequence_enumerate(x, length, win_size, pad_value=0, name=None):
    """sequence_enumerate_op.cc parity: each position emits the window
    [i, i+win_size) of token ids, padded with pad_value past the sequence
    end. x [B, T] int -> [B, T, win_size]."""
    def fn(v, ln):
        B, T = v.shape
        ln = ln.astype(jnp.int32)
        pos = jnp.arange(T)[None, :, None] + jnp.arange(win_size)[None, None, :]
        inb = pos < ln[:, None, None]
        src = jnp.clip(pos, 0, T - 1).astype(jnp.int32)
        win = jnp.take_along_axis(
            jnp.broadcast_to(v[:, :, None], (B, T, win_size)), src, axis=1)
        valid_row = jnp.arange(T)[None, :, None] < ln[:, None, None]
        return jnp.where(inb & valid_row, win, pad_value)

    out = apply(fn, _t(x).detach(), _t(length).detach())
    out.stop_gradient = True
    return out


def sequence_erase(x, length, tokens, name=None):
    """sequence_erase_op.cc parity: delete the given token ids from each
    sequence and re-compact left. Returns (ids [B, T], new lengths [B])."""
    def fn(v, ln):
        B, T = v.shape
        ln = ln.astype(jnp.int32)
        valid = jnp.arange(T)[None, :] < ln[:, None]
        keep = valid
        for t in tokens:
            keep = keep & (v != t)
        dest = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
        dest = jnp.where(keep, dest, T)                     # T = dump slot
        bidx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
        out = jnp.zeros((B, T + 1), v.dtype).at[
            bidx.reshape(-1), dest.reshape(-1)].set(v.reshape(-1))[:, :T]
        return out, jnp.sum(keep, axis=1)

    ids, newlen = apply(fn, _t(x).detach(), _t(length).detach())
    ids.stop_gradient = True
    newlen.stop_gradient = True
    return ids, newlen


def sequence_reshape(x, length, new_dim, name=None):
    """sequence_reshape_op.cc parity: refold each sequence's valid elements
    into rows of width new_dim (length[i]*D must divide by new_dim). Padded
    representation: [B, T, D] -> [B, T*D//new_dim, new_dim] with new lengths."""
    def fn(v, ln):
        B, T, D = v.shape
        T2 = T * D // new_dim
        return v.reshape(B, T2, new_dim), (ln.astype(jnp.int32) * D) // new_dim

    out, newlen = apply(fn, _t(x), _t(length).detach())
    newlen.stop_gradient = True
    return out, newlen


def sequence_scatter(x, index, updates, length, name=None):
    """sequence_scatter_op.cc parity (padded): add updates at per-sequence
    positions. x [B, T], index [B, U] (positions within each sequence),
    updates [B, U]; entries past `length` of the update row are ignored."""
    def fn(v, ix, up, ln):
        B, T = v.shape[0], v.shape[1]
        U = ix.shape[1]
        ln = ln.astype(jnp.int32)
        valid = jnp.arange(U)[None, :] < ln[:, None]
        dest = jnp.where(valid, ix.astype(jnp.int32), T)    # T = dump slot
        bidx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, U))
        return jnp.concatenate(
            [v, jnp.zeros((B, 1) + v.shape[2:], v.dtype)], axis=1).at[
                bidx.reshape(-1), dest.reshape(-1)].add(
                    up.reshape((-1,) + up.shape[2:]))[:, :T]

    return apply(fn, _t(x), _t(index).detach(), _t(updates),
                 _t(length).detach())


def sequence_conv(x, length, weight, context_length, context_start=None,
                  bias=None, name=None):
    """sequence_conv_op.cc parity: time-dimension context-window projection.
    x [B, T, D]; weight [context_length*D, M]; out [B, T, M]. Out-of-sequence
    context rows are zero (the reference's context padding without trainable
    padding data). context_start defaults to -context_length//2."""
    if context_start is None:
        context_start = -(context_length // 2)

    args = [_t(x), _t(length).detach(), _t(weight)]
    if bias is not None:
        args.append(_t(bias))

    def fn(v, ln, w, *b):
        B, T, D = v.shape
        ln = ln.astype(jnp.int32)
        valid = jnp.arange(T)[None, :] < ln[:, None]        # [B, T]
        cols = []
        for c in range(context_length):
            shift = context_start + c
            pos = jnp.arange(T) + shift
            inb = (pos >= 0) & (pos < T)
            src = jnp.clip(pos, 0, T - 1).astype(jnp.int32)
            col = v[:, src]                                  # [B, T, D]
            ok = inb[None, :] & jnp.take(
                valid, src, axis=1)                          # [B, T]
            cols.append(col * ok[:, :, None])
        ctx = jnp.concatenate(cols, axis=-1)                 # [B, T, cl*D]
        out = ctx @ w
        if b:
            out = out + b[0]
        return out * valid[:, :, None]

    return apply(fn, *args)


def sequence_topk_avg_pooling(x, row_length, col_length, topks, channel_num,
                              name=None):
    """sequence_topk_avg_pooling_op.cc:131 parity (text-matching pooling over
    a per-sample score map): for each (row r, channel j), take the top-k
    column scores and emit their mean for every k in `topks` — the divisor is
    always k, with missing positions contributing 0, exactly the reference's
    running-sum-with-padding rule (sequence_topk_avg_pooling_op.h:150-166).

    Padded TPU form of the LoD op: x [B, channel_num, Rmax, Cmax] score maps,
    row_length/col_length [B] valid sizes; output [B, Rmax,
    channel_num * len(topks)] laid out row -> channel -> k like the
    reference's out_slice indexing, rows past row_length zeroed. The `pos`
    output (top-k indices the reference materializes for its hand-written
    grad) is not produced — autodiff differentiates the gather directly."""
    topks = [int(k) for k in topks]
    if not topks or min(topks) < 1:
        raise ValueError(f"topks must be positive ints, got {topks}")
    max_k = max(topks)

    def fn(v, rl, cl):
        B, C, R, Cm = v.shape
        if C != channel_num:
            raise ValueError(
                f"x has {C} channels but channel_num={channel_num}")
        rl32 = rl.astype(jnp.int32)
        cl32 = cl.astype(jnp.int32)
        colmask = jnp.arange(Cm)[None, :] < cl32[:, None]     # [B, Cm]
        neg = jnp.asarray(-jnp.inf, v.dtype)
        vm = jnp.where(colmask[:, None, None, :], v, neg)
        if max_k > Cm:  # shorter-than-k columns pad like the reference
            vm = jnp.pad(vm, ((0, 0),) * 3 + ((0, max_k - Cm),),
                         constant_values=neg)
        vals = jax.lax.top_k(vm, max_k)[0]                    # [B,C,R,max_k]
        vals = jnp.where(jnp.isfinite(vals), vals, 0)         # padding -> +0
        cums = jnp.cumsum(vals, axis=-1)
        outs = jnp.stack([cums[..., k - 1] / k for k in topks],
                         axis=-1)                             # [B,C,R,K]
        out = jnp.transpose(outs, (0, 2, 1, 3)).reshape(
            B, R, C * len(topks))
        rowmask = (jnp.arange(R)[None, :] < rl32[:, None]).astype(v.dtype)
        return out * rowmask[:, :, None]

    return apply(fn, _t(x), _t(row_length).detach(), _t(col_length).detach())
