"""Pooling functionals.

Reference parity: python/paddle/nn/functional/pooling.py backed by operators/pool_op.cc.
All pools lower to lax.reduce_window; adaptive pools compute per-output windows.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x))


def _ntuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    return v * n if len(v) == 1 else v


def _pool(x, kernel, stride, padding, n, op, channel_last, ceil_mode=False, exclusive=True, count_include_pad=False):
    ks = _ntuple(kernel, n)
    st = _ntuple(stride if stride is not None else kernel, n)
    pd = _ntuple(padding, n) if not isinstance(padding, str) else padding

    def fn(v):
        if channel_last:
            window = (1,) + ks + (1,)
            strides = (1,) + st + (1,)
            if isinstance(pd, str):
                pads = pd.upper()
            else:
                pads = ((0, 0),) + tuple((p, p) for p in pd) + ((0, 0),)
        else:
            window = (1, 1) + ks
            strides = (1, 1) + st
            if isinstance(pd, str):
                pads = pd.upper()
            else:
                pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pd)
        if op == "max":
            init = -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) else jnp.iinfo(v.dtype).min
            return jax.lax.reduce_window(v, init, jax.lax.max, window, strides, pads)
        # avg
        summed = jax.lax.reduce_window(v, 0.0, jax.lax.add, window, strides, pads)
        if isinstance(pads, str) or count_include_pad or not exclusive:
            denom = float(np.prod(ks))
            return summed / denom
        ones = jnp.ones_like(v)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pads)
        return summed / counts

    return apply(fn, _t(x))


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCL", name=None):
    out = _pool(x, kernel_size, stride, padding, 1, "max", data_format == "NLC", ceil_mode)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, "max", data_format == "NHWC", ceil_mode)
    if return_mask:
        idx = _max_pool_indices(x, kernel_size, stride, padding, 2)
        return out, idx
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "max", data_format == "NDHWC", ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", data_format == "NLC", ceil_mode, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg", data_format == "NHWC", ceil_mode, exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg", data_format == "NDHWC", ceil_mode, exclusive)


def _max_pool_indices(x, kernel, stride, padding, n):
    # indices of maxima within each window, flattened per spatial map (paddle semantics)
    x = _t(x)
    ks = _ntuple(kernel, n)
    st = _ntuple(stride if stride is not None else kernel, n)

    def fn(v):
        flat_idx = jnp.arange(int(np.prod(v.shape[2:]))).reshape((1, 1) + v.shape[2:]).astype(jnp.float32)
        idx_b = jnp.broadcast_to(flat_idx, v.shape)

        def reducer(a, b):
            av, ai = a
            bv, bi = b
            take_b = bv > av
            return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

        window = (1, 1) + ks
        strides = (1, 1) + st
        init = (-jnp.inf, jnp.float32(-1))
        vals, idxs = jax.lax.reduce_window((v, idx_b), init, reducer, window, strides, "VALID")
        return idxs.astype(jnp.int32)

    out = apply(fn, x.detach())
    out.stop_gradient = True
    return out


def _adaptive_windows(in_size, out_size):
    starts = [int(np.floor(i * in_size / out_size)) for i in range(out_size)]
    ends = [int(np.ceil((i + 1) * in_size / out_size)) for i in range(out_size)]
    return starts, ends


def _adaptive_pool(x, output_size, n, op, channel_last=False):
    x = _t(x)
    spatial = x.shape[2:] if not channel_last else x.shape[1:-1]
    out_size = _ntuple(output_size, n)
    out_size = tuple(s if o is None else o for s, o in zip(spatial, out_size))

    def fn(v):
        # reduce one spatial dim at a time with gathered windows
        out = v
        for d in range(n):
            axis = (2 + d) if not channel_last else (1 + d)
            in_s = out.shape[axis]
            o_s = out_size[d]
            if in_s == o_s:
                continue
            starts, ends = _adaptive_windows(in_s, o_s)
            slices = []
            for s, e in zip(starts, ends):
                win = jax.lax.slice_in_dim(out, s, e, axis=axis)
                red = jnp.max(win, axis=axis, keepdims=True) if op == "max" else jnp.mean(win, axis=axis, keepdims=True)
                slices.append(red)
            out = jnp.concatenate(slices, axis=axis)
        return out

    return apply(fn, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, "avg", data_format == "NHWC")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, "avg", data_format == "NDHWC")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, "max")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, "max")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, "max")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0, data_format="NCHW", output_size=None, name=None):
    ks = _ntuple(kernel_size, 2)
    st = _ntuple(stride if stride is not None else kernel_size, 2)

    def fn(v, idx):
        b, c, h, w = v.shape
        if output_size is not None:
            oh, ow = output_size[-2:]
        else:
            oh = (h - 1) * st[0] + ks[0]
            ow = (w - 1) * st[1] + ks[1]
        flat = jnp.zeros((b, c, oh * ow), dtype=v.dtype)
        idx_f = idx.reshape(b, c, -1).astype(jnp.int32)
        v_f = v.reshape(b, c, -1)
        bi = jnp.arange(b)[:, None, None]
        ci = jnp.arange(c)[None, :, None]
        flat = flat.at[bi, ci, idx_f].set(v_f)
        return flat.reshape(b, c, oh, ow)

    return apply(fn, _t(x), _t(indices).detach())


def spp(x, pyramid_height=3, pool_type="max", name=None):
    """spp_op parity (Spatial Pyramid Pooling): concat adaptive pools at
    2^l x 2^l bins for l in [0, pyramid_height), flattened per level."""
    outs = []
    for l in range(pyramid_height):
        bins = 2 ** l
        pooled = (adaptive_max_pool2d(x, bins) if pool_type == "max"
                  else adaptive_avg_pool2d(x, bins))
        n, c = pooled.shape[0], pooled.shape[1]
        outs.append(pooled.reshape([n, c * bins * bins]))
    from ...tensor.manipulation import concat

    return concat(outs, axis=1)
