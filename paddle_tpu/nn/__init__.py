"""paddle.nn parity surface (python/paddle/nn/__init__.py)."""


class ParamAttr:
    """python/paddle/fluid/param_attr.py ParamAttr parity."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0, regularizer=None,
                 trainable=True, do_model_average=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip


from . import functional  # noqa: E402,F401
from . import initializer  # noqa: E402,F401
from .layer.layers import Layer  # noqa: E402,F401
from .layer.activation import (  # noqa: E402,F401
    CELU, ELU, GELU, GLU, Hardshrink, Hardsigmoid, Hardswish, Hardtanh, LeakyReLU,
    LogSigmoid, LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6, RReLU, SELU, Sigmoid,
    Silu, Softmax, Softplus, Softshrink, Softsign, Swish, Tanh, Tanhshrink,
    ThresholdedReLU,
)
from .layer.common import (  # noqa: E402,F401
    AlphaDropout, Bilinear, CosineSimilarity, Dropout, Dropout2D, Dropout3D, Embedding,
    Flatten, Identity, LayerList, Linear, Pad1D, Pad2D, Pad3D, PairwiseDistance,
    ChannelShuffle, Fold, ParameterList, PixelShuffle, PixelUnshuffle, Sequential, Unfold, Upsample,
    UpsamplingBilinear2D, UpsamplingNearest2D, ZeroPad2D,
)
from .layer.conv import (  # noqa: E402,F401
    Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D, Conv3DTranspose,
)
from .layer.loss import (  # noqa: E402,F401
    BCELoss, BCEWithLogitsLoss, CosineEmbeddingLoss, CrossEntropyLoss, CTCLoss,
    HingeEmbeddingLoss, HSigmoidLoss, KLDivLoss, L1Loss, MarginRankingLoss, MSELoss,
    NLLLoss, SmoothL1Loss, TripletMarginLoss,
)
from .layer.norm import (  # noqa: E402,F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm, InstanceNorm1D,
    InstanceNorm2D, InstanceNorm3D, LayerNorm, LocalResponseNorm, SpectralNorm,
    SyncBatchNorm,
)
from .layer.pooling import (  # noqa: E402,F401
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D, AdaptiveMaxPool1D,
    AdaptiveMaxPool2D, AdaptiveMaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D, MaxPool1D,
    MaxPool2D, MaxPool3D, MaxUnPool2D,
)
from .layer.rnn import (  # noqa: E402,F401
    BiRNN, GRU, GRUCell, LSTM, LSTMCell, RNN, RNNCellBase, SimpleRNN, SimpleRNNCell,
)
from .layer.transformer import (  # noqa: E402,F401
    MultiHeadAttention, Transformer, TransformerDecoder, TransformerDecoderLayer,
    TransformerEncoder, TransformerEncoderLayer,
)
from .layer.moe import MoELayer  # noqa: E402,F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: E402,F401
from .utils_weight_norm import remove_weight_norm, weight_norm  # noqa: E402,F401

# reference exposes the layer submodules at paddle.nn.<name> (nn/__init__.py
# imports them); alias ours so `from paddle.nn import loss` style works
from .layer import common, conv, loss, norm, rnn  # noqa: E402,F401
from .functional import extension  # noqa: E402,F401
from ..vision import ops as vision  # noqa: E402,F401
from .utils_weight_norm import weight_norm as weight_norm_hook  # noqa: E402,F401
from .decode import BeamSearchDecoder, dynamic_decode  # noqa: E402,F401
