"""Gradient clipping (python/paddle/fluid/clip.py parity: ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm). Applied by optimizers before the update step."""
import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(g._data.astype(jnp.float32) ** 2))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._data * scale).astype(g.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def __call__(self, params_grads):
        sq = 0.0
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            sq = sq + jnp.sum(g._data.astype(jnp.float32) ** 2)
        global_norm = jnp.sqrt(sq)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor((g._data * scale).astype(g.dtype))))
        return out


GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._data)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack([jnp.sum(jnp.abs(g._data.astype(jnp.float32)) ** norm_type) for g in grads])) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._data = (p.grad._data * scale).astype(p.grad.dtype)
    return Tensor(total)
