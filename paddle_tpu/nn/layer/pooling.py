"""Pooling layers (python/paddle/nn/layer/pooling.py parity)."""
from .. import functional as F
from .layers import Layer


class _Pool(Layer):
    def __init__(self, fn, kernel_size=None, stride=None, padding=0, **kwargs):
        super().__init__()
        self._fn = fn
        self._kernel_size = kernel_size
        self._stride = stride
        self._padding = padding
        self._kwargs = {k: v for k, v in kwargs.items() if k != "name"}

    def forward(self, x):
        return self._fn(x, self._kernel_size, self._stride, self._padding, **self._kwargs)


class MaxPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
        super().__init__(F.max_pool1d, kernel_size, stride, padding, return_mask=return_mask, ceil_mode=ceil_mode)


class MaxPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
        super().__init__(F.max_pool2d, kernel_size, stride, padding, return_mask=return_mask, ceil_mode=ceil_mode, data_format=data_format)


class MaxPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
        super().__init__(F.max_pool3d, kernel_size, stride, padding, return_mask=return_mask, ceil_mode=ceil_mode, data_format=data_format)


class AvgPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
        super().__init__(F.avg_pool1d, kernel_size, stride, padding, exclusive=exclusive, ceil_mode=ceil_mode)


class AvgPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
        super().__init__(F.avg_pool2d, kernel_size, stride, padding, ceil_mode=ceil_mode, exclusive=exclusive, data_format=data_format)


class AvgPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
        super().__init__(F.avg_pool3d, kernel_size, stride, padding, ceil_mode=ceil_mode, exclusive=exclusive, data_format=data_format)


class _AdaptivePool(Layer):
    def __init__(self, fn, output_size, **kwargs):
        super().__init__()
        self._fn = fn
        self._output_size = output_size
        self._kwargs = {k: v for k, v in kwargs.items() if k != "name"}

    def forward(self, x):
        return self._fn(x, self._output_size, **self._kwargs)


class AdaptiveAvgPool1D(_AdaptivePool):
    def __init__(self, output_size, name=None):
        super().__init__(F.adaptive_avg_pool1d, output_size)


class AdaptiveAvgPool2D(_AdaptivePool):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__(F.adaptive_avg_pool2d, output_size, data_format=data_format)


class AdaptiveAvgPool3D(_AdaptivePool):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__(F.adaptive_avg_pool3d, output_size, data_format=data_format)


class AdaptiveMaxPool1D(_AdaptivePool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(F.adaptive_max_pool1d, output_size, return_mask=return_mask)


class AdaptiveMaxPool2D(_AdaptivePool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(F.adaptive_max_pool2d, output_size, return_mask=return_mask)


class AdaptiveMaxPool3D(_AdaptivePool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(F.adaptive_max_pool3d, output_size, return_mask=return_mask)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, osz = self.args
        return F.max_unpool2d(x, indices, k, s, p, df, osz)
