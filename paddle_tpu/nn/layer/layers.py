"""Layer: the module system.

Reference parity: python/paddle/fluid/dygraph/layers.py (Layer — parameter/buffer/sublayer
registration via __setattr__, state_dict, hooks, train/eval, to_static_state) and
framework.py:5430 ParamBase.

TPU-native addition: `functional_state` / `functional_call` give a pure view
(params+buffers pytree -> outputs) so any Layer drops into jax.jit/grad/pjit unchanged —
this is the bridge between the stateful dygraph API and XLA's functional world.
"""
import collections

import numpy as np

from ...core import dtype as dtype_mod
from ...core.tensor import ParamBase, Tensor
from .. import initializer as I


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self.training = True
        self._dtype = dtype_mod.convert_dtype(dtype)
        self._name = name_scope or self.__class__.__name__.lower()

    # ---- registration --------------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, ParamBase):
            if params is None:
                raise RuntimeError("call super().__init__() before assigning parameters")
            params[name] = value
            for d in (layers, buffers):
                if d is not None and name in d:
                    del d[name]
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            layers[name] = value
            for d in (params, buffers):
                if d is not None and name in d:
                    del d[name]
            self.__dict__.pop(name, None)
        else:
            for d in (params, layers, buffers):
                if d is not None and name in d:
                    del d[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ):
        """fluid/dygraph/layers.py create_parameter parity (ParamAttr handling)."""
        from .. import ParamAttr

        dtype = dtype_mod.convert_dtype(dtype) or self._dtype
        init = default_initializer
        name = None
        trainable = True
        if isinstance(attr, ParamAttr):
            name = attr.name
            trainable = attr.trainable
            if attr.initializer is not None:
                init = attr.initializer
        elif attr is False:
            return None
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        data = init(tuple(shape), dtype)
        p = ParamBase(data, dtype=dtype, name=name, trainable=trainable)
        return p

    def create_tensor(self, name=None, persistable=False, dtype=None):
        import jax.numpy as jnp

        return Tensor(jnp.zeros((), dtype=dtype_mod.convert_dtype(dtype) or self._dtype))

    # ---- traversal -----------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else prefix + "." + name), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = prefix + lname if not prefix else prefix + "." + lname
                for n, p in layer.named_parameters(prefix=sub_prefix):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (prefix + name if not prefix else prefix + "." + name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = prefix + lname if not prefix else prefix + "." + lname
                yield from layer.named_buffers(prefix=sub_prefix)

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for _, l in self._sub_layers.items():
            if l is not None:
                out.extend(l.sublayers(include_self=True))
        return out

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            if l is None:
                continue
            sub_prefix = prefix + name if not prefix else prefix + "." + name
            yield from l.named_sublayers(prefix=sub_prefix, include_self=True)

    def children(self):
        return [l for _, l in self.named_children()]

    def named_children(self):
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # ---- mode ----------------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # ---- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True):
        out = destination if destination is not None else collections.OrderedDict()
        for n, p in self.named_parameters(include_sublayers=include_sublayers):
            out[n] = p
        for n, b in self.named_buffers(include_sublayers=include_sublayers):
            leaf = n.rsplit(".", 1)[-1]
            if leaf not in self._non_persistable_buffer_names:
                out[n] = b
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing = []
        for k, v in state_dict.items():
            if k in own:
                arr = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
                own[k].set_value(arr.astype(own[k].numpy().dtype))
            else:
                missing.append(k)
        return missing

    set_dict = set_state_dict
    load_dict = set_state_dict

    # ---- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        handle = _HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[handle._id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[handle._id] = hook
        return handle

    # ---- call ----------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    # ---- functional bridge (TPU-native) --------------------------------------
    def functional_state(self):
        """Return (params, buffers) as flat dicts of raw jax arrays."""
        params = {n: p._data for n, p in self.named_parameters()}
        buffers = {n: b._data for n, b in self.named_buffers()}
        return params, buffers

    def functional_call(self, params, inputs, buffers=None, training=None):
        """Run forward with `params` (+buffers) substituted — pure w.r.t. the arrays.

        Safe under jax tracing: original array refs are restored afterwards.
        """
        named_p = dict(self.named_parameters())
        named_b = dict(self.named_buffers())
        saved = {n: t._data for n, t in {**named_p, **named_b}.items()}
        saved_mode = self.training
        try:
            if training is not None:
                self.training = training
                for l in self.sublayers():
                    l.training = training
            for n, v in (params or {}).items():
                if n in named_p:
                    named_p[n]._data = v
            for n, v in (buffers or {}).items():
                if n in named_b:
                    named_b[n]._data = v
            if isinstance(inputs, (list, tuple)):
                return self.forward(*inputs)
            return self.forward(inputs)
        finally:
            for n, t in {**named_p, **named_b}.items():
                t._data = saved[n]
            self.training = saved_mode
            for l in self.sublayers():
                l.training = saved_mode

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = dtype_mod.convert_dtype(dtype)
            for p in self.parameters():
                p._data = p._data.astype(d)
        return self

    def full_name(self):
        return self._name

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [self.__class__.__name__ + "(" + extra]
        for name, l in self._sub_layers.items():
            rep = repr(l).replace("\n", "\n  ")
            lines.append(f"  ({name}): {rep}")
        return "\n".join(lines) + ")" if len(lines) > 1 else lines[0] + ")"


class _HookRemoveHelper:
    _next_id = [0]

    def __init__(self, hooks):
        self._hooks = hooks
        self._id = _HookRemoveHelper._next_id[0]
        _HookRemoveHelper._next_id[0] += 1

    def remove(self):
        self._hooks.pop(self._id, None)
