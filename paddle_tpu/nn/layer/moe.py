"""MoE Layer — expert-parallel mixture-of-experts FFN.

No reference equivalent (SURVEY.md §2.3: expert parallelism ABSENT in
thisjiang/Paddle); beyond-reference TPU-native capability. The math lives in
paddle_tpu/distributed/moe.py; this Layer holds the parameters (gate + stacked
expert weights, MXU-friendly [E, d, dff] layout) and exposes the single-shard
dense path by default, or the shard_map expert-parallel path when given a mesh
with an 'ep' axis.
"""
import functools

import jax

from ...core.dispatch import apply
from ...distributed import moe as moe_ops
from .. import initializer as I
from .layers import Layer


class MoELayer(Layer):
    """Top-k gated mixture of expert FFNs over the last dim.

    Input [*, d_model] is flattened to tokens, routed through `num_experts`
    FFNs (d_model -> d_ff -> d_model) with static capacity
    ceil(k*T/E*capacity_factor), and recombined. `self.aux_loss` holds the
    GShard load-balance loss of the last forward (add it to the train loss).
    """

    def __init__(self, d_model, d_ff, num_experts, k=2, capacity_factor=2.0,
                 activation="gelu", mesh=None, ep_axis="ep"):
        super().__init__()
        self.d_model = d_model
        self.d_ff = d_ff
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.mesh = mesh
        self.ep_axis = ep_axis
        self._act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
                     "silu": jax.nn.silu}[activation]

        self.gate_weight = self.create_parameter(
            [d_model, num_experts], default_initializer=I.XavierNormal(
                fan_in=d_model, fan_out=num_experts))
        self.w1 = self.create_parameter(
            [num_experts, d_model, d_ff], default_initializer=I.XavierNormal(
                fan_in=d_model, fan_out=d_ff))
        self.b1 = self.create_parameter([num_experts, d_ff], is_bias=True)
        self.w2 = self.create_parameter(
            [num_experts, d_ff, d_model], default_initializer=I.XavierNormal(
                fan_in=d_ff, fan_out=d_model))
        self.b2 = self.create_parameter([num_experts, d_model], is_bias=True)
        self.aux_loss = None

    def forward(self, x):
        lead = x.shape[:-1]
        d = x.shape[-1]

        if self.mesh is not None and self.ep_axis in self.mesh.axis_names:
            fn = functools.partial(
                _moe_flat_spmd, mesh=self.mesh, k=self.k,
                capacity_factor=self.capacity_factor, activation=self._act,
                axis_name=self.ep_axis, lead=tuple(lead), d=d)
        else:
            fn = functools.partial(
                _moe_flat_dense, k=self.k, capacity_factor=self.capacity_factor,
                activation=self._act, lead=tuple(lead), d=d)
        out, aux = apply(fn, x, self.gate_weight, self.w1, self.b1, self.w2,
                         self.b2, n_outputs=2)
        self.aux_loss = aux
        return out

    def extra_repr(self):
        return (f"d_model={self.d_model}, d_ff={self.d_ff}, "
                f"num_experts={self.num_experts}, k={self.k}")


def _moe_flat_dense(x, gate_w, w1, b1, w2, b2, *, k, capacity_factor, activation,
                    lead, d):
    xt = x.reshape(-1, d)
    out, aux = moe_ops.moe_dense(xt, gate_w, w1, b1, w2, b2, k=k,
                                 capacity_factor=capacity_factor,
                                 activation=activation)
    return out.reshape(*lead, d), aux


def _moe_flat_spmd(x, gate_w, w1, b1, w2, b2, *, mesh, k, capacity_factor,
                   activation, axis_name, lead, d):
    xt = x.reshape(-1, d)
    out, aux = moe_ops.expert_parallel_moe(
        xt, gate_w, w1, b1, w2, b2, mesh, k=k, capacity_factor=capacity_factor,
        activation=activation, axis_name=axis_name)
    return out.reshape(*lead, d), aux
