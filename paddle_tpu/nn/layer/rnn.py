"""RNN layers (python/paddle/nn/layer/rnn.py parity): SimpleRNNCell/LSTMCell/GRUCell,
RNN/BiRNN wrappers, SimpleRNN/LSTM/GRU multi-layer nets.

TPU-native design: the whole sequence loop is ONE lax.scan inside one autodiff apply()
(the reference runs cuDNN fused kernels, operators/cudnn_lstm_op.cu.cc; scan+matmul gets
the same fusion from XLA without a hand-written kernel). Gate weight layout matches
paddle: weight_ih [gates*hidden, input], weight_hh [gates*hidden, hidden].
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply
from ...core.tensor import Tensor
from .. import functional as F  # noqa: F401
from .. import initializer as I
from .layers import Layer


def _cell_params(layer, input_size, hidden_size, gates, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
    std = 1.0 / math.sqrt(hidden_size)
    layer.weight_ih = layer.create_parameter([gates * hidden_size, input_size], attr=weight_ih_attr, default_initializer=I.Uniform(-std, std))
    layer.weight_hh = layer.create_parameter([gates * hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=I.Uniform(-std, std))
    layer.bias_ih = layer.create_parameter([gates * hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=I.Uniform(-std, std))
    layer.bias_hh = layer.create_parameter([gates * hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=I.Uniform(-std, std))


def _simple_rnn_step(x, h, w_ih, w_hh, b_ih, b_hh, activation="tanh"):
    z = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    return jnp.tanh(z) if activation == "tanh" else jax.nn.relu(z)


def _lstm_step(x, hc, w_ih, w_hh, b_ih, b_hh):
    h, c = hc
    z = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


def _gru_step(x, h, w_ih, w_hh, b_ih, b_hh):
    xz = x @ w_ih.T + b_ih
    hz = h @ w_hh.T + b_hh
    xr, xu, xn = jnp.split(xz, 3, axis=-1)
    hr, hu, hn = jnp.split(hz, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    u = jax.nn.sigmoid(xu + hu)
    n = jnp.tanh(xn + r * hn)
    return (1 - u) * n + u * h


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        h = Tensor(jnp.full((batch, self.hidden_size), init_value, dtype=jnp.float32))
        if getattr(self, "state_components", 1) == 2:
            c = Tensor(jnp.full((batch, self.hidden_size), init_value, dtype=jnp.float32))
            return h, c
        return h


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        self.state_components = 1
        _cell_params(self, input_size, hidden_size, 1, weight_ih_attr, weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out = apply(
            lambda x, h, wi, wh, bi, bh: _simple_rnn_step(x, h, wi, wh, bi, bh, self.activation),
            inputs, states, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh,
        )
        return out, out


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.state_components = 2
        _cell_params(self, input_size, hidden_size, 4, weight_ih_attr, weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states
        h_new, c_new = apply(
            lambda x, hh, cc, wi, wh, bi, bh: _lstm_step(x, (hh, cc), wi, wh, bi, bh),
            inputs, h, c, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh,
        )
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.state_components = 1
        _cell_params(self, input_size, hidden_size, 3, weight_ih_attr, weight_hh_attr, bias_ih_attr, bias_hh_attr)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out = apply(
            _gru_step, inputs, states, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh,
        )
        return out, out


class RNN(Layer):
    """Runs a cell over a sequence with lax.scan (layer/rnn.py RNN parity)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        cell = self.cell
        mode = {SimpleRNNCell: "rnn", LSTMCell: "lstm", GRUCell: "gru"}[type(cell)]
        act = getattr(cell, "activation", "tanh")
        batch_axis = 1 if self.time_major else 0

        x = inputs
        if initial_states is None:
            ref = x
            batch = x.shape[0 if not self.time_major else 1]
            h0 = Tensor(jnp.zeros((batch, cell.hidden_size), dtype=jnp.float32))
            initial_states = (h0, Tensor(jnp.zeros((batch, cell.hidden_size), dtype=jnp.float32))) if mode == "lstm" else h0

        states = list(initial_states) if isinstance(initial_states, (tuple, list)) else [initial_states]
        rev = self.is_reverse
        tm = self.time_major

        def fn(xv, *rest):
            sts = rest[: len(states)]
            wi, wh, bi, bh = rest[len(states) :]
            seq = xv if tm else jnp.swapaxes(xv, 0, 1)  # [T, B, D]
            if rev:
                seq = jnp.flip(seq, axis=0)

            def step(carry, xt):
                if mode == "lstm":
                    h_new, c_new = _lstm_step(xt, carry, wi, wh, bi, bh)
                    return (h_new, c_new), h_new
                if mode == "gru":
                    h_new = _gru_step(xt, carry[0], wi, wh, bi, bh)
                    return (h_new,), h_new
                h_new = _simple_rnn_step(xt, carry[0], wi, wh, bi, bh, act)
                return (h_new,), h_new

            carry0 = tuple(sts)
            carry, outs = jax.lax.scan(step, carry0, seq)
            if rev:
                outs = jnp.flip(outs, axis=0)
            if not tm:
                outs = jnp.swapaxes(outs, 0, 1)
            return (outs,) + carry

        results = apply(fn, x, *states, cell.weight_ih, cell.weight_hh, cell.bias_ih, cell.bias_hh)
        outs = results[0]
        final = results[1:]
        if mode == "lstm":
            return outs, (final[0], final[1])
        return outs, final[0]


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        states_fw, states_bw = (initial_states if initial_states is not None else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, states_fw)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw)
        from ...tensor.manipulation import concat

        out = concat([out_fw, out_bw], axis=-1)
        return out, (st_fw, st_bw)


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        bidirect = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if bidirect else 1
        self.state_components = 2 if mode == "lstm" else 1

        def make_cell(in_size):
            if mode == "lstm":
                return LSTMCell(in_size, hidden_size, weight_ih_attr, weight_hh_attr, bias_ih_attr, bias_hh_attr)
            if mode == "gru":
                return GRUCell(in_size, hidden_size, weight_ih_attr, weight_hh_attr, bias_ih_attr, bias_hh_attr)
            return SimpleRNNCell(in_size, hidden_size, activation, weight_ih_attr, weight_hh_attr, bias_ih_attr, bias_hh_attr)

        from .common import LayerList

        self.rnns = LayerList()
        for layer_i in range(num_layers):
            in_size = input_size if layer_i == 0 else hidden_size * self.num_directions
            if bidirect:
                self.rnns.append(BiRNN(make_cell(in_size), make_cell(in_size), time_major))
            else:
                self.rnns.append(RNN(make_cell(in_size), time_major=time_major))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import stack

        x = inputs
        finals_h = []
        finals_c = []
        for i, rnn_l in enumerate(self.rnns):
            x, st = rnn_l(x)
            if self.dropout > 0 and i < self.num_layers - 1:
                x = F.dropout(x, p=self.dropout, training=self.training)
            if self.num_directions == 2:
                st_fw, st_bw = st
                if self.mode == "lstm":
                    finals_h += [st_fw[0], st_bw[0]]
                    finals_c += [st_fw[1], st_bw[1]]
                else:
                    finals_h += [st_fw, st_bw]
            else:
                if self.mode == "lstm":
                    finals_h.append(st[0])
                    finals_c.append(st[1])
                else:
                    finals_h.append(st)
        h = stack(finals_h, axis=0)
        if self.mode == "lstm":
            c = stack(finals_c, axis=0)
            return x, (h, c)
        return x, h


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        super().__init__("rnn", input_size, hidden_size, num_layers, direction, time_major, dropout, activation, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("lstm", input_size, hidden_size, num_layers, direction, time_major, dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("gru", input_size, hidden_size, num_layers, direction, time_major, dropout, **kwargs)
