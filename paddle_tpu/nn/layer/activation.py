"""Activation layers (python/paddle/nn/layer/activation.py parity — 23 classes)."""
from .. import functional as F
from .. import initializer as I
from .layers import Layer


def _simple(fn_name, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kwargs = {**fixed}
            # map positional args onto the functional's keyword names in order
            names = [k for k in _ARG_NAMES.get(fn_name, [])]
            for n, v in zip(names, args):
                self._kwargs[n] = v
            for k, v in kwargs.items():
                if k != "name":
                    self._kwargs[k] = v

        def forward(self, x):
            return getattr(F, fn_name)(x, **self._kwargs)

    _Act.__name__ = fn_name.title().replace("_", "")
    return _Act


_ARG_NAMES = {
    "elu": ["alpha"],
    "gelu": ["approximate"],
    "hardshrink": ["threshold"],
    "hardtanh": ["min", "max"],
    "hardsigmoid": [],
    "leaky_relu": ["negative_slope"],
    "log_softmax": ["axis"],
    "maxout": ["groups", "axis"],
    "softmax": ["axis"],
    "softplus": ["beta", "threshold"],
    "softshrink": ["threshold"],
    "thresholded_relu": ["threshold"],
    "celu": ["alpha"],
}

ELU = _simple("elu")
GELU = _simple("gelu")
Hardshrink = _simple("hardshrink")
Hardswish = _simple("hardswish")
Tanh = _simple("tanh")
Hardtanh = _simple("hardtanh")
ReLU = _simple("relu")
ReLU6 = _simple("relu6")
SELU = _simple("selu")
CELU = _simple("celu")
LeakyReLU = _simple("leaky_relu")
Sigmoid = _simple("sigmoid")
Hardsigmoid = _simple("hardsigmoid")
Softplus = _simple("softplus")
Softshrink = _simple("softshrink")
Softsign = _simple("softsign")
Swish = _simple("swish")
Silu = _simple("silu")
Mish = _simple("mish")
Tanhshrink = _simple("tanhshrink")
ThresholdedReLU = _simple("thresholded_relu")
LogSigmoid = _simple("log_sigmoid")
Softmax = _simple("softmax")
LogSoftmax = _simple("log_softmax")
Maxout = _simple("maxout")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_parameters], attr=weight_attr, default_initializer=I.Constant(init)
        )

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self._data_format)


class RReLU(Layer):
    def __init__(self, lower=0.125, upper=0.3333333333333333, name=None):
        super().__init__()
        self.lower = lower
        self.upper = upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, axis=self.axis)
