"""Transformer layers.

Reference parity: python/paddle/nn/layer/transformer.py (MultiHeadAttention:83,
TransformerEncoderLayer, TransformerEncoder, TransformerDecoderLayer,
TransformerDecoder, Transformer). Attention routes through
functional/transformer.scaled_dot_product_attention (Pallas flash kernel on TPU).
"""
import numpy as np

from ...core.tensor import Tensor
from .. import functional as F
from .common import Linear, Dropout, LayerList
from .layers import Layer
from .norm import LayerNorm


def _convert_attn_mask(attn_mask, q_len=None, k_len=None):
    """Normalize user masks for SDPA ([b, heads, q, k] broadcast space).

    2-D masks are ambiguous: paddle's documented form is a [q, k] score mask
    (broadcasts right-aligned, bool = keep / float = additive), while the
    HF/BERT convention is a [b, s] key-padding keep-mask. Disambiguate by
    shape: an exact (q_len, k_len) match keeps paddle semantics (pass
    through; this wins the square b==q==s tie for backward compat);
    otherwise a trailing k_len means key-padding and expands to bool
    [b, 1, 1, s] — previously such masks were silently ADDED as 0/1.
    Richer (>=3-D) masks pass through."""
    if attn_mask is None:
        return None
    m = attn_mask
    if m.ndim == 2:
        import jax.numpy as jnp

        if q_len is not None and tuple(m.shape) == (q_len, k_len):
            return m  # paddle [q, k] score mask
        if jnp.issubdtype(jnp.asarray(m._data).dtype, jnp.floating):
            return m  # float 2-D mask: additive semantics, broadcast as-is
        if k_len is None or m.shape[-1] == k_len:
            return m.astype("bool").unsqueeze(1).unsqueeze(2)
    return m


class MultiHeadAttention(Layer):
    """layer/transformer.py:83 parity. q/k/v projections -> [B, S, H, D] -> fused SDPA."""

    Cache = None

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        assert self.head_dim * num_heads == embed_dim

        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = query if value is None else value
        b, sq = query.shape[0], query.shape[1]
        q = self.q_proj(query).reshape([b, sq, self.num_heads, self.head_dim])
        k = self.k_proj(key).reshape([b, key.shape[1], self.num_heads, self.head_dim])
        v = self.v_proj(value).reshape([b, value.shape[1], self.num_heads, self.head_dim])

        if cache is not None:
            from ...tensor.manipulation import concat

            k = concat([cache[0], k], axis=1)
            v = concat([cache[1], v], axis=1)
            new_cache = (k, v)

        out = F.scaled_dot_product_attention(
            q, k, v,
            attn_mask=_convert_attn_mask(attn_mask, q_len=sq,
                                         k_len=k.shape[1]),
            dropout_p=self.dropout if self.training else 0.0, training=self.training,
        )
        out = out.reshape([b, sq, self.embed_dim])
        out = self.out_proj(out)
        if cache is not None:
            return out, new_cache
        return out

    def gen_cache(self, key, value=None, type=None):
        import jax.numpy as jnp

        b = key.shape[0]
        k0 = Tensor(jnp.zeros((b, 0, self.num_heads, self.head_dim), dtype=jnp.float32))
        v0 = Tensor(jnp.zeros((b, 0, self.num_heads, self.head_dim), dtype=jnp.float32))
        return (k0, v0)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1, activation="relu",
                 attn_dropout=None, act_dropout=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                            weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, incremental_cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, incremental_cache)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([encoder_layer] + [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask)
            else:
                output, new_cache = mod(output, src_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1, activation="relu",
                 attn_dropout=None, act_dropout=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout, weight_attr=weight_attr, bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout, weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask, cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incremental_cache,))


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([decoder_layer] + [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask, memory_mask)
            else:
                output, new_cache = mod(output, memory, tgt_mask, memory_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)


class Transformer(Layer):
    """layer/transformer.py Transformer parity."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6, num_decoder_layers=6,
                 dim_feedforward=2048, dropout=0.1, activation="relu", attn_dropout=None,
                 act_dropout=None, normalize_before=False, weight_attr=None,
                 bias_attr=None, custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(d_model, nhead, dim_feedforward, dropout,
                                               activation, attn_dropout, act_dropout,
                                               normalize_before, weight_attr, bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers, enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(d_model, nhead, dim_feedforward, dropout,
                                               activation, attn_dropout, act_dropout,
                                               normalize_before, weight_attr, bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers, dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    def generate_square_subsequent_mask(self, length):
        import jax.numpy as jnp

        m = jnp.where(jnp.tril(jnp.ones((length, length), dtype=bool)), 0.0, -np.inf).astype(jnp.float32)
        return Tensor(m)
