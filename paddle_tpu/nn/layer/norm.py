"""Norm layers (python/paddle/nn/layer/norm.py parity): BatchNorm1D/2D/3D, SyncBatchNorm,
LayerNorm, GroupNorm, InstanceNorm1D/2D/3D, LocalResponseNorm, SpectralNorm."""
import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(shape=[num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(shape=[num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features, dtype=jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features, dtype=jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format, use_global_stats=self._use_global_stats,
        )


class BatchNorm(_BatchNormBase):
    """fluid/dygraph/nn.py BatchNorm legacy alias."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5, **kwargs):
        super().__init__(num_channels, momentum=momentum, epsilon=epsilon)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act == "relu":
            out = F.relu(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """operators/sync_batch_norm_op.cu parity.

    TPU-native: inside pjit/shard_map the batch axis is mesh-sharded, and the mean/var
    reductions become cross-replica automatically (XLA inserts the psum); eager
    single-process falls back to local stats. convert_sync_batchnorm mirrors
    python/paddle/nn/layer/norm.py:1059.
    """

    def forward(self, x):
        from ...distributed import collective as C

        if C.in_spmd_context():
            # functional cross-replica stats: psum over the data-parallel axis
            return C.sync_batch_norm(
                x, self._mean, self._variance, self.weight, self.bias,
                training=self.training, momentum=self._momentum,
                epsilon=self._epsilon, data_format=self._data_format,
            )
        return super().forward(x)

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon,
                                data_format=layer._data_format)
            if layer.weight is not None:
                out.weight.set_value(layer.weight.numpy())
                out.bias.set_value(layer.bias.numpy())
            out._mean.set_value(layer._mean.numpy())
            out._variance.set_value(layer._variance.numpy())
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(shape=self._normalized_shape, attr=weight_attr, default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(shape=self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(shape=[num_channels], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(shape=[num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(shape=[num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(shape=[num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """layer/norm.py SpectralNorm (power-iteration weight normalization)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, name=None):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        import numpy as np

        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.register_buffer("weight_u", Tensor(jnp.asarray(np.random.randn(h).astype(np.float32))))
        self.register_buffer("weight_v", Tensor(jnp.asarray(np.random.randn(w).astype(np.float32))))

    def forward(self, weight):
        from ...core.dispatch import apply

        dim = self._dim
        eps = self._eps
        iters = self._power_iters
        u0 = self.weight_u._data
        v0 = self.weight_v._data

        def fn(w):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            u, v = u0, v0
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma

        return apply(fn, weight)
