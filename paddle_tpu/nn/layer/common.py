"""Common layers (python/paddle/nn/layer/common.py parity): Linear, Embedding, Dropout,
Pad, Upsample, Bilinear, CosineSimilarity, Flatten, etc."""
import numpy as np

from ... import tensor as pt
from .. import functional as F
from .. import initializer as I
from .layers import Layer


class Linear(Layer):
    """python/paddle/nn/layer/common.py Linear — weight [in, out] (matmul-ready for MXU)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=None if (weight_attr and getattr(weight_attr, "initializer", None)) else I.XavierNormal(fan_in=in_features, fan_out=out_features),
        )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(shape=[out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    """layer/common.py Embedding (lookup_table_v2)."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx if (padding_idx is None or padding_idx >= 0) else num_embeddings + padding_idx
        self._sparse = sparse
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierNormal() if not (weight_attr and getattr(weight_attr, "initializer", None)) else None,
        )
        if self._padding_idx is not None:
            arr = np.asarray(self.weight.numpy())
            arr[self._padding_idx] = 0
            self.weight.set_value(arr)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx, sparse=self._sparse)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training, mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training, data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training, data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode, self.align_corners, self.align_mode, self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(shape=[out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(shape=[out_features], attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        d = pt.norm(x - y + self.epsilon, p=self.p, axis=-1, keepdim=self.keepdim)
        return d


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        return pt.flatten(x, self.start_axis, self.stop_axis)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad2D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    """Inverse of Unfold (col2im; reference fold/col2im kernels)."""

    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.args = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, *self.args)


class ChannelShuffle(Layer):
    """channel_shuffle_op parity (ShuffleNet block primitive)."""

    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class LayerList(Layer):
    """fluid/dygraph/container.py LayerList parity."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return list(self._sub_layers.values())[idx]
        if idx < 0:
            idx += len(self)
        return self._sub_layers[str(idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class Sequential(Layer):
    """fluid/dygraph/container.py Sequential parity."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and layers and isinstance(layers[0][0], tuple):
            for name, layer in layers[0]:
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                if isinstance(layer, tuple):
                    self.add_sublayer(layer[0], layer[1])
                else:
                    self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self)), parameter)
        return self
