"""Beam-search decoding (python/paddle/nn/decode.py BeamSearchDecoder +
dynamic_decode parity; reference beam_search_op.cc / beam_search_decode_op.cc).

TPU-native stance: the beam dimension is folded into batch ([B*K, ...]) so the
cell runs one MXU-friendly batched step per time step; the per-step top-k over
(beam x vocab) and the final backtrace (gather_tree) are the same primitives
the compiled beam ops use. dynamic_decode drives the loop eagerly — decode is
an inference utility with data-dependent termination (every step's `finished`
is reduced on host, like the reference's while_op + is_empty check).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.functional.extension import gather_tree

__all__ = ["BeamSearchDecoder", "dynamic_decode"]


def _raw(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class BeamSearchDecoder:
    """Wraps an RNN cell for beam search. `embedding_fn` maps token ids
    [B*K] -> embeddings [B*K, D]; `output_fn` maps cell outputs to vocab
    logits (identity when the cell already emits logits)."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- state helpers: states are Tensors or (nested) tuples of Tensors ----
    def _map_state(self, states, fn):
        if isinstance(states, (tuple, list)):
            return type(states)(self._map_state(s, fn) for s in states)
        return Tensor(fn(_raw(states)))

    def tile_beam_merge_with_batch(self, t):
        """[B, ...] -> [B*K, ...] (repeat each batch row beam_size times)."""
        K = self.beam_size

        def f(v):
            return jnp.repeat(v, K, axis=0)

        return self._map_state(t, f)

    def initialize(self, initial_cell_states):
        states = self.tile_beam_merge_with_batch(initial_cell_states)
        first = initial_cell_states
        while isinstance(first, (tuple, list)):
            first = first[0]
        B = _raw(first).shape[0]
        K = self.beam_size
        ids = np.full((B, K), self.start_token, np.int64)
        # only beam 0 is live initially so the K start tokens don't duplicate
        log_probs = np.full((B, K), -1e9, np.float32)
        log_probs[:, 0] = 0.0
        finished = np.zeros((B, K), bool)
        return ids, states, log_probs, finished

    def step(self, ids, states, log_probs, finished):
        """One beam step. Returns (next_ids, parent_idx, next_states,
        next_log_probs, next_finished)."""
        B, K = ids.shape
        flat_ids = Tensor(jnp.asarray(ids.reshape(-1)))
        inputs = (self.embedding_fn(flat_ids) if self.embedding_fn is not None
                  else flat_ids)
        cell_out, next_states = self.cell(inputs, states)
        logits = self.output_fn(cell_out) if self.output_fn is not None else cell_out
        logp = np.asarray(jax.nn.log_softmax(_raw(logits), axis=-1))  # [B*K, V]
        V = logp.shape[-1]
        logp = logp.reshape(B, K, V)
        # finished beams emit only end_token with probability 1
        fin_row = np.full(V, -1e9, np.float32)
        fin_row[self.end_token] = 0.0
        logp = np.where(finished[:, :, None], fin_row[None, None, :], logp)
        total = log_probs[:, :, None] + logp                   # [B, K, V]
        flat = total.reshape(B, K * V)
        top_idx = np.argsort(-flat, axis=1, kind="stable")[:, :K]
        next_log_probs = np.take_along_axis(flat, top_idx, axis=1)
        parent = (top_idx // V).astype(np.int64)               # [B, K]
        token = (top_idx % V).astype(np.int64)
        next_finished = np.take_along_axis(finished, parent, axis=1) | (
            token == self.end_token)

        # reorder cell states by the chosen parent beams
        gather = (parent + np.arange(B)[:, None] * K).reshape(-1)

        def f(v):
            return jnp.asarray(np.asarray(v)[gather])

        next_states = self._map_state(next_states, f)
        return token, parent, next_states, next_log_probs, next_finished


def dynamic_decode(decoder, inits=None, max_step_num=100, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """Runs the decoder until every beam finishes or max_step_num steps.
    Returns (predicted_ids [B, T, K], final_log_probs [B, K]) and, with
    return_length, the per-beam sequence lengths [B, K]."""
    ids, states, log_probs, finished = decoder.initialize(inits)
    B, K = ids.shape
    all_tokens, all_parents = [], []
    lengths = np.zeros((B, K), np.int64)
    for _ in range(max_step_num):
        token, parent, states, log_probs, new_finished = decoder.step(
            ids, states, log_probs, finished)
        all_tokens.append(np.asarray(token))
        all_parents.append(np.asarray(parent))
        # beams reorder every step: carry each slot's length along its parent
        # lineage, then extend the slots whose PARENT beam was still live
        par = np.asarray(parent)
        parent_finished = np.take_along_axis(finished, par, axis=1)
        lengths = np.take_along_axis(lengths, par, axis=1) + (
            ~parent_finished).astype(np.int64)
        ids, finished = np.asarray(token), np.asarray(new_finished)
        if finished.all():
            break
    T = len(all_tokens)
    tok = np.stack(all_tokens)                                 # [T, B, K]
    par = np.stack(all_parents)
    traced = gather_tree(Tensor(jnp.asarray(tok)), Tensor(jnp.asarray(par)))
    out = np.asarray(traced._data).transpose(1, 0, 2)          # [B, T, K]
    if output_time_major:
        out = out.transpose(1, 0, 2)
    outs = (Tensor(jnp.asarray(out)), Tensor(jnp.asarray(log_probs)))
    if return_length:
        return outs + (Tensor(jnp.asarray(lengths)),)
    return outs
