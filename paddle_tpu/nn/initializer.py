"""Weight initializers.

Reference parity: python/paddle/fluid/initializer.py (Constant, Uniform, Normal,
TruncatedNormal, Xavier, MSRA/Kaiming, Bilinear, Assign) re-exported as
paddle.nn.initializer.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.generator import default_generator


def _key():
    return default_generator().split()


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle stores [out_c, in_c, *spatial]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(tuple(shape), self.value, dtype=dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(_key(), tuple(shape), dtype=jnp.float32, minval=self.low, maxval=self.high).astype(dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return (jax.random.normal(_key(), tuple(shape), dtype=jnp.float32) * self.std + self.mean).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return (jax.random.truncated_normal(_key(), -2.0, 2.0, tuple(shape), dtype=jnp.float32) * self.std + self.mean).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(_key(), tuple(shape), dtype=jnp.float32, minval=-limit, maxval=limit).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (jax.random.normal(_key(), tuple(shape), dtype=jnp.float32) * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2))
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(_key(), tuple(shape), dtype=jnp.float32, minval=-limit, maxval=limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2))
        std = gain / math.sqrt(fi)
        return (jax.random.normal(_key(), tuple(shape), dtype=jnp.float32) * std).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        from ..core.tensor import Tensor

        v = self.value
        if isinstance(v, Tensor):
            v = v.numpy()
        arr = jnp.asarray(np.asarray(v), dtype=dtype)
        return arr.reshape(tuple(shape)) if arr.shape != tuple(shape) else arr


class Bilinear(Initializer):
    """Bilinear upsampling kernel init (fluid/initializer.py BilinearInitializer)."""

    def __call__(self, shape, dtype):
        weight = np.zeros(tuple(shape), dtype=np.float32)
        f = math.ceil(shape[3] / 2)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for k in range(int(np.prod(shape))):
            idx = np.unravel_index(k, shape)
            x, y = idx[3], idx[2]
            weight[idx] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return jnp.asarray(weight, dtype=dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        flat = (shape[0], int(np.prod(shape[1:])))
        a = jax.random.normal(_key(), flat, dtype=jnp.float32)
        q, r = jnp.linalg.qr(a if flat[0] >= flat[1] else a.T)
        q = q * jnp.sign(jnp.diag(r))
        if flat[0] < flat[1]:
            q = q.T
        return (self.gain * q.reshape(tuple(shape))).astype(dtype)


class Dirac(Initializer):
    def __call__(self, shape, dtype):
        w = np.zeros(tuple(shape), dtype=np.float32)
        oc, ic = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for i in range(min(oc, ic)):
            w[(i, i) + tuple(centers)] = 1.0
        return jnp.asarray(w, dtype=dtype)
