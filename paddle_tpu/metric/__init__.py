"""paddle.metric parity (python/paddle/metric/metrics.py)."""
from .metrics import Accuracy, Auc, Metric, Precision, Recall, accuracy, mean_iou  # noqa: F401
