"""Metrics (python/paddle/metric/metrics.py parity: Metric:37 base + Accuracy:180,
Precision:329, Recall:459, Auc:592)."""
import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return np.asarray(x._data) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np.squeeze(-1)
        correct = idx == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _np(correct)
        num = c.shape[0]
        accs = []
        for k in self.topk:
            c_k = c[..., :k].sum(-1).mean()
            self.total[self.topk.index(k)] += c_k * num
            self.count[self.topk.index(k)] += num
            accs.append(float(c_k))
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c > 0 else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        l = _np(labels)
        pred_bin = (p > 0.5).astype(np.int32).reshape(-1)
        l = l.reshape(-1).astype(np.int32)
        self.tp += int(((pred_bin == 1) & (l == 1)).sum())
        self.fp += int(((pred_bin == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        l = _np(labels)
        pred_bin = (p > 0.5).astype(np.int32).reshape(-1)
        l = l.reshape(-1).astype(np.int32)
        self.tp += int(((pred_bin == 1) & (l == 1)).sum())
        self.fn += int(((pred_bin == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc", *args, **kwargs):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        l = _np(labels).reshape(-1).astype(np.int64)
        if p.ndim == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        bins = np.clip((p * self.num_thresholds).astype(np.int64), 0, self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, dtype=np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, dtype=np.int64)

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return float(auc / denom) if denom else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """paddle.metric.accuracy functional parity (metrics/accuracy_op.cc).

    Dispatched (jnp, not host numpy) so it works under jit traces and is
    recorded into static Programs for fetch_list."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import apply

    def fn(p, l):
        idx = jax.lax.top_k(p, k)[1]
        if l.ndim == 2 and l.shape[1] == 1:
            l = l[:, 0]
        hit = (idx == l[:, None]).any(axis=1)
        return hit.astype(jnp.float32).mean()

    return apply(fn,
                 input if isinstance(input, Tensor) else Tensor(input),
                 label if isinstance(label, Tensor) else Tensor(label))


def mean_iou(input, label, num_classes, name=None):
    """operators/metrics/mean_iou_op.cc parity: input/label int class maps.

    Returns (mean_iou scalar, out_wrong [num_classes], out_correct [num_classes])
    — IoU per class = correct / (pred + label - correct), averaged over classes
    that appear; bincount via XLA scatter-add.
    """
    import jax.numpy as jnp
    from ..core.dispatch import apply
    from ..core.tensor import Tensor
    import numpy as np

    def _t(x):
        return x if isinstance(x, Tensor) else Tensor(np.asarray(x))

    def fn(p, l):
        p = p.reshape(-1).astype(jnp.int32)
        l = l.reshape(-1).astype(jnp.int32)
        ones = jnp.ones_like(p, jnp.float32)
        pred_cnt = jnp.zeros(num_classes, jnp.float32).at[p].add(ones)
        lab_cnt = jnp.zeros(num_classes, jnp.float32).at[l].add(ones)
        correct = jnp.zeros(num_classes, jnp.float32).at[p].add(
            (p == l).astype(jnp.float32))
        # reference: a mismatch increments out_wrong for BOTH the label's and
        # the prediction's class; denominator = wrong + correct (= union)
        wrong = pred_cnt + lab_cnt - 2.0 * correct
        union = wrong + correct
        present = union > 0
        iou = jnp.where(present, correct / jnp.maximum(union, 1.0), 0.0)
        miou = jnp.sum(iou) / jnp.maximum(jnp.sum(present), 1)
        return miou, wrong.astype(jnp.int32), correct.astype(jnp.int32)

    m, w, c = apply(fn, _t(input).detach(), _t(label).detach())
    for t in (m, w, c):
        t.stop_gradient = True
    return m, w, c
