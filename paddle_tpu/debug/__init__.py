"""Debug façade over the black-box flight recorder.

``paddle_tpu.debug`` re-exports the :mod:`paddle_tpu.monitor.blackbox`
surface under the name operators reach for first::

    from paddle_tpu import debug

    debug.beacon("my_loop")            # progress beacon per iteration
    debug.start_sentinel(timeout_s=60) # stall watcher -> dump bundles
    path = debug.dump("signal")        # on-demand bundle, returns path

The implementation (flight-recorder ring, beacon registry, stall
sentinel, dump bundles, SIGUSR1/excepthook integration) lives in
``paddle_tpu/monitor/blackbox.py``; see docs/OBSERVABILITY.md
"Flight recorder & stall diagnostics" and tools/blackbox_dump.py.
"""
from ..monitor import blackbox  # noqa: F401
from ..monitor.blackbox import (  # noqa: F401
    BUNDLE_KEYS, beacon, beacons, capacity, context, default_dir, disable,
    dump, enable, install_hooks, is_enabled, load_bundle, note, note_span,
    progress, quiesce, register_provider, reset, ring, ring_summary,
    sentinel_running, set_capacity, set_context, start_sentinel,
    stop_sentinel, sync_from_flag, validate_bundle)

__all__ = ["blackbox"] + [n for n in dir(blackbox)
                          if n in blackbox.__all__]
