"""Benchmark: GPT-2 small causal-LM training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} (+ mfu).

Metric: tokens/sec/chip for a full jitted train step (fwd+bwd+AdamW) in bfloat16
matmuls — the BASELINE.md north-star family (ERNIE/BERT-class tokens/sec/chip).
vs_baseline: ratio against the reference-class target of 10_000 tokens/sec/device
(0.6 × a ~16.6k tok/s A100+NCCL BERT-base-class figure — BASELINE.json's ≥60% goal),
since the reference repo publishes no absolute numbers (BASELINE.md: "published: {}").

The recorded number for a round lives in BENCH_r{N}.json (written by the driver);
that file is the single source of truth — sweep locally with --sweep.

Usage: python bench.py [--batch B] [--seq S] [--steps N] [--sweep]
"""
import argparse
import json
import sys
import time

import numpy as np

BASELINE_TOKENS_PER_SEC = 10_000.0


def _model_flops_per_token(cfg):
    """Approximate training FLOPs/token (fwd+bwd ~= 6*N params + attention)."""
    h, L, s, v = cfg.hidden_size, cfg.num_layers, cfg.max_seq_len, cfg.vocab_size
    n_params = v * h + L * (12 * h * h) + h * v  # emb + blocks + head (tied-ish)
    attn = L * 12 * s * h  # 2 matmuls of [s,h]x[h,s] per layer, fwd+bwd
    return 6 * n_params + attn


def run_config(batch, seq, steps, quiet=False):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainLoss

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    if not on_tpu:  # keep the CPU fallback tractable
        cfg = GPTConfig(vocab_size=8192, hidden_size=256, num_layers=4,
                        num_heads=8, max_seq_len=seq, dropout=0.0)
        steps = min(steps, 3)
    else:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=seq, dropout=0.0)

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    loss_layer = GPTPretrainLoss()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
    trainer = SpmdTrainer(model, opt, loss_fn=loss_layer, mesh=mesh)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    with paddle.amp.auto_cast(True, dtype="bfloat16"):
        # warmup + compile (host-copy forces completion through the tunnel)
        np.asarray(trainer.train_step(ids, labels)._data)
        t0 = time.perf_counter()
        loss = None
        for _ in range(steps):
            loss = trainer.train_step(ids, labels)
        # trailing sync: last loss + a param leaf depend on every prior step
        np.asarray(loss._data)
        first = next(iter(trainer.params))
        np.asarray(trainer.params[first][(0,) * trainer.params[first].ndim])
        dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    # MFU against one v5e-class chip (~197 TFLOP/s bf16); CPU runs report 0
    peak = 197e12 if on_tpu else float("inf")
    mfu = tokens_per_sec * _model_flops_per_token(cfg) / peak
    if not quiet:
        print(f"  batch={batch} seq={seq}: {tokens_per_sec:,.0f} tok/s "
              f"(mfu~{mfu:.1%})", file=sys.stderr)
    return tokens_per_sec, mfu


def _arm_watchdog(seconds=900):
    """If the TPU tunnel is wedged (device init / first compile hangs), emit a
    parseable failure line instead of hanging until the driver's kill. The
    timer is cancelled once the first measurement completes."""
    import os
    import threading

    def _fire():
        # no "metric"/"value" keys: a failure must never parse as a number
        print(json.dumps({
            "error": f"watchdog: no measurement within {seconds}s — "
                     "TPU tunnel unavailable/wedged",
        }), flush=True)
        os._exit(3)

    t = threading.Timer(seconds, _fire)
    t.daemon = True
    t.start()
    return t


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--sweep", action="store_true",
                    help="sweep batch/seq configs, report the best")
    args = ap.parse_args()

    # arm BEFORE backend init: a wedged tunnel hangs inside jax.devices()
    # itself, which is precisely the case the watchdog must catch
    watchdog = _arm_watchdog(900)

    import jax

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    if not on_tpu:
        watchdog.cancel()
        watchdog = None
    # batch 16 was the r1 sweet spot at seq 1024 (batch 32 exceeded 16G HBM);
    # the r2 flash-attention retune cut attention HBM traffic, so when no
    # explicit --batch is given on TPU, a quick 2-config probe (6 steps each)
    # picks between 16 and 24 before the full 20-step measurement.
    batch = args.batch or (16 if on_tpu else 2)
    seq = args.seq or (1024 if on_tpu else 128)

    if on_tpu and args.batch is None and not args.sweep:
        probes = {}
        for b in (16, 24):
            try:
                probes[b], _ = run_config(b, seq, 6)
            except Exception as e:
                print(f"  probe batch={b} failed ({e})", file=sys.stderr)
        if probes:
            batch = max(probes, key=probes.get)
        if watchdog is not None:
            watchdog.cancel()          # device + compile proven healthy
            watchdog = _arm_watchdog(900)

    if args.sweep:
        best = (0.0, 0.0, None)
        for b, s in ((8, 1024), (16, 1024), (24, 1024), (16, 2048),
                     (8, 2048), (4, 4096), (8, 4096)):
            try:
                tps, mfu = run_config(b, s, args.steps)
            except Exception as e:
                print(f"  batch={b} seq={s}: failed ({e})", file=sys.stderr)
                continue
            if watchdog is not None:
                # first config proved the tunnel healthy; a long sweep is
                # not a wedge — stand the watchdog down
                watchdog.cancel()
                watchdog = None
            if tps > best[0]:
                best = (tps, mfu, (b, s))
        tps, mfu, cfg = best
        if cfg is None:
            print(json.dumps({"error": "every sweep config failed"}))
            sys.exit(1)
        print(json.dumps({
            "metric": "gpt2s_train_tokens_per_sec_per_chip",
            "value": round(tps, 1), "unit": "tokens/s",
            "vs_baseline": round(tps / BASELINE_TOKENS_PER_SEC, 3),
            "mfu": round(mfu, 4), "config": cfg,
        }))
        return

    tps, mfu = run_config(batch, seq, args.steps, quiet=True)
    if watchdog is not None:
        watchdog.cancel()
    print(json.dumps({
        "metric": "gpt2s_train_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tps / BASELINE_TOKENS_PER_SEC, 3),
        "mfu": round(mfu, 4),
    }))


if __name__ == "__main__":
    main()
