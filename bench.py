"""Benchmark: GPT-2 small causal-LM training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} (+ mfu).

Metric: tokens/sec/chip for a full jitted train step (fwd+bwd+AdamW) in bfloat16
matmuls — the BASELINE.md north-star family (ERNIE/BERT-class tokens/sec/chip).
vs_baseline: ratio against the reference-class target of 10_000 tokens/sec/device
(0.6 × a ~16.6k tok/s A100+NCCL BERT-base-class figure — BASELINE.json's ≥60% goal),
since the reference repo publishes no absolute numbers (BASELINE.md: "published: {}").

The recorded number for a round lives in BENCH_r{N}.json (written by the driver);
that file is the single source of truth — sweep locally with --sweep.

Other BASELINE.md milestone configs measure standalone via --config:
  --config resnet50      ResNet-50 @to_static-style jitted train step, imgs/s
  --config bert_dp       BERT-base pretrain step, tokens/s
  --config lenet         LeNet hapi Model train_batch loop, steps/s
  --config gpt2s_decode  KV-cache decode, pure new-tokens/s (prefill excluded)
  --config ppyolo        PP-YOLOE train step imgs/s (+ infer+NMS imgs/s extra)
  --config gpt2m         GPT-2-medium (~350M) train step, tokens/s (BASELINE #4 class)
  --config gpt2s_16k     GPT-2s train step at seq 16384 (flash long-context)
  --config gpt2s_serve   continuous-batching ServingEngine, aggregate new tok/s
The default (gpt2s) run also appends an "extra" dict with a quick ResNet-50
measurement when the chip is healthy (disable with --no-extra).

Usage: python bench.py [--batch B] [--seq S] [--steps N] [--sweep]
                       [--config gpt2s|resnet50|bert_dp|lenet|gpt2s_decode|
                                 ppyolo|gpt2m|gpt2s_16k|gpt2s_serve]
                       [--no-extra] [--no-micro]
"""
import argparse
import json
import os
import sys
import time

import numpy as np

BASELINE_TOKENS_PER_SEC = 10_000.0

# The most recent COMPLETE metric line emitted this run. The watchdog
# re-emits it (exit 0) if a later, heavier compile wedges: any healthy
# window — however short — must yield a parseable number, because the
# driver records the LAST JSON line and the process exit code.
_LAST_GOOD = None


def _emit(line):
    """Print a metric line immediately (flushed) and remember it as the
    best-so-far result for the watchdog to fall back on. Deep-copied so
    later in-place mutation of nested dicts (the incremental "extra"
    block) can't change what the async watchdog would re-emit.

    Every line carries the runtime-telemetry snapshot ("monitor": compile
    counts, step/TTFT latencies, host syncs...) so the recorded number is
    attributable: a wedged round's last line shows exactly how far the
    instrumented stack got."""
    import copy

    global _LAST_GOOD
    try:
        from paddle_tpu import monitor

        line = dict(line, monitor=monitor.flatten(monitor.snapshot()))
    except Exception:
        pass  # the metric line must never die on telemetry
    _LAST_GOOD = copy.deepcopy(line)
    print(json.dumps(line), flush=True)


# --- banked-legs resume (ROADMAP item 4) -------------------------------------
# Each completed leg's metric line is appended to the --banked JSONL the
# moment it lands; a re-invocation with the same file skips already-banked
# legs, so five wedged rounds can still assemble one complete result
# inside the TPU-tunnel watchdog window.

_BANKED_PATH = None
_BANKED = {}


def _bank_load(path):
    """Read the banked-legs JSONL from an earlier (possibly wedged)
    invocation: one {"leg", "line"} record per completed measurement."""
    global _BANKED_PATH
    _BANKED_PATH = path
    _BANKED.clear()
    if not path or not os.path.exists(path):
        return
    try:
        with open(path) as f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    rec = json.loads(raw)
                except ValueError:
                    continue   # torn tail line from a killed writer
                if isinstance(rec, dict) and "leg" in rec:
                    _BANKED[rec["leg"]] = rec.get("line")
    except OSError as e:
        print(f"  banked file unreadable ({e})", file=sys.stderr)


def _bank(leg, line):
    """Persist one completed leg NOW (append + flush + fsync): a later
    wedge, crash, or kill cannot erase it. With FLAGS_perf_ledger armed
    the leg also lands as one perf-ledger row (site=bench/<leg>), so
    retried BENCH rounds auto-accumulate cross-run calibration data."""
    _BANKED[leg] = line
    if _BANKED_PATH:
        try:
            with open(_BANKED_PATH, "a") as f:
                f.write(json.dumps({"ts": round(time.time(), 3),
                                    "leg": leg, "line": line}) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError as e:
            print(f"  banking leg {leg!r} failed ({e})", file=sys.stderr)
    try:
        from paddle_tpu import flags

        if flags.get_flag("perf_ledger", False) \
                and isinstance(line, dict):
            from paddle_tpu.monitor import perfledger

            perfledger.record_leg(leg, line)
    except Exception as e:
        # ledger telemetry must never cost a banked measurement
        print(f"  perf-ledger row for leg {leg!r} failed ({e})",
              file=sys.stderr)


def _banked(leg):
    """The banked payload for a leg, or None (leg must be re-measured)."""
    return _BANKED.get(leg)


def _goodput_leg(leg):
    """Open a goodput run for the NEXT measurement leg (FLAGS_goodput,
    docs/OBSERVABILITY.md "Goodput ledger"): ``start_run`` finalizes the
    previous leg's run — its bucket breakdown lands as one perf-ledger
    row at site=run/goodput — so each bench leg's wall time is accounted
    separately. Disarmed this is one flag lookup; a failure never costs
    the measurement."""
    try:
        from paddle_tpu import flags as _gp_flags

        if not _gp_flags.get_flag("goodput", False):
            return
        from paddle_tpu.monitor import goodput as _goodput

        _goodput.start_run("bench/" + leg)
    except Exception as e:
        print(f"  goodput run for leg {leg!r} failed ({e})",
              file=sys.stderr)


def _goodput_close():
    """Finalize the LAST leg's goodput run (atexit: main has many exit
    paths and the final row must land on all of them)."""
    try:
        from paddle_tpu import flags as _gp_flags

        if not _gp_flags.get_flag("goodput", False):
            return
        from paddle_tpu.monitor import goodput as _goodput

        _goodput.end_run()
    except Exception as e:
        print(f"  goodput finalize failed ({e})", file=sys.stderr)


# cumulative compile-cache counts at the previous heartbeat, so each
# bench_phase line also carries the DELTA attributable to its phase
_LAST_CACHE_COUNTS = {}


def _compile_cache_counts():
    """Aggregate compile_cache_total by (event, source) across all sites —
    the per-phase attribution signal: a wedged round whose heartbeats show
    only miss_fresh deltas died compiling; one showing hit_disk warmed
    from FLAGS_jit_cache_dir and its time went to runtime."""
    from paddle_tpu import monitor

    out = {}
    metric = monitor.default_registry().get("compile_cache_total")
    if metric is None:
        return out
    for s in metric.series():
        key = (f"{s.labels.get('event', '?')}_"
               f"{s.labels.get('source', '?')}")
        out[key] = out.get(key, 0) + int(s.value)
    return out


def _heartbeat(phase, status="start", **fields):
    """Phase heartbeat into the monitor JSONL event log
    (FLAGS_monitor_log_path; defaults to /tmp/paddle_tpu_bench_events.jsonl
    for bench runs): when a later compile wedges past the watchdog, the
    log's last heartbeat names the wedged phase instead of an opaque
    'no measurement within 900s'. Each line carries the compile-cache
    hit/miss counts by source (memory|disk|fresh) plus the delta since
    the previous heartbeat, so a wedged phase is attributable to compile
    vs runtime from the artifact alone."""
    try:
        from paddle_tpu import flags, monitor, trace

        if not flags.get_flag("monitor_log_path", ""):
            flags.set_flags(
                {"monitor_log_path": "/tmp/paddle_tpu_bench_events.jsonl"})
        counts = _compile_cache_counts()
        delta = {k: v - _LAST_CACHE_COUNTS.get(k, 0)
                 for k, v in counts.items()
                 if v != _LAST_CACHE_COUNTS.get(k, 0)}
        _LAST_CACHE_COUNTS.clear()
        _LAST_CACHE_COUNTS.update(counts)
        # trace summary (FLAGS_trace runs): span count + top-3 span
        # totals, so a wedged phase's heartbeat also names WHERE the
        # traced time went (prefill vs decode vs compile vs checkpoint)
        tsum = trace.snapshot_summary(3)
        # flight recorder: every heartbeat beats the bench/phase beacon
        # and stamps the current phase into the dump-bundle context, so a
        # sentinel/watchdog dump names the wedged phase by itself
        monitor.blackbox.beacon("bench/phase")
        monitor.blackbox.set_context("bench_phase",
                                     f"{phase}:{status}")
        monitor.blackbox.note("bench_phase", phase=phase, status=status)
        monitor.log_event("bench_phase", phase=phase, status=status,
                          compile_cache=counts, compile_cache_delta=delta,
                          jit_cache_dir=flags.get_flag("jit_cache_dir", ""),
                          trace_spans=tsum["spans"], trace_top=tsum["top"],
                          **fields)
    except Exception:
        pass


def _n_params(cfg):
    """Parameter count for the GPT family: embedding + transformer blocks +
    lm head (tied-ish). Shared by MFU (all params matter for FLOPs) and
    MBU (which subtracts the gathered-not-streamed embedding)."""
    h, L, v = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    return v * h + L * (12 * h * h) + h * v


def _model_flops_per_token(cfg):
    """Approximate training FLOPs/token (fwd+bwd ~= 6*N params + attention).
    Sliding-window attention only computes an O(s*W) band — charge that,
    not O(s^2), or windowed MFU overstates by the skipped blocks."""
    h, L, s = cfg.hidden_size, cfg.num_layers, cfg.max_seq_len
    eff = min(getattr(cfg, "attention_window", None) or s, s)
    attn = L * 12 * eff * h  # 2 matmuls of [s,eff]x[eff,s-ish] per layer
    return 6 * _n_params(cfg) + attn


def _gpt2s_cfg(on_tpu, seq):
    """The benchmark's GPT-2-small config (CPU runs shrink it to stay
    tractable) — single source for the train AND decode configs."""
    from paddle_tpu.models import GPTConfig

    if not on_tpu:
        return GPTConfig(vocab_size=8192, hidden_size=256, num_layers=4,
                         num_heads=8, max_seq_len=seq, dropout=0.0)
    return GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                     num_heads=12, max_seq_len=seq, dropout=0.0)


def _gpt2m_cfg(on_tpu, seq):
    """GPT-2-medium (~350M params): the BASELINE #4 model class (ERNIE-1.0 /
    GPT-2 medium). Single-chip it exercises HBM pressure at real scale; the
    sharding_stage2 side of BASELINE #4 is compile-validated by
    tools/scaling_check.py and dryrun_multichip (no multi-chip hardware)."""
    from paddle_tpu.models import GPTConfig

    if not on_tpu:
        return GPTConfig(vocab_size=8192, hidden_size=320, num_layers=6,
                         num_heads=8, max_seq_len=seq, dropout=0.0)
    return GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                     num_heads=16, max_seq_len=seq, dropout=0.0)


def _gpt2s_setup(batch, seq, cfg_fn=None, window=None):
    """Model+trainer+data for the headline GPT-2s train config — shared with
    tools/profile_gpt.py so the profiled program IS the benchmarked one.
    cfg_fn overrides the model config family (e.g. _gpt2m_cfg); window sets
    sliding-window attention (the flash kernels then skip out-of-band
    blocks: O(s*W) attention instead of O(s^2))."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.models import GPTForCausalLM, GPTPretrainLoss

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    cfg = (cfg_fn or _gpt2s_cfg)(on_tpu, seq)
    if window is not None:
        # rebuild THROUGH the constructor so its validation fires (a bad
        # window must fail loudly, not print a garbage throughput line)
        from paddle_tpu.models import GPTConfig

        cfg = GPTConfig(vocab_size=cfg.vocab_size,
                        hidden_size=cfg.hidden_size,
                        num_layers=cfg.num_layers, num_heads=cfg.num_heads,
                        max_seq_len=cfg.max_seq_len, dropout=cfg.dropout,
                        intermediate_size=cfg.intermediate_size,
                        use_flash=cfg.use_flash,
                        gelu_approx=cfg.gelu_approx,
                        num_kv_heads=getattr(cfg, "num_kv_heads", None),
                        attention_window=window)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
    trainer = SpmdTrainer(model, opt, loss_fn=GPTPretrainLoss(), mesh=mesh)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    return on_tpu, cfg, trainer, ids, labels


def run_config(batch, seq, steps, quiet=False, cfg_fn=None, window=None):
    import paddle_tpu as paddle

    on_tpu, cfg, trainer, ids, labels = _gpt2s_setup(batch, seq, cfg_fn,
                                                     window=window)
    if not on_tpu:  # keep the CPU fallback tractable
        steps = min(steps, 3)

    with paddle.amp.auto_cast(True, dtype="bfloat16"):
        # warmup + compile (host-copy forces completion through the tunnel)
        np.asarray(trainer.train_step(ids, labels)._data)
        t0 = time.perf_counter()
        loss = None
        for _ in range(steps):
            loss = trainer.train_step(ids, labels)
        # trailing sync: last loss + a param leaf depend on every prior step
        np.asarray(loss._data)
        first = next(iter(trainer.params))
        np.asarray(trainer.params[first][(0,) * trainer.params[first].ndim])
        dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt
    # MFU against one v5e-class chip (~197 TFLOP/s bf16); CPU runs report 0
    peak = 197e12 if on_tpu else float("inf")
    mfu = tokens_per_sec * _model_flops_per_token(cfg) / peak
    if not quiet:
        print(f"  batch={batch} seq={seq}: {tokens_per_sec:,.0f} tok/s "
              f"(mfu~{mfu:.1%})", file=sys.stderr)
    return tokens_per_sec, mfu


def run_resnet50(batch, steps, quiet=False):
    """BASELINE config #2: ResNet-50 fwd+bwd+Momentum, imgs/s/chip."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.vision.models import resnet50

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    size = 224 if on_tpu else 32
    if not on_tpu:
        steps = min(steps, 2)

    paddle.seed(0)
    model = resnet50(num_classes=1000)
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())
    loss_layer = paddle.nn.CrossEntropyLoss()
    mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
    trainer = SpmdTrainer(model, opt, loss_fn=loss_layer, mesh=mesh)

    rng = np.random.RandomState(0)
    imgs = paddle.to_tensor(rng.rand(batch, 3, size, size).astype(np.float32))
    labels = paddle.to_tensor(rng.randint(0, 1000, (batch,)).astype(np.int32))

    with paddle.amp.auto_cast(True, dtype="bfloat16"):
        np.asarray(trainer.train_step(imgs, labels)._data)  # compile+sync
        t0 = time.perf_counter()
        loss = None
        for _ in range(steps):
            loss = trainer.train_step(imgs, labels)
        np.asarray(loss._data)
        dt = time.perf_counter() - t0
    ips = batch * steps / dt
    if not quiet:
        print(f"  resnet50 batch={batch}: {ips:,.1f} imgs/s", file=sys.stderr)
    return ips


def run_bert(batch, seq, steps, quiet=False):
    """BASELINE config #3: BERT-base pretrain step (MLM+NSP), tokens/s/chip."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.models import BertConfig, BertForPretraining, \
        BertPretrainLoss

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    if not on_tpu:
        cfg = BertConfig(vocab_size=8192, hidden_size=128, num_layers=2,
                         num_heads=4, intermediate_size=256,
                         max_position=max(seq, 128), dropout=0.0)
        steps = min(steps, 2)
    else:
        cfg = BertConfig(dropout=0.0)  # base: 12L/768h/12heads, 512 pos

    paddle.seed(0)
    model = BertForPretraining(cfg)
    loss_layer = BertPretrainLoss()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
    trainer = SpmdTrainer(model, opt, loss_fn=loss_layer, mesh=mesh)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    tok_type = paddle.to_tensor(np.zeros((batch, seq), np.int32))
    mlm_labels = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    with paddle.amp.auto_cast(True, dtype="bfloat16"):
        np.asarray(trainer.train_step(ids, tok_type, mlm_labels)._data)
        t0 = time.perf_counter()
        loss = None
        for _ in range(steps):
            loss = trainer.train_step(ids, tok_type, mlm_labels)
        np.asarray(loss._data)
        dt = time.perf_counter() - t0
    tps = batch * seq * steps / dt
    if not quiet:
        print(f"  bert batch={batch} seq={seq}: {tps:,.0f} tok/s",
              file=sys.stderr)
    return tps


def run_lenet(batch, steps, quiet=False):
    """BASELINE config #1: LeNet hapi Model train_batch loop, steps/s."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.vision.models import LeNet

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    if not on_tpu:
        steps = min(steps, 3)
    paddle.seed(0)
    model = paddle.Model(LeNet())
    model.prepare(paddle.optimizer.Adam(learning_rate=1e-3,
                                        parameters=model.network.parameters()),
                  paddle.nn.CrossEntropyLoss())
    rng = np.random.RandomState(0)
    imgs = rng.rand(batch, 1, 28, 28).astype(np.float32)
    labels = rng.randint(0, 10, (batch, 1)).astype(np.int64)
    model.train_batch([imgs], [labels])  # compile
    t0 = time.perf_counter()
    for _ in range(steps):
        out = model.train_batch([imgs], [labels])
    dt = time.perf_counter() - t0
    sps = steps / dt
    if not quiet:
        print(f"  lenet batch={batch}: {sps:,.1f} steps/s", file=sys.stderr)
    return sps


def _ppyolo_setup(batch):
    """Shared model+data setup for the two ppyolo measurements."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.vision.models import PPYOLOE

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    if on_tpu:
        size, model = 640, PPYOLOE(num_classes=80, width=64, depth=2)
    else:
        size, model = 64, PPYOLOE(num_classes=80, width=16, depth=1)
    paddle.seed(0)
    rng = np.random.RandomState(0)
    imgs = paddle.to_tensor(rng.rand(batch, 3, size, size).astype(np.float32))
    return on_tpu, size, model, imgs


def run_ppyolo_train(batch, steps, quiet=False, setup=None):
    """BASELINE config #5 (train half): PP-YOLOE jitted fwd+bwd+Momentum
    step via SpmdTrainer, imgs/s/chip. setup: see run_ppyolo_infer."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.vision.models import PPYOLOELoss

    on_tpu, size, model, imgs = setup if setup is not None \
        else _ppyolo_setup(batch)
    if not on_tpu:
        steps = min(steps, 2)

    class TrainStep(nn.Layer):
        """Detector + loss fused so SpmdTrainer jits loss(decode(model(x)))."""

        def __init__(self, det, loss_fn):
            super().__init__()
            self.det = det
            self.det_loss = loss_fn

        def forward(self, x, gt_boxes, gt_labels):
            decoded = self.det.decode(self.det(x))
            return self.det_loss(decoded, (gt_boxes, gt_labels))

    step_layer = TrainStep(model, PPYOLOELoss(num_classes=80))
    opt = paddle.optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                                    parameters=step_layer.parameters())
    mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
    trainer = SpmdTrainer(step_layer, opt, loss_fn=None, mesh=mesh)

    A = sum((size // s) ** 2 for s in model.strides)
    rng = np.random.RandomState(1)
    gt_boxes = paddle.to_tensor(
        (rng.rand(batch, A, 4) * size).astype(np.float32))
    gt_labels = paddle.to_tensor(
        rng.randint(0, 81, (batch, A)).astype(np.int64))  # 80 == background

    with paddle.amp.auto_cast(True, dtype="bfloat16"):
        np.asarray(trainer.train_step(imgs, gt_boxes, gt_labels)._data)
        t0 = time.perf_counter()
        loss = None
        for _ in range(steps):
            loss = trainer.train_step(imgs, gt_boxes, gt_labels)
        np.asarray(loss._data)
        train_ips = batch * steps / (time.perf_counter() - t0)
    if not quiet:
        print(f"  ppyolo batch={batch} size={size}: train {train_ips:,.1f} "
              f"imgs/s", file=sys.stderr)
    return train_ips


def run_ppyolo_infer(batch, steps, quiet=False, setup=None):
    """BASELINE config #5 (infer half): forward + decode + multiclass-NMS
    postprocess as ONE @to_static-compiled program (Pallas NMS on TPU) in
    bf16 (the serving convention, matching gpt2s_decode), imgs/s/chip.
    Pass setup=(on_tpu, size, model, imgs) to reuse the train half's model
    and device-resident batch instead of rebuilding them."""
    import paddle_tpu as paddle

    on_tpu, size, model, imgs = setup if setup is not None \
        else _ppyolo_setup(batch)
    if not on_tpu:
        steps = min(steps, 2)
    model.eval()

    infer_fn = paddle.jit.to_static(
        lambda im: model.postprocess(model(im), score_threshold=0.3,
                                     keep_top_k=100))

    def infer_once():
        _, counts = infer_fn(imgs)
        np.asarray(counts._data)  # sync

    # bf16 serving on TPU (run_decode convention); CPU bf16 is emulated/slow
    with paddle.amp.auto_cast(on_tpu, dtype="bfloat16"):
        infer_once()  # compile
        t0 = time.perf_counter()
        for _ in range(steps):
            infer_once()
        infer_ips = batch * steps / (time.perf_counter() - t0)
    if not quiet:
        print(f"  ppyolo batch={batch} size={size}: infer+nms "
              f"{infer_ips:,.1f} imgs/s", file=sys.stderr)
    return infer_ips


def run_decode(batch, steps, quiet=False, cache_dtype=None):
    """Serving-side metric: KV-cache decode, PURE new-tokens/s/chip (GPT-2
    small, prompt 128, greedy). Prefill time is excluded by differencing a
    max_new_tokens=1 run against the full run at identical reps.
    cache_dtype='int8' measures the quantized-cache serving config.
    Returns (new_tokens/s, MBU) — MBU computed HERE, from the exact
    prompt/new_tokens/cfg this function measured (one source of truth)."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    cfg = _gpt2s_cfg(on_tpu, 1024 if on_tpu else 512)
    new_tokens = 256 if on_tpu else 32
    dec_dtype = "bfloat16" if on_tpu else None  # bf16 cache: serving config

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, 128)).astype(np.int32))
    reps = max(1, steps // 4)

    def timed(n):
        np.asarray(model.generate(ids, max_new_tokens=n, temperature=0.0,
                                  dtype=dec_dtype,
                                  cache_dtype=cache_dtype)._data)  # compile
        t0 = time.perf_counter()
        out = None
        for _ in range(reps):
            out = model.generate(ids, max_new_tokens=n, temperature=0.0,
                                 dtype=dec_dtype, cache_dtype=cache_dtype)
        np.asarray(out._data)
        return time.perf_counter() - t0

    dt_full = timed(new_tokens)
    dt_prefill = timed(1)  # prefill + a single decode step
    decode_dt = max(dt_full - dt_prefill, 1e-9)
    tps = batch * (new_tokens - 1) * reps / decode_dt
    mbu = _decode_mbu(cfg, batch, tps, 128, new_tokens,
                      cache_dtype=cache_dtype, on_tpu=on_tpu)
    if not quiet:
        print(f"  decode batch={batch} cache={cache_dtype or 'dtype'}: "
              f"{tps:,.0f} new tok/s mbu~{mbu:.1%} (full {dt_full:.2f}s, "
              f"prefill {dt_prefill:.2f}s)", file=sys.stderr)
    return tps, mbu


def _decode_mbu(cfg, batch, tps, prompt, new_tokens, cache_dtype=None,
                on_tpu=True):
    """Model-bandwidth-utilization for the HBM-bound decode loop — the
    serving dual of training MFU. Bytes each decode step must move from
    HBM: every parameter (bf16 serving weights, read once per step,
    amortized over the batch) plus the KV cache at its average length
    over the run. MBU = tokens/s x bytes/token / HBM bandwidth, against
    the same v5e-class chip as the 197 TFLOP/s MFU peak (~819 GB/s).
    Off-TPU reports 0, matching the MFU convention (peak=inf on CPU).

    The input-embedding table is NOT charged: a decode step gathers only
    `batch` rows of it (negligible), unlike the lm-head matmul which
    streams its full [h, v] weight for the logits."""
    h, L, v = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    streamed_params = _n_params(cfg) - v * h  # minus the gathered embedding
    kv_heads = getattr(cfg, "num_kv_heads", None) or cfg.num_heads
    head_dim = h // cfg.num_heads
    # quantized caches stream 1-byte values PLUS the f32 per-row scale
    # (4 bytes per head_dim-element row) — omit it and quantized MBU reads
    # a few percent low vs the bf16 leg
    cache_el = 1 if cache_dtype in ("int8", "fp8") else 2
    avg_len = prompt + new_tokens / 2
    row_bytes = head_dim * cache_el + \
        (4 if cache_dtype in ("int8", "fp8") else 0)
    cache_bytes = batch * 2 * L * avg_len * kv_heads * row_bytes
    bytes_per_token = (2 * streamed_params + cache_bytes) / batch
    hbm_bw = 819e9 if on_tpu else float("inf")
    return tps * bytes_per_token / hbm_bw


def enable_tpu_compile_cache():
    """Persistent compilation cache (ONE place for the dir + policy — also
    used by tools/pipeline_memory.py and tools/profile_gpt.py): a probe
    session that compiled these programs makes the driver's later bench
    run skip straight to measurement, shrinking the window a tunnel wedge
    can hit. Call only on TPU: CPU AOT cache hits can trip host
    machine-feature mismatches (the loader warns about SIGILL)."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/paddle_tpu_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:
        print(f"  compilation cache unavailable ({e})", file=sys.stderr)


def run_serve(slots, n_requests, quiet=False):
    """Serving-engine metric: continuous batching over one fixed KV cache
    (bf16 params/cache, mixed prompt lengths, eos-free greedy), aggregate
    NEW tokens/s across all requests — the serving dual of gpt2s_decode's
    static-batch number."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import GPTForCausalLM

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    cfg = _gpt2s_cfg(on_tpu, 1024 if on_tpu else 256)
    new_tokens = 128 if on_tpu else 8

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    eng = ServingEngine(model, max_batch=slots,
                        dtype="bfloat16" if on_tpu else None)
    rng = np.random.RandomState(0)
    lens = [int(rng.randint(32, 128)) for _ in range(n_requests)]
    prompts = [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in lens]
    # warmup: compile EVERY prefill bucket the timed prompts will hit,
    # plus the decode step, off the clock
    seen_buckets = set()
    for p in prompts:
        b = eng._bucket(len(p))
        if b not in seen_buckets:
            seen_buckets.add(b)
            eng.submit(p, max_new_tokens=2)
    eng.run_until_complete()

    t0 = time.perf_counter()
    for p in prompts:
        eng.submit(p, max_new_tokens=new_tokens)
    res = eng.run_until_complete()
    dt = time.perf_counter() - t0
    # res accumulates across the engine's lifetime: count only the timed
    # requests (the warmups ran with max_new_tokens=2)
    total_new = sum(len(res[r].tokens) for r in res
                    if res[r].max_new_tokens == new_tokens)
    tps = total_new / dt
    if not quiet:
        print(f"  serve slots={slots} reqs={n_requests}: {tps:,.0f} "
              f"new tok/s aggregate", file=sys.stderr)
    return tps


def run_serve_mixed(slots, n_requests, quiet=False):
    """Serving realism scenario (the production shape, not an all-greedy
    drain): requests ARRIVE STAGGERED over the run, ~1/3 of them sample
    (temperature 0.8, top_k 50) while the rest stay greedy, and CHUNKED
    PREFILL is on so long prompts never stall running decodes. Reports
    (aggregate new tok/s, p50/p99 inter-token ms, p50/p99 time-to-first-
    token ms) — the latency percentiles are what the chunked-prefill
    design exists to protect."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import GPTForCausalLM

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    cfg = _gpt2s_cfg(on_tpu, 1024 if on_tpu else 256)
    new_tokens = 128 if on_tpu else 8
    chunk = 128 if on_tpu else 32

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    eng = ServingEngine(model, max_batch=slots,
                        dtype="bfloat16" if on_tpu else None,
                        prefill_chunk=chunk)
    rng = np.random.RandomState(1)
    lens = [int(rng.randint(32, 128)) for _ in range(n_requests)]
    prompts = [rng.randint(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in lens]
    kwargs = [({"temperature": 0.8, "top_k": 50, "seed": i}
               if i % 3 == 0 else {}) for i in range(n_requests)]

    # warmup off the clock: chunk program, greedy step AND sampling step
    eng.submit(prompts[0], max_new_tokens=2)
    eng.submit(prompts[-1], max_new_tokens=2, temperature=0.8, top_k=50,
               seed=0)
    eng.run_until_complete()

    tracked = {}      # rid -> (Request, submit_time)
    counts = {}       # rid -> tokens seen
    last_emit = {}    # rid -> timestamp of last emitted token
    inter_ms, ttft_ms = [], []
    pending = list(zip(prompts, kwargs))
    step_i = 0
    t0 = time.perf_counter()
    while pending or eng.has_work():
        if step_i % 3 == 0:    # staggered arrivals: 2 requests per 3 steps
            for _ in range(2):
                if pending:
                    p, kw = pending.pop(0)
                    rid = eng.submit(p, max_new_tokens=new_tokens, **kw)
                    tracked[rid] = (eng.get_request(rid),
                                    time.perf_counter())
                    counts[rid] = 0
        eng.step()
        now = time.perf_counter()
        for rid, (req, t_submit) in tracked.items():
            n = len(req.output_ids)
            if n > counts[rid]:
                if counts[rid] == 0:
                    ttft_ms.append((now - t_submit) * 1e3)
                else:
                    inter_ms.append((now - last_emit[rid]) * 1e3)
                last_emit[rid] = now
                counts[rid] = n
        step_i += 1
    dt = time.perf_counter() - t0
    total_new = sum(counts.values())
    tps = total_new / dt
    p50 = float(np.percentile(inter_ms, 50)) if inter_ms else 0.0
    p99 = float(np.percentile(inter_ms, 99)) if inter_ms else 0.0
    t50 = float(np.percentile(ttft_ms, 50)) if ttft_ms else 0.0
    t99 = float(np.percentile(ttft_ms, 99)) if ttft_ms else 0.0
    if not quiet:
        print(f"  serve-mixed slots={slots} reqs={n_requests}: {tps:,.0f} "
              f"tok/s, inter-token p50={p50:.1f}ms p99={p99:.1f}ms, "
              f"ttft p50={t50:.1f}ms p99={t99:.1f}ms", file=sys.stderr)
    return tps, p50, p99, t50, t99


def _arm_watchdog(seconds=900):
    """If the TPU tunnel is wedged (device init / compile hangs), don't hang
    until the driver's kill: if ANY measurement already completed, re-emit
    the best-so-far metric line (the driver parses the LAST JSON line) and
    exit 0 — a wedge after a success must not erase the success. Only a
    run with NO measurement at all exits 3 with an error line (no
    "metric"/"value" keys, so a failure never parses as a number)."""
    import threading

    def _dump_bundle():
        """Best-effort, BOUNDED dump attempt: the bundle's context names
        the wedged phase and its stacks show where every thread hung —
        but a dump that itself blocks (the wedged process may hold the
        very locks the bundle writer needs) must never stand between the
        watchdog and its exit, so it runs on a helper thread with a
        join timeout."""
        try:
            from paddle_tpu.monitor import blackbox

            if not blackbox.is_enabled():
                return
            extra = {"watchdog_s": seconds}
            try:
                from paddle_tpu import flags

                if flags.get_flag("perf_ledger", False):
                    # the last perf rows before the wedge ride along in
                    # the bundle (the ledger's dump provider adds its
                    # snapshot too, once any site constructed it)
                    from paddle_tpu.monitor import perfledger

                    extra["perf_ledger_tail"] = perfledger.tail(
                        flags.get_flag("perf_ledger_path", ""), 10)
            except Exception:
                pass
            t = threading.Thread(
                target=blackbox.dump, args=("stall",),
                kwargs={"site": "bench/watchdog",
                        "extra": extra},
                name="bench-watchdog-dump", daemon=True)
            t.start()
            t.join(timeout=30)
        except Exception:
            pass

    def _fire():
        # the re-emit comes FIRST: the driver parses the LAST JSON line,
        # and nothing — dump included — may stand between a wedged
        # process and that line
        if _LAST_GOOD is not None:
            line = dict(_LAST_GOOD)
            line["partial"] = True  # truncated run — later phase(s) missing
            line["watchdog_note"] = (
                f"a later phase hung >{seconds}s; this is the last complete "
                "measurement")
            print(json.dumps(line), flush=True)
            _dump_bundle()
            # exit 0 only when a REAL config measurement survived; if all
            # we have is the toy canary, exit 2: the line is still
            # driver-verifiable evidence of a healthy window, but the run
            # must not be bookable as a successful headline
            os._exit(0 if line.get("config") != "micro" else 2)
        print(json.dumps({
            "error": f"watchdog: no measurement within {seconds}s — "
                     "TPU tunnel unavailable/wedged",
        }), flush=True)
        _dump_bundle()
        os._exit(3)

    t = threading.Timer(seconds, _fire)
    t.daemon = True
    t.start()
    return t


def run_micro(quiet=False):
    """The wedge-proofing micro-measurement: a 2-layer GPT train step at
    tiny shapes — the smallest compile that still exercises the real
    trainer path (SpmdTrainer + AdamW + bf16 autocast). On a healthy
    tunnel this lands a flushed JSON metric within ~tens of seconds,
    BEFORE the heavy gpt2s compile gets a chance to wedge."""
    from paddle_tpu.models import GPTConfig

    def micro_cfg(on_tpu, seq):
        return GPTConfig(vocab_size=4096, hidden_size=128, num_layers=2,
                         num_heads=4, max_seq_len=seq, dropout=0.0)

    return run_config(8, 128, 5, quiet=quiet, cfg_fn=micro_cfg)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--sweep", action="store_true",
                    help="sweep batch/seq configs, report the best")
    ap.add_argument("--config", default="gpt2s",
                    choices=["gpt2s", "resnet50", "bert_dp", "lenet",
                             "gpt2s_decode", "ppyolo", "gpt2m",
                             "gpt2s_16k", "gpt2s_serve"])
    ap.add_argument("--no-extra", action="store_true",
                    help="skip the appended quick ResNet-50 measurement")
    ap.add_argument("--no-micro", action="store_true",
                    help="skip the wedge-canary micro measurement")
    ap.add_argument("--window", type=int, default=None,
                    help="sliding-window attention width for gpt2s/gpt2s_16k "
                         "(flash kernels skip out-of-band blocks)")
    ap.add_argument("--banked", default=None, metavar="PATH",
                    help="banked-legs JSONL: completed legs are appended "
                         "here as they land and SKIPPED on re-invocation, "
                         "so retries inside the TPU-tunnel window resume "
                         "instead of re-measuring (ROADMAP item 4)")
    args = ap.parse_args()

    _bank_load(args.banked)

    def leg_key(base):
        """Banked-leg key = leg name + every explicitly pinned
        measurement parameter: a leg banked under one configuration must
        never satisfy a re-invocation asking for a different one
        (--window/--batch/--seq/--steps each change what is measured)."""
        parts = [base]
        if args.batch is not None:
            parts.append(f"b{args.batch}")
        if args.seq is not None:
            parts.append(f"s{args.seq}")
        if args.steps != 20:
            parts.append(f"st{args.steps}")
        if args.window is not None:
            parts.append(f"w{args.window}")
        return ":".join(parts)

    headline_leg = leg_key("headline")

    # black-box flight recorder + stall sentinel (docs/OBSERVABILITY.md):
    # armed BEFORE backend init so a wedged phase — device init, a heavy
    # compile, a serving drain — produces a dump bundle naming the phase
    # (bench_phase context + beacon table + all-thread stacks) instead of
    # only the watchdog note. Default threshold 850s: just inside the
    # initial 900s watchdog window (a real init wedge dumps before the
    # kill) but above any leg the 900s windows consider healthy — a
    # sentinel bundle from a 1200/2500s re-armed window means "no
    # progress for 850s", evidence, not a verdict (the watchdog decides
    # life/death). FLAGS_stall_timeout_s overrides.
    try:
        from paddle_tpu import flags as _bb_flags
        from paddle_tpu.monitor import blackbox as _bb

        _bb.enable()
        _bb.start_sentinel(
            timeout_s=float(_bb_flags.get_flag("stall_timeout_s", 0.0))
            or 850.0)
    except Exception as e:
        print(f"  blackbox recorder unavailable ({e})", file=sys.stderr)

    # goodput accountant (FLAGS_goodput): every leg below opens its own
    # run via _goodput_leg; the atexit hook finalizes the last one on
    # every exit path (watchdog kill excepted — the blackbox bundle's
    # goodput provider still carries that run's breakdown)
    import atexit

    atexit.register(_goodput_close)

    # arm BEFORE backend init: a wedged tunnel hangs inside jax.devices()
    # itself, which is precisely the case the watchdog must catch
    watchdog = _arm_watchdog(900)

    import jax

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    _heartbeat("device_init", "done", on_tpu=on_tpu)
    # FLAGS_jit_cache_dir (env or set_flags) turns on the framework's own
    # persistent AOT executable cache: every SpmdTrainer/Executor/
    # ServingEngine compile below loads from it when warm (the aot_warm
    # tool pre-populates it) — a probe session's 26-minute compile becomes
    # this run's millisecond deserialize. Heartbeats carry the hit/miss
    # split so the artifact shows whether a round ran warm.
    from paddle_tpu import flags as _ptflags

    if _ptflags.get_flag("jit_cache_dir", ""):
        print(f"  AOT executable cache: "
              f"{_ptflags.get_flag('jit_cache_dir')}", file=sys.stderr)
    if on_tpu:
        enable_tpu_compile_cache()
    if not on_tpu:
        watchdog.cancel()
        watchdog = None

    if on_tpu and not args.no_micro and args.config == "gpt2s" \
            and not args.sweep:
        # default (driver) config only: a staged --config run (or a sweep,
        # which has its own every-config-failed exit path) must NOT be
        # able to exit 0 with the toy canary metric as its last line when
        # its own measurement wedges — those runs already ride a window
        # the default run proved healthy.
        # Wedge-proofing: the FIRST flushed metric lands within ~tens of
        # seconds of a healthy device — before any heavy compile starts —
        # so a wedge later in the run can never reduce this process to a
        # watchdog error (the watchdog re-emits the last complete line).
        try:
            micro_banked = _banked("micro")
            if micro_banked is not None:
                print("  micro canary: banked, skipping", file=sys.stderr)
                _emit(dict(micro_banked, banked=True))
            else:
                _heartbeat("micro_canary")
                _goodput_leg("micro")
                sps, _ = run_micro(quiet=True)
                _heartbeat("micro_canary", "done")
                # vs_baseline 0.0: a toy config has no baseline target and
                # its raw tokens/s against the headline's 10k would
                # misread as a baseline-beating result
                line = {"metric": "micro_gpt2_train_tokens_per_sec_per_chip",
                        "value": round(sps, 1), "unit": "tokens/s",
                        "vs_baseline": 0.0, "config": "micro",
                        "note": "wedge-canary (2-layer GPT); "
                                "headline follows"}
                _emit(line)
                _bank("micro", line)
        except Exception as e:
            _heartbeat("micro_canary", "failed", error=str(e))
            print(f"  micro canary failed ({e})", file=sys.stderr)
        finally:
            # fresh window either way: a slow canary FAILURE must not eat
            # the headline compile's watchdog budget (the r3 failure mode)
            if watchdog is not None:
                watchdog.cancel()
                watchdog = _arm_watchdog(1200)

    if args.config != "gpt2s":
        leg = leg_key("config:" + args.config)
        cached = _banked(leg)
        if cached is not None:
            # the whole config leg already landed in an earlier invocation
            # of this round: re-emit the banked line, skip the compiles
            print(f"  {leg}: banked, skipping", file=sys.stderr)
            if watchdog is not None:
                watchdog.cancel()
            _emit(dict(cached, banked=True))
            return
        _heartbeat("config:" + args.config)
        _goodput_leg(leg)
        extra = None
        line_fields = {}  # extra TOP-LEVEL fields for the final line (mbu)
        if args.config == "resnet50":
            b = args.batch or (64 if on_tpu else 4)
            v = run_resnet50(b, args.steps, quiet=True)
            metric, unit, base = "resnet50_train_imgs_per_sec_per_chip", \
                "imgs/s", 170.0  # ~0.6x a V100-class ResNet-50 fp16 figure
        elif args.config == "bert_dp":
            b = args.batch or (16 if on_tpu else 2)
            s = args.seq or (512 if on_tpu else 128)
            v = run_bert(b, s, args.steps, quiet=True)
            metric, unit, base = "bert_base_train_tokens_per_sec_per_chip", \
                "tokens/s", BASELINE_TOKENS_PER_SEC
        elif args.config == "gpt2s_decode":
            b = args.batch or (8 if on_tpu else 2)
            v, mbu = run_decode(b, args.steps, quiet=True)
            metric, unit, base = "gpt2s_decode_new_tokens_per_sec_per_chip", \
                "tokens/s", 1000.0  # ~A100-class HF GPT-2 batch decode proxy
            # one key, one location: the measured config's own MBU is always
            # top-level "mbu" (mid-run emit AND final line); extras carry
            # only the int8 A/B pair
            line_fields["mbu"] = round(mbu, 4)
            if on_tpu:  # int8/fp8-KV A/B legs ride the same healthy window

                def bank(extra_d=None):
                    """Emit the decode line (ONE construction for both the
                    banked fallbacks and the final form) and open a fresh
                    watchdog window for the next quantized-cache leg — an
                    already-banked line survives a later leg's wedge or
                    crash (the watchdog re-emits the LAST line)."""
                    nonlocal watchdog
                    line = {"metric": metric, "value": round(v, 1),
                            "unit": unit,
                            "vs_baseline": round(v / base, 3),
                            "mbu": round(mbu, 4), "config": args.config}
                    if extra_d:
                        line["extra"] = dict(extra_d)
                    _emit(line)
                    if watchdog is not None:
                        watchdog.cancel()
                        watchdog = _arm_watchdog(1500)

                extra = {}
                bank()
                for leg in ("int8", "fp8"):
                    try:
                        tps_q, mbu_q = run_decode(b, args.steps,
                                                  quiet=True,
                                                  cache_dtype=leg)
                    except Exception as e:
                        print(f"  {leg}-kv decode failed ({e})",
                              file=sys.stderr)
                        return
                    extra["gpt2s_decode_" + leg
                          + "_kv_new_tokens_per_sec_per_chip"] \
                        = round(tps_q, 1)
                    extra[f"gpt2s_decode_{leg}_kv_mbu"] = round(mbu_q, 4)
                    if leg != "fp8":     # the final form falls through to
                        bank(extra)      # the shared emit below
        elif args.config == "gpt2s_serve":
            slots = args.batch or (8 if on_tpu else 2)
            n_req = 3 * slots
            if watchdog is not None:
                # prefill-bucket compiles + the per-row decode step
                watchdog.cancel()
                watchdog = _arm_watchdog(2500)
            v = run_serve(slots, n_req, quiet=True)
            metric, unit, base = \
                "gpt2s_serve_continuous_new_tokens_per_sec_per_chip", \
                "tokens/s", 1000.0  # same class target as gpt2s_decode
            # bank the drain number, then run the REALISM scenario
            # (staggered arrivals + sampling mix + chunked prefill) and
            # re-emit enriched with latency percentiles — a mixed-phase
            # wedge re-emits the banked line via the watchdog
            _emit({"metric": metric, "value": round(v, 1), "unit": unit,
                   "vs_baseline": round(v / base, 3),
                   "config": args.config})
            if watchdog is not None:
                watchdog.cancel()
                watchdog = _arm_watchdog(1500)  # fresh chunk-fn compiles
            try:
                mtps, p50, p99, t50, t99 = run_serve_mixed(slots, n_req,
                                                           quiet=True)
            except Exception as e:  # banked drain number must survive a
                # mixed-phase CRASH too, not just a hang (the watchdog
                # only covers hangs) — same contract as the int8-kv and
                # ppyolo-infer second halves
                print(f"  serve-mixed phase failed: {e}", file=sys.stderr)
                return
            if watchdog is not None:
                watchdog.cancel()
            line = {"metric": metric, "value": round(v, 1), "unit": unit,
                    "vs_baseline": round(v / base, 3),
                    "config": args.config,
                    "extra": {
                        "mixed_new_tokens_per_sec": round(mtps, 1),
                        "mixed_inter_token_p50_ms": round(p50, 2),
                        "mixed_inter_token_p99_ms": round(p99, 2),
                        "mixed_ttft_p50_ms": round(t50, 2),
                        "mixed_ttft_p99_ms": round(t99, 2)}}
            _emit(line)
            _bank(leg, line)
            return
        elif args.config == "gpt2s_16k":
            # long-context single chip: flash attention is what makes 16k
            # fit (VMEM-resident blocks; nothing scales with seq in VMEM)
            b = args.batch or 1
            s = args.seq or (16384 if on_tpu else 512)
            if watchdog is not None:
                watchdog.cancel()
                watchdog = _arm_watchdog(2500)  # long-seq compile headroom
            v, mfu = run_config(b, s, args.steps, quiet=True,
                                window=args.window)
            if watchdog is not None:
                watchdog.cancel()
            line = {
                "metric": "gpt2s_16k_train_tokens_per_sec_per_chip"
                          + (f"_w{args.window}" if args.window else ""),
                "value": round(v, 1), "unit": "tokens/s",
                "vs_baseline": round(v / BASELINE_TOKENS_PER_SEC, 3),
                "mfu": round(mfu, 4), "config": args.config}
            _emit(line)
            _bank(leg, line)
            return
        elif args.config == "gpt2m":
            b = args.batch or (8 if on_tpu else 2)
            s = args.seq or (1024 if on_tpu else 128)
            if watchdog is not None:
                # 24-layer compile is much heavier than gpt2s: one wide
                # window (inside the session script's 3500s budget) so a
                # slow-but-healthy compile isn't mislabeled a wedge
                watchdog.cancel()
                watchdog = _arm_watchdog(2500)
            v, mfu = run_config(b, s, args.steps, quiet=True,
                                cfg_fn=_gpt2m_cfg)
            if watchdog is not None:
                watchdog.cancel()
            line = {
                "metric": "gpt2m_train_tokens_per_sec_per_chip",
                "value": round(v, 1), "unit": "tokens/s",
                # same 10k tok/s/device class target as the BERT/ERNIE row
                "vs_baseline": round(v / BASELINE_TOKENS_PER_SEC, 3),
                "mfu": round(mfu, 4), "config": args.config}
            _emit(line)
            _bank(leg, line)
            return
        elif args.config == "ppyolo":
            b = args.batch or (8 if on_tpu else 1)
            setup = _ppyolo_setup(b)
            v = run_ppyolo_train(b, args.steps, quiet=True, setup=setup)
            metric, unit, base = "ppyoloe_train_imgs_per_sec_per_chip", \
                "imgs/s", 60.0  # ~0.6x a V100-class PP-YOLOE-s 640px figure
            if watchdog is not None:
                watchdog.cancel()          # train measured: tunnel healthy
            if not args.no_extra:
                # the train number must survive an infer hang/kill: emit it
                # now; a successful infer re-emits the full line below (the
                # LAST line is the most complete). The infer half's fresh
                # to_static+NMS compile gets its own watchdog window.
                _emit({"metric": metric, "value": round(v, 1),
                       "unit": unit, "vs_baseline": round(v / base, 3),
                       "config": args.config})
                if watchdog is not None:
                    # generous: must exceed worst-case to_static+NMS compile
                    # (session script budgets 3500s for the two halves)
                    watchdog = _arm_watchdog(1500)
                try:
                    infer_ips = run_ppyolo_infer(b, args.steps, quiet=True,
                                                 setup=setup)
                    extra = {"ppyoloe_infer_nms_imgs_per_sec_per_chip":
                             round(infer_ips, 1)}
                except Exception as e:  # train number already emitted
                    print(f"  ppyolo infer failed ({e})", file=sys.stderr)
                    return
            else:
                watchdog = None
        else:
            b = args.batch or 64
            v = run_lenet(b, args.steps, quiet=True)
            metric, unit, base = "lenet_fit_steps_per_sec", "steps/s", 100.0
        if watchdog is not None:
            watchdog.cancel()
        line = {"metric": metric, "value": round(v, 1),
                "unit": unit, "vs_baseline": round(v / base, 3),
                "config": args.config}
        line.update(line_fields)
        if extra:
            line["extra"] = extra
        _emit(line)
        _bank(leg, line)
        return
    # batch 16 was the r1 sweet spot at seq 1024; the r2 flash retune cut
    # attention HBM traffic, so when no explicit --batch is given on TPU a
    # quick probe (6 steps each) picks among 16/24/32 before the full
    # 20-step measurement.
    batch = args.batch or (16 if on_tpu else 2)
    seq = args.seq or (1024 if on_tpu else 128)

    if on_tpu and args.batch is None and not args.sweep \
            and _banked(headline_leg) is None:
        if watchdog is not None:
            # fresh window sized for THREE cold compiles (the canary's
            # re-arm doesn't run under --no-micro; don't let the probes
            # eat the init window on a healthy device)
            watchdog.cancel()
            watchdog = _arm_watchdog(1500)
        probes = {}
        _heartbeat("batch_probe")
        _goodput_leg("batch_probe")
        # 32 exceeded 16G HBM in r1 PRE-flash; the flash retune freed the
        # attention HBM, so it may fit now — OOM fails fast and is caught
        for b in (16, 24, 32):
            try:
                probes[b], _ = run_config(b, seq, 6, window=args.window)
            except Exception as e:
                print(f"  probe batch={b} failed ({e})", file=sys.stderr)
        if probes:
            batch = max(probes, key=probes.get)
        if watchdog is not None:
            watchdog.cancel()          # device + compile proven healthy
            watchdog = _arm_watchdog(900)

    if args.sweep:
        _heartbeat("sweep")
        _goodput_leg("sweep")
        best = (0.0, 0.0, None)
        for b, s in ((8, 1024), (16, 1024), (24, 1024), (16, 2048),
                     (8, 2048), (4, 4096), (8, 4096)):
            sweep_leg = leg_key(f"sweep:{b}x{s}")
            got = _banked(sweep_leg)
            if got is not None:
                # this (batch, seq) leg landed in an earlier invocation:
                # reuse its number instead of paying the compile again
                tps, mfu = float(got["tps"]), float(got["mfu"])
                print(f"  batch={b} seq={s}: banked {tps:,.0f} tok/s",
                      file=sys.stderr)
                if tps > best[0]:
                    best = (tps, mfu, (b, s))
                continue
            try:
                tps, mfu = run_config(b, s, args.steps, window=args.window)
            except Exception as e:
                print(f"  batch={b} seq={s}: failed ({e})", file=sys.stderr)
                continue
            _bank(sweep_leg, {"tps": tps, "mfu": mfu})
            if watchdog is not None:
                # first config proved the tunnel healthy; a long sweep is
                # not a wedge — stand the watchdog down
                watchdog.cancel()
                watchdog = None
            if tps > best[0]:
                best = (tps, mfu, (b, s))
        tps, mfu, cfg = best
        if cfg is None:
            print(json.dumps({"error": "every sweep config failed"}))
            sys.exit(1)
        _emit({
            "metric": "gpt2s_train_tokens_per_sec_per_chip"
                      + (f"_w{args.window}" if args.window else ""),
            "value": round(tps, 1), "unit": "tokens/s",
            "vs_baseline": round(tps / BASELINE_TOKENS_PER_SEC, 3),
            "mfu": round(mfu, 4), "config": cfg,
        })
        return

    headline_banked = _banked(headline_leg)
    if headline_banked is not None:
        print("  headline: banked, skipping", file=sys.stderr)
        line = dict(headline_banked, banked=True)
        _emit(line)
    else:
        _heartbeat("headline_gpt2s", batch=batch, seq=seq)
        _goodput_leg(headline_leg)
        tps, mfu = run_config(batch, seq, args.steps, quiet=True,
                              window=args.window)
        _heartbeat("headline_gpt2s", "done")
        line = {
            "metric": "gpt2s_train_tokens_per_sec_per_chip"
                      + (f"_w{args.window}" if args.window else ""),
            "value": round(tps, 1),
            "unit": "tokens/s",
            "vs_baseline": round(tps / BASELINE_TOKENS_PER_SEC, 3),
            "mfu": round(mfu, 4),
        }
        # the headline is the round's deliverable: emit AND bank it the
        # moment it exists (the LAST line — re-emitted below with extras —
        # is the most complete; the banked copy survives any later wedge)
        _emit(line)
        _bank(headline_leg, line)
    if on_tpu and not args.no_extra:
        # chip proven healthy by the main measurement: append the ResNet-50
        # milestone (BASELINE #2) and the serving decode metric with MBU,
        # each under a fresh watchdog window — a hang or failure in an
        # extra must not cost the headline (the watchdog re-emits it).
        # Each extra is its own banked leg: a retry re-measures only the
        # legs that never landed.
        def _resnet_extra():
            return {"resnet50_train_imgs_per_sec_per_chip":
                    round(run_resnet50(64, 10, quiet=True), 1)}

        def _decode_extra():
            dtps, dmbu = run_decode(8, 20, quiet=True)
            return {"gpt2s_decode_new_tokens_per_sec_per_chip":
                    round(dtps, 1),
                    "gpt2s_decode_mbu": round(dmbu, 4)}

        extra = {}
        for extra_leg, measure in (("extra:resnet50", _resnet_extra),
                                   ("extra:gpt2s_decode", _decode_extra)):
            got = _banked(extra_leg)
            if got is None:
                if watchdog is not None:
                    watchdog.cancel()
                    watchdog = _arm_watchdog(1200)
                try:
                    _heartbeat(extra_leg)
                    _goodput_leg(extra_leg)
                    got = measure()
                    _bank(extra_leg, got)
                except Exception as e:
                    print(f"  {extra_leg} failed ({e})", file=sys.stderr)
                    continue
            extra.update(got)
            line["extra"] = extra
            _emit(line)
    if watchdog is not None:
        watchdog.cancel()


if __name__ == "__main__":
    main()
