"""Benchmark: GPT-2 small causal-LM training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: tokens/sec/chip for a full jitted train step (fwd+bwd+AdamW) in bfloat16
matmuls — the BASELINE.md north-star family (ERNIE/BERT-class tokens/sec/chip).
vs_baseline: ratio against the reference-class target of 10_000 tokens/sec/device
(0.6 × a ~16.6k tok/s A100+NCCL BERT-base-class figure — BASELINE.json's ≥60% goal),
since the reference repo publishes no absolute numbers (BASELINE.md: "published: {}").
"""
import json
import time

import numpy as np

BASELINE_TOKENS_PER_SEC = 10_000.0


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainLoss

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    # batch 16 is the single-chip sweet spot (measured 74.9k tok/s vs 53.8k at
    # batch 8; batch 32 exceeds 16G HBM for GPT-2 small at seq 1024)
    batch, seq = (16, 1024) if on_tpu else (2, 128)

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12, num_heads=12,
                    max_seq_len=seq, dropout=0.0)
    if not on_tpu:  # keep the CPU fallback tractable
        cfg = GPTConfig(vocab_size=8192, hidden_size=256, num_layers=4, num_heads=8,
                        max_seq_len=seq, dropout=0.0)
    model = GPTForCausalLM(cfg)
    loss_layer = GPTPretrainLoss()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())

    mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
    trainer = SpmdTrainer(model, opt, loss_fn=loss_layer, mesh=mesh)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    with paddle.amp.auto_cast(True, dtype="bfloat16"):
        # warmup + compile (host-copy forces real completion through the device tunnel)
        np.asarray(trainer.train_step(ids, labels)._data)
        n_steps = 20 if on_tpu else 3
        t0 = time.perf_counter()
        loss = None
        for _ in range(n_steps):
            loss = trainer.train_step(ids, labels)
        # trailing sync: the last loss + a param leaf depend on every prior step
        np.asarray(loss._data)
        np.asarray(next(iter(trainer.params.values()))[(0,) * trainer.params[next(iter(trainer.params))].ndim])
        dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * n_steps / dt
    print(json.dumps({
        "metric": "gpt2s_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(tokens_per_sec / BASELINE_TOKENS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
