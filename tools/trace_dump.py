"""Trace dump CLI: run a traced workload, check its span families, export.

    python tools/trace_dump.py --model gpt --train        # traced train step
    python tools/trace_dump.py --serving                  # traced serving loop
    python tools/trace_dump.py --router                   # multi-engine tier
    python tools/trace_dump.py --serving --chrome out.json
    python tools/trace_dump.py --all --json               # machine report

Each target runs under FLAGS_trace=1 at CPU-shrunk shapes (the
metrics_dump runners), then the collected spans are audited: a target
missing a REQUIRED span family — train: train_step; serving: request /
queue_wait / prefill / decode sharing one trace_id per request — reports
an error-severity finding and the exit code is 1 (the acceptance
criterion in executable form). ``--chrome`` additionally writes the
merged chrome://tracing JSON (host RecordEvents + spans + flow links +
counter samples; open in chrome://tracing or Perfetto).

``--json`` emits the tools/graph_lint.py report schema ({"tool",
"passes", "targets": {name: {"name", "counts", "findings"}}, "totals"},
plus per-target "trace" summary and "cost_table"), so CI reads
graph_lint / op_coverage / metrics_dump / trace_dump through one loader.
"""
import argparse
import importlib.util
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MODEL_TARGETS = ("gpt", "bert", "ernie")

# span families that MUST appear in a target's trace
REQUIRED = {
    "train": ("train_step",),
    "serving": ("request", "queue_wait", "prefill", "decode"),
    # the multi-engine tier: route (Router placement) + kv_handoff
    # (disaggregated prefill->decode transfer) threading into the same
    # engine span families the monolithic loop emits
    "router": ("route", "kv_handoff", "request", "queue_wait", "decode"),
}


def _load_runners():
    """The metrics_dump workload runners — one source for both CLIs."""
    spec = importlib.util.spec_from_file_location(
        "._metrics_dump_runners",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "metrics_dump.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run_target(name):
    """Run one target under FLAGS_trace; returns (spans, findings)."""
    from paddle_tpu import trace
    from paddle_tpu.trace import costs

    md = _load_runners()
    trace.clear()
    costs.reset()   # each target reports ITS executables, not the
    trace.enable()  # accumulated table of every earlier target
    try:
        if name == "serving":
            md.run_serving_loop()
        elif name == "router":
            md.run_router_loop()
        else:
            md.run_train_step(name)
    finally:
        trace.disable()
    spans = trace.spans()
    kind = name if name in ("serving", "router") else "train"
    names = {s.name for s in spans}
    findings = []
    for fam in REQUIRED[kind]:
        if fam not in names:
            findings.append({
                "pass": "spans-present", "severity": "error",
                "message": f"required span family {fam!r} missing after "
                           f"the {name} run", "where": name})
    if kind == "serving":
        # every request's lifecycle spans must share its trace_id
        roots = [s for s in spans if s.name == "request"]
        if not roots:
            findings.append({"pass": "trace-linkage", "severity": "error",
                             "message": "no request root spans recorded",
                             "where": name})
        for root in roots:
            members = {s.name for s in spans if s.trace_id == root.trace_id}
            missing = {"queue_wait", "decode"} - members
            if missing:
                findings.append({
                    "pass": "trace-linkage", "severity": "error",
                    "message": f"request trace {root.trace_id} is missing "
                               f"span families {sorted(missing)}",
                    "where": name})
    if kind == "router":
        # placement and handoff spans must THREAD into engine traces:
        # a route/kv_handoff trace_id with no request/decode members
        # means the propagation chain (submit trace_id=/parent_span=)
        # broke somewhere
        for fam, need in (("route", {"request"}),
                          ("kv_handoff", {"request", "decode"})):
            for root in [s for s in spans if s.name == fam]:
                members = {s.name for s in spans
                           if s.trace_id == root.trace_id}
                missing = need - members
                if missing:
                    findings.append({
                        "pass": "trace-linkage", "severity": "error",
                        "message": f"{fam} trace {root.trace_id} is "
                                   f"missing span families "
                                   f"{sorted(missing)}",
                        "where": name})
    if kind == "train":
        steps = [s for s in spans if s.name == "train_step"]
        if steps and not any(
                costs.get("trainer", s.attrs.get("sig")) for s in steps):
            findings.append({
                "pass": "cost-join", "severity": "error",
                "message": "train_step spans have no matching cost-"
                           "registry entry (MFU join would be empty)",
                "where": name})
    for nm, total_ms, count in trace.top_spans(5):
        findings.append({"pass": "spans", "severity": "info",
                         "message": f"{nm}: {count} spans, "
                                    f"{total_ms:.3f} ms total",
                         "where": name})
    return spans, findings


def build_report(targets):
    from paddle_tpu.trace import costs

    report = {"tool": "trace_dump",
              "passes": ["spans-present", "trace-linkage", "cost-join"],
              "targets": {},
              "totals": {"error": 0, "warning": 0, "info": 0}}
    for name in targets:
        spans, findings = run_target(name)
        counts = {"error": 0, "warning": 0, "info": 0}
        for f in findings:
            counts[f["severity"]] += 1
        from paddle_tpu import trace

        report["targets"][name] = {
            "name": name, "counts": counts, "findings": findings,
            "trace": trace.snapshot_summary(5),
            "cost_table": costs.table(),
        }
        if name in ("serving", "router"):
            # with the flight recorder on, the serving/router targets
            # also carry the ring summary (span digests + byte tags) —
            # the same view a dump bundle would open with
            from paddle_tpu.monitor import blackbox

            if blackbox.is_enabled():
                report["targets"][name]["blackbox_ring"] = \
                    blackbox.ring_summary(5)
        for sev, n in counts.items():
            report["totals"][sev] += n
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", choices=MODEL_TARGETS, action="append",
                    default=[], help="trace one bundled model (use with "
                                     "--train; implied when given)")
    ap.add_argument("--train", action="store_true",
                    help="trace a train step for the chosen --model "
                         "(default gpt when no --model given)")
    ap.add_argument("--serving", action="store_true",
                    help="trace the ServingEngine decode loop")
    ap.add_argument("--router", action="store_true", dest="router",
                    help="trace the multi-engine tier (Router fan-out + "
                         "disaggregated handoff); exit 1 when the "
                         "route/kv_handoff span families are missing or "
                         "unlinked")
    ap.add_argument("--all", action="store_true",
                    help="all models + the serving loop + the router "
                         "tier")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the graph_lint-schema machine report")
    ap.add_argument("--chrome", metavar="OUT.json",
                    help="also write the merged chrome://tracing JSON of "
                         "the LAST target's spans")
    args = ap.parse_args(argv)

    targets = list(args.model)
    if args.train and not targets:
        targets = ["gpt"]
    if args.serving:
        targets.append("serving")
    if args.router:
        targets.append("router")
    if args.all:
        targets = list(MODEL_TARGETS) + ["serving", "router"]
    if not targets:
        ap.error("pick a target: --model NAME [--train], --serving, "
                 "--router or --all")

    report = build_report(targets)
    if args.chrome:
        from paddle_tpu import trace

        trace.export_chrome(args.chrome)
        report["chrome"] = args.chrome
    if args.as_json:
        print(json.dumps(report, indent=1))
    else:
        for name, t in report["targets"].items():
            print(f"# target: {name}")
            print(json.dumps({"trace": t["trace"],
                              "cost_entries": len(t["cost_table"])},
                             sort_keys=True))
            for f in t["findings"]:
                print(f"  [{f['severity']}] {f['pass']}: {f['message']}")
    return 1 if report["totals"]["error"] else 0


if __name__ == "__main__":
    sys.exit(main())
