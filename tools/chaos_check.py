"""Chaos gate CLI: drive short serving + trainer + checkpoint loops under a
canned fault schedule (paddle_tpu.testing.failpoints) and verify every
recovery path actually recovers.

    python tools/chaos_check.py           # human-readable
    python tools/chaos_check.py --json    # machine-readable report

Checks (one entry per name in `passes`):

  ckpt_atomic        a save killed between payload and commit leaves the
                     destination checkpoint untouched
  ckpt_fallback      a corrupt newest checkpoint is evicted and the
                     previous valid one restored
  serving_deadline   an overdue request finishes reason="deadline" while
                     its batch-mate decodes to exact greedy parity
  serving_slot_error an injected per-slot error evicts ONLY that slot;
                     the survivor stays bit-exact
  serving_shed       a full bounded queue raises QueueFullError and a
                     higher-priority arrival sheds the lowest
  router_failover    one of a Router's two engines is killed mid-stream
                     via the serving/step failpoint; every request —
                     including the dead engine's in-flight ones —
                     finishes on the survivor with exact greedy parity
  stall_dump         a serving/step=delay failpoint wedges an engine;
                     the blackbox stall sentinel fires DURING the wedge
                     and its dump bundle names site=serving/step, the
                     in-flight rids, and all-thread stacks — then the
                     engine drains to exact greedy parity
  stage_backpressure with FLAGS_mpmd armed the disagg pool's handoff
                     rides a typed StageEdge: a full edge rejects the
                     overflow put (EdgeFullError, counted, nothing
                     lost on drain), a stage/edge=delay failpoint
                     wedges one hand-off mid-run and the stall
                     sentinel fires DURING the wedge naming
                     site=stage/edge, then the drain keeps exact
                     greedy parity with edge puts==gets==prompts
  trainer_nonfinite  a NaN batch under FLAGS_check_nan_inf skips the
                     update, leaving params/moments bit-identical
  numerics_anomaly   a trainer/batch=scale failpoint injects a gradient
                     spike: the numerics telescope's drift detector
                     fires (naming the layer) BEFORE the non-finite
                     guard ever trips; a follow-up scale:nan step then
                     trips the guard AND the per-layer nonfinite
                     detector on the same step
  quantized_nonfinite a trainer/batch=scale:nan failpoint under the
                     FLAGS_quantized_allreduce path: the PR 4 guard
                     still trips through the int8 reduce (NaN poisons
                     the fp32 block scales, staying loud), params stay
                     bit-identical, AND the error-feedback residuals
                     are where-selected back bit-exactly — no
                     quantization poison carried into the next step,
                     which then trains normally
  adapter_evict_under_load the FLAGS_paged_kv engine's hot adapter is
                     evicted mid-stream: the live session requeues (not
                     reason='error'), re-admits after a hot-reload and
                     finishes bit-exact vs an undisturbed twin; a
                     serving/adapter=error:1 failpoint on a load leaves
                     the registry untouched
  page_pool_full     paged-KV pool exhaustion backpressures BEFORE any
                     work: a never-fits request is rejected at submit
                     with zero pool mutation, transient exhaustion
                     requeues to bit-exact completion, drain frees
                     every block
  elastic_resume     a dp8 run under the ElasticSupervisor is killed
                     mid-step (trainer/step failpoint) with the dp8
                     topology marked gone: the supervisor resumes on
                     dp4 through the topology-aware restore, the loss
                     trajectory stays within tolerance of an
                     uninterrupted dp8 twin, and the recovery is
                     attributed (blackbox crash bundle at
                     site=elastic/resume + elastic_resume_total
                     {reason=failpoint})
  goodput_attribution the elastic_resume kill re-run under FLAGS_goodput:
                     the finalized run's ledger row books nonzero
                     resume_backoff + ckpt_restore + reshard seconds,
                     its buckets sum to wall time within 10%, its
                     goodput lands below an uninterrupted twin's (which
                     books >= 95% of post-warmup wall as step+compile),
                     and the crash bundle's goodput provider names the
                     bucket active at kill time (step)
  stage_replace      one stage of a FLAGS_mpmd 2-stage pipeline is
                     killed via the stage/run failpoint; replace_stage
                     rebinds JUST that stage onto a replacement mesh
                     (sibling programs' compiled entries asserted
                     untouched, the rebind disk-hits a warmed
                     FLAGS_jit_cache_dir) and training continues to
                     loss parity with an uninterrupted twin

Report format: the tools/graph_lint.py schema ({"tool", "passes",
"targets": {name: {"name", "counts", "findings"}}, "totals"}), so CI reads
graph_lint, op_coverage, metrics_dump, aot_warm, and chaos_check through
one loader. Exit code 1 when any recovery path fails (error-severity
finding), else 0. Wired into tier-1 by tests/test_failpoints_gate.py.
"""
import argparse
import json
import os
import sys
import tempfile
import time

# the elastic passes build dp8 meshes on the CPU backend (same forcing
# as tools/parity_check.py — must precede the jax import)
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PASSES = ["ckpt_atomic", "ckpt_fallback", "serving_deadline",
          "serving_slot_error", "serving_shed", "router_failover",
          "stall_dump", "stage_backpressure", "trainer_nonfinite",
          "numerics_anomaly", "quantized_nonfinite", "async_nonfinite",
          "adapter_evict_under_load", "page_pool_full",
          "elastic_resume", "stage_replace", "goodput_attribution"]


def _finding(name, severity, message, where=""):
    return {"pass": name, "severity": severity, "message": message,
            "where": where}


def _ok(name, message):
    return _finding(name, "info", message)


def _check_ckpt_atomic():
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.testing import failpoints as fp

    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "state.pdparams")
        paddle.save({"w": paddle.to_tensor(np.ones(4))}, p)
        before = open(p, "rb").read()
        try:
            with fp.scoped("ckpt/write=error:1"):
                paddle.save({"w": paddle.to_tensor(np.zeros(4))}, p)
            return [_finding("ckpt_atomic", "error",
                             "armed ckpt/write failpoint did not fire")]
        except fp.FailpointError:
            pass
        if open(p, "rb").read() != before:
            return [_finding("ckpt_atomic", "error",
                             "destination changed after a failed save — "
                             "the commit is not atomic", where=p)]
        out = paddle.load(p)
        if not np.array_equal(np.asarray(out["w"]._data), np.ones(4)):
            return [_finding("ckpt_atomic", "error",
                             "surviving checkpoint does not load the "
                             "pre-fault state", where=p)]
    return [_ok("ckpt_atomic",
                "failed save left the committed checkpoint bit-intact")]


def _check_ckpt_fallback():
    import warnings

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.incubate.checkpoint.auto_checkpoint import \
        CheckpointSaver

    with tempfile.TemporaryDirectory() as d:
        saver = CheckpointSaver(d)
        saver.save_checkpoint({"v": paddle.to_tensor(np.zeros(2))},
                              meta={"epoch": 0})
        saver.save_checkpoint({"v": paddle.to_tensor(np.ones(2))},
                              meta={"epoch": 1})
        newest = os.path.join(d, "__paddle_checkpoint__.1",
                              "state.pdparams")
        blob = open(newest, "rb").read()
        open(newest, "wb").write(blob[: len(blob) // 2])   # truncate
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            state, meta = saver.load_checkpoint()
        if meta is None or meta.get("epoch") != 0:
            return [_finding("ckpt_fallback", "error",
                             "corrupt newest checkpoint did not fall back "
                             f"to the previous valid one (meta={meta})",
                             where=newest)]
        if saver.get_checkpoint_numbers() != [0]:
            return [_finding("ckpt_fallback", "error",
                             "corrupt checkpoint was not evicted: "
                             f"{saver.get_checkpoint_numbers()}")]
    return [_ok("ckpt_fallback",
                "corrupt newest checkpoint evicted; epoch-0 state restored")]


def _tiny_model():
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=2, max_seq_len=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _ref_tokens(m, p, n):
    import numpy as np

    import paddle_tpu as paddle

    out = m.generate(paddle.to_tensor(p[None]), max_new_tokens=n,
                     temperature=0.0)
    return np.asarray(out._data)[0, len(p):]


def _check_serving_deadline(m):
    import numpy as np

    from paddle_tpu.inference.serving import ServingEngine

    rng = np.random.RandomState(0)
    p1 = rng.randint(0, 64, (5,)).astype(np.int32)
    p2 = rng.randint(0, 64, (9,)).astype(np.int32)
    eng = ServingEngine(m, max_batch=2)
    r1 = eng.submit(p1, max_new_tokens=6)
    r2 = eng.submit(p2, max_new_tokens=6, deadline_ms=0.001)
    time.sleep(0.005)
    res = eng.run_until_complete()
    if res[r2].finish_reason != "deadline":
        return [_finding("serving_deadline", "error",
                         "overdue request finished with "
                         f"{res[r2].finish_reason!r}, not 'deadline'")]
    if not np.array_equal(res[r1].tokens, _ref_tokens(m, p1, 6)):
        return [_finding("serving_deadline", "error",
                         "batch-mate of an expired request lost greedy "
                         "parity")]
    return [_ok("serving_deadline",
                "overdue request expired; batch-mate stayed bit-exact")]


def _check_serving_slot_error(m):
    import numpy as np

    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.testing import failpoints as fp

    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, 64, (n,)).astype(np.int32) for n in (4, 7)]
    eng = ServingEngine(m, max_batch=2)
    rids = [eng.submit(p, max_new_tokens=6) for p in prompts]
    eng.step()
    with fp.scoped("serving/slot=error:1"):
        eng.step()
    res = eng.run_until_complete()
    reasons = {rid: res[rid].finish_reason for rid in rids}
    if sorted(reasons.values()) != ["error", "length"]:
        return [_finding("serving_slot_error", "error",
                         "injected slot error did not evict exactly one "
                         f"request (reasons={reasons})")]
    (surv,) = [rid for rid in rids if reasons[rid] == "length"]
    if not np.array_equal(res[surv].tokens,
                          _ref_tokens(m, prompts[rids.index(surv)], 6)):
        return [_finding("serving_slot_error", "error",
                         "the surviving slot lost greedy parity")]
    return [_ok("serving_slot_error",
                "injected slot error isolated; survivor bit-exact")]


def _check_serving_shed(m):
    import numpy as np

    from paddle_tpu.inference.serving import QueueFullError, ServingEngine

    rng = np.random.RandomState(2)
    p = rng.randint(0, 64, (5,)).astype(np.int32)
    eng = ServingEngine(m, max_batch=1, max_queue=1)
    low = eng.submit(p, max_new_tokens=2, priority=0)
    try:
        eng.submit(p, max_new_tokens=2, priority=0)
        return [_finding("serving_shed", "error",
                         "full queue accepted an equal-priority request")]
    except QueueFullError:
        pass
    eng.submit(p, max_new_tokens=2, priority=5)
    if eng.get_request(low).finish_reason != "shed":
        return [_finding("serving_shed", "error",
                         "higher-priority arrival did not shed the "
                         "lowest-priority queued request")]
    return [_ok("serving_shed",
                "queue bound enforced; priority shedding works")]


def _check_router_failover(m):
    import numpy as np

    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.serving.router import Router
    from paddle_tpu.testing import failpoints as fp

    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, 64, (n,)).astype(np.int32)
               for n in (4, 7, 9)]
    router = Router({"a": ServingEngine(m, max_batch=2),
                     "b": ServingEngine(m, max_batch=2)})
    rids = [router.submit(p, max_new_tokens=6, session_id=i)
            for i, p in enumerate(prompts)]
    for _ in range(2):
        router.step()   # tokens already streaming on both engines
    with fp.scoped("serving/step=error:1"):
        router.step()   # the first stepped engine dies mid-stream
    st = router.stats()["router"]
    if len(st["dead"]) != 1:
        return [_finding("router_failover", "error",
                         "killed engine was not marked dead "
                         f"(dead={st['dead']})")]
    res = router.run_until_complete()
    for rid, p in zip(rids, prompts):
        if res[rid].finish_reason != "length":
            return [_finding(
                "router_failover", "error",
                f"request {rid} finished with "
                f"{res[rid].finish_reason!r}, not 'length' — the finish "
                "reason was lost in the failover")]
        if not np.array_equal(res[rid].tokens, _ref_tokens(m, p, 6)):
            return [_finding("router_failover", "error",
                             f"request {rid} lost greedy parity after "
                             "re-routing to the survivor")]
    (survivor,) = st["alive"]
    stranded = [rid for rid in rids
                if router._reqs[rid].engine != survivor]
    if stranded:
        return [_finding("router_failover", "error",
                         f"requests {stranded} did not end on the "
                         f"surviving engine {survivor!r}")]
    return [_ok("router_failover",
                "engine killed mid-stream; all requests finished on the "
                "survivor, bit-exact, reasons recorded")]


def _check_stall_dump(m):
    """Chaos-injected stall: a serving/step=delay failpoint wedges one
    engine step; the sentinel (short timeout) must fire DURING the wedge
    and leave a bundle naming site=serving/step + the in-flight rids."""
    import glob

    import numpy as np

    from paddle_tpu import flags
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.monitor import blackbox as bb
    from paddle_tpu.testing import failpoints as fp

    rng = np.random.RandomState(4)
    prompt = rng.randint(0, 64, (5,)).astype(np.int32)
    tmp_ctx = tempfile.TemporaryDirectory(
        prefix="paddle_tpu_chaos_blackbox_")
    d = tmp_ctx.name
    old_dir = flags.get_flag("blackbox_dir", "")
    was_enabled = bb.is_enabled()
    bb.enable(install=False)
    flags.set_flags({"blackbox_dir": d})
    try:
        eng = ServingEngine(m, max_batch=1)
        rid = eng.submit(prompt, max_new_tokens=6)
        eng.step()   # a healthy beat first: the stall is a TRANSITION
        bb.start_sentinel(timeout_s=0.15, poll_s=0.05)
        with fp.scoped("serving/step=delay:800"):
            eng.step()   # wedged inside the delay; the sentinel fires
        # the sentinel writes the bundle on ITS thread: poll briefly so a
        # loaded CI machine's slow write doesn't read as a missed fire
        deadline = time.time() + 3.0
        bundles = []
        while time.time() < deadline:
            bundles = sorted(glob.glob(os.path.join(d,
                                                    "blackbox-*.json")))
            if bundles:
                break
            time.sleep(0.05)
        if not bundles:
            return [_finding("stall_dump", "error",
                             "sentinel did not write a dump bundle while "
                             "the engine step was wedged")]
        bundle = bb.load_bundle(bundles[0])
        if bundle["reason"] != "stall" \
                or bundle.get("site") != "serving/step":
            return [_finding(
                "stall_dump", "error",
                f"bundle names reason={bundle['reason']!r} "
                f"site={bundle.get('site')!r}, expected a stall at "
                "serving/step")]
        tables = [t["table"] for t in bundle.get("requests", [])
                  if t.get("kind") == "serving_engine" and "table" in t]
        if not any(rid in t.get("in_flight", []) for t in tables):
            return [_finding("stall_dump", "error",
                             f"wedged request rid={rid} missing from the "
                             "bundle's in-flight request tables")]
        if not bundle.get("stacks"):
            return [_finding("stall_dump", "error",
                             "bundle carries no all-thread stacks")]
        res = eng.run_until_complete()
        if not np.array_equal(res[rid].tokens, _ref_tokens(m, prompt, 6)):
            return [_finding("stall_dump", "error",
                             "the wedged-then-released request lost "
                             "greedy parity")]
    finally:
        bb.stop_sentinel()
        flags.set_flags({"blackbox_dir": old_dir})
        bb.quiesce()
        bb.reset()
        if not was_enabled:
            bb.disable()
        tmp_ctx.cleanup()
    return [_ok("stall_dump",
                "sentinel fired during the wedge; bundle named "
                "site=serving/step + in-flight rids; drain stayed "
                "bit-exact")]


def _check_stage_backpressure(m):
    """Chaos-injected MPMD edge stall: with FLAGS_mpmd armed the disagg
    pool's prefill->decode hand-off travels a typed StageEdge. First a
    full edge must reject the overflow put (EdgeFullError, counted as
    backpressure) and still drain every accepted payload FIFO bit-exact;
    then a stage/edge=delay failpoint wedges one live hand-off inside the
    edge's beacon window — the stall sentinel must fire DURING the wedge
    naming site=stage/edge, and the post-stall drain must keep exact
    greedy parity with edge puts==gets==prompts (no payload lost)."""
    import glob

    import numpy as np

    from paddle_tpu import flags
    from paddle_tpu.monitor import blackbox as bb
    from paddle_tpu.serving.disagg import DisaggregatedPool
    from paddle_tpu.testing import failpoints as fp

    name = "stage_backpressure"
    old_mpmd = flags.get_flag("mpmd", False)
    flags.set_flags({"mpmd": True})
    try:
        from paddle_tpu.distributed import stage as stage_mod

        # 1) a FULL edge backpressures without loss: a capacity-2 queue
        # rejects the third put before doing any work, counts it, then
        # drains FIFO bit-exact and accepts the retried payload
        edge = stage_mod.StageEdge("chaos", stage_mod.HANDOFF_SCHEMA,
                                   capacity=2)
        rows = [np.full((1, 2, 4), float(i + 1), np.float32)
                for i in range(3)]
        for r in rows[:2]:
            edge.put({"activation": r})
        try:
            edge.put({"activation": rows[2]})
            return [_finding(name, "error",
                             "third put on a capacity-2 edge did not "
                             "raise EdgeFullError")]
        except stage_mod.EdgeFullError:
            pass
        if edge.stats["backpressured"] != 1 or edge.stats["puts"] != 2:
            return [_finding(name, "error",
                             "rejected put was not booked as pure "
                             f"backpressure: {edge.stats}")]
        drained = [edge.get()["activation"] for _ in range(2)]
        edge.put({"activation": rows[2]})   # the producer's retry lands
        drained.append(edge.get()["activation"])
        for want, got in zip(rows, drained):
            if not np.array_equal(np.asarray(got), want):
                return [_finding(name, "error",
                                 "backpressured edge lost or reordered a "
                                 "payload on drain")]

        # 2) the armed pool wedged INSIDE a live edge put: two waves of
        # prompts so the wedged step still has a free decode slot (and
        # therefore actually touches the edge), healthy beat first so
        # the stall is a transition the sentinel can see
        rng = np.random.RandomState(7)
        prompts = [rng.randint(0, 64, (n,)).astype(np.int32)
                   for n in (5, 7, 4, 6, 5, 8)]
        tmp_ctx = tempfile.TemporaryDirectory(
            prefix="paddle_tpu_chaos_stage_")
        d = tmp_ctx.name
        old_dir = flags.get_flag("blackbox_dir", "")
        was_enabled = bb.is_enabled()
        bb.enable(install=False)
        flags.set_flags({"blackbox_dir": d})
        try:
            pool = DisaggregatedPool(m, prefill_workers=1,
                                     decode_engines=1, max_batch=3)
            rids = [pool.submit(p, max_new_tokens=5) for p in prompts[:2]]
            pool.step()   # healthy hand-offs first
            rids += [pool.submit(p, max_new_tokens=5) for p in prompts[2:]]
            bb.start_sentinel(timeout_s=0.15, poll_s=0.05)
            with fp.scoped("stage/edge=delay:800"):
                pool.step()   # one free slot -> one wedged hand-off
            deadline = time.time() + 3.0
            bundles = []
            while time.time() < deadline:
                bundles = sorted(glob.glob(os.path.join(
                    d, "blackbox-*.json")))
                if bundles:
                    break
                time.sleep(0.05)
            if not bundles:
                return [_finding(name, "error",
                                 "sentinel wrote no dump bundle while a "
                                 "stage-edge hand-off was wedged")]
            bundle = bb.load_bundle(bundles[0])
            if bundle["reason"] != "stall" \
                    or bundle.get("site") != "stage/edge":
                return [_finding(
                    name, "error",
                    f"bundle names reason={bundle['reason']!r} "
                    f"site={bundle.get('site')!r}, expected a stall at "
                    "stage/edge")]
            res = pool.run_until_complete()
            for rid, p in zip(rids, prompts):
                if not np.array_equal(res[rid].tokens,
                                      _ref_tokens(m, p, 5)):
                    return [_finding(name, "error",
                                     "post-stall drain lost greedy "
                                     f"parity for rid={rid}")]
            st = pool.stats()["edge"]
            if st["puts"] != len(prompts) or st["gets"] != len(prompts):
                return [_finding(name, "error",
                                 "edge puts/gets do not match the prompt "
                                 f"count — a payload was lost: {st}")]
        finally:
            bb.stop_sentinel()
            flags.set_flags({"blackbox_dir": old_dir})
            bb.quiesce()
            bb.reset()
            if not was_enabled:
                bb.disable()
            tmp_ctx.cleanup()
    finally:
        flags.set_flags({"mpmd": old_mpmd})
    return [_ok(name,
                "full edge backpressured without loss; sentinel fired "
                "during the wedge naming site=stage/edge; post-stall "
                "drain stayed bit-exact with puts==gets==prompts")]


def _export_tiny_adapter(m, seed):
    """A LoRA export over the tiny chaos model, lora_B randomized so the
    adapter's delta actually moves tokens."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.incubate.lora import apply_lora, export_lora
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=2, max_seq_len=64, dropout=0.0)
    m2 = GPTForCausalLM(cfg)
    m2.load_dict(m.state_dict())
    apply_lora(m2, r=4, alpha=8)
    rng = np.random.RandomState(seed)
    for n_, p_ in m2.named_parameters():
        if "lora_B" in n_:
            p_.set_value(paddle.to_tensor(
                rng.normal(0, 0.3, p_.shape).astype(np.float32)))
    return export_lora(m2)


def _check_adapter_evict_under_load(m):
    """Chaos-injected adapter churn on the FLAGS_paged_kv engine: the hot
    adapter is evicted while its session is mid-stream — the session must
    be booted back to the queue (NOT finished reason='error'), re-admit
    after the adapter hot-reloads, and finish bit-exact against an
    undisturbed twin. A serving/adapter=error:1 failpoint on a load must
    additionally leave the registry and device factors exactly as they
    were, with in-flight sessions still decoding."""
    import numpy as np

    from paddle_tpu import flags
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.testing import failpoints as fp

    name = "adapter_evict_under_load"
    old = {"paged_kv": flags.get_flag("paged_kv")}
    flags.set_flags({"paged_kv": True})
    try:
        expA = _export_tiny_adapter(m, 11)
        expB = _export_tiny_adapter(m, 12)
        rng = np.random.RandomState(5)
        prompt = rng.randint(0, 64, (5,)).astype(np.int32)

        ref_eng = ServingEngine(m, max_batch=2, max_adapters=2)
        ref_eng.load_adapter("hot", expA)
        rr = ref_eng.submit(prompt, max_new_tokens=8, adapter="hot")
        ref = tuple(int(t)
                    for t in ref_eng.run_until_complete()[rr].output_ids)

        eng = ServingEngine(m, max_batch=2, max_adapters=2)
        eng.load_adapter("hot", expA)
        rid = eng.submit(prompt, max_new_tokens=8, adapter="hot")
        for _ in range(3):
            eng.step()          # mid-stream: tokens already emitted
        if not eng.get_request(rid).output_ids:
            return [_finding(name, "error",
                             "scenario broken: no tokens streamed before "
                             "the eviction")]
        eng.evict_adapter("hot")   # under load: boots the live session
        req = eng.get_request(rid)
        if req.finish_reason is not None:
            return [_finding(name, "error",
                             "evicting the hot adapter finished its "
                             f"session (reason={req.finish_reason!r}) "
                             "instead of requeueing it")]
        with fp.scoped("serving/adapter=error:1"):
            try:
                eng.load_adapter("other", expB)
                return [_finding(name, "error",
                                 "armed serving/adapter failpoint did "
                                 "not fire on load_adapter")]
            except fp.FailpointError:
                pass
        if eng._adapters.lookup("other") is not None:
            return [_finding(name, "error",
                             "a load that died on the failpoint still "
                             "mutated the adapter registry")]
        eng.load_adapter("hot", expA)   # hot-reload: the session re-admits
        res = eng.run_until_complete()
        got = tuple(int(t) for t in res[rid].output_ids)
        if res[rid].finish_reason != "length" or got != ref:
            return [_finding(
                name, "error",
                "evicted-then-reloaded session lost bit-exactness vs the "
                f"undisturbed twin (reason={res[rid].finish_reason!r}, "
                f"got={list(got)}, want={list(ref)})")]
    finally:
        flags.set_flags(old)
    return [_ok(name,
                "hot adapter evicted mid-stream; session requeued (not "
                "errored), re-admitted after hot-reload, bit-exact vs "
                "the undisturbed twin; a failed load left the registry "
                "untouched")]


def _check_page_pool_full(m):
    """Paged-KV pool exhaustion: reservation-before-compute means a full
    pool backpressures BEFORE any prefill work — a permanently-oversized
    request is rejected at submit() (pool counters unmoved), and
    transient exhaustion requeues sessions until blocks free, every one
    finishing reason='length' bit-exact against a roomy-pool twin."""
    import numpy as np

    from paddle_tpu import flags
    from paddle_tpu.inference.serving import ServingEngine

    name = "page_pool_full"
    old = {"paged_kv": flags.get_flag("paged_kv")}
    flags.set_flags({"paged_kv": True})
    try:
        rng = np.random.RandomState(6)
        prompts = [rng.randint(0, 64, (n,)).astype(np.int32)
                   for n in (4, 6, 5)]

        # 3 usable frames (+ null): a 60-column budget needs 4 blocks —
        # never fits; the 3-block transient requests fit one at a time
        eng = ServingEngine(m, max_batch=4, page_blocks=4)
        free0 = eng._pool.stats()["free_blocks"]
        try:
            eng.submit(rng.randint(0, 64, (40,)).astype(np.int32),
                       max_new_tokens=20)
            return [_finding(name, "error",
                             "a request that can NEVER fit the pool was "
                             "accepted instead of rejected at submit()")]
        except ValueError:
            pass
        if eng._pool.stats()["free_blocks"] != free0:
            return [_finding(name, "error",
                             "the rejected oversized request leaked pool "
                             "blocks — work happened before the "
                             "reservation check")]
        rids = [eng.submit(p, max_new_tokens=30) for p in prompts]
        res = eng.run_until_complete()
        roomy = ServingEngine(m, max_batch=4)
        rids2 = [roomy.submit(p, max_new_tokens=30) for p in prompts]
        res2 = roomy.run_until_complete()
        for i, (a, b) in enumerate(zip(rids, rids2)):
            if res[a].finish_reason != "length":
                return [_finding(
                    name, "error",
                    f"request {i} under the tiny pool finished "
                    f"{res[a].finish_reason!r}, not 'length' — "
                    "backpressure turned into an error")]
            if [int(t) for t in res[a].output_ids] \
                    != [int(t) for t in res2[b].output_ids]:
                return [_finding(name, "error",
                                 f"request {i} lost bit-exactness under "
                                 "pool-full requeueing")]
        if eng._pool.stats()["live_blocks"] != 0:
            return [_finding(name, "error",
                             "drained engine still holds live pool "
                             f"blocks: {eng._pool.stats()}")]
    finally:
        flags.set_flags(old)
    return [_ok(name,
                "oversized request rejected before any work; transient "
                "pool exhaustion requeued sessions to bit-exact "
                "completion; all blocks freed on drain")]


def _check_trainer_nonfinite():
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer

    paddle.set_flags({"check_nan_inf": True})
    try:
        paddle.seed(0)
        model = paddle.nn.Linear(4, 1)
        opt = paddle.optimizer.Adam(learning_rate=0.1,
                                    parameters=model.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        tr = SpmdTrainer(model, opt, loss_fn=paddle.nn.MSELoss(),
                         mesh=mesh)
        x = np.ones((2, 4), np.float32)
        y = np.zeros((2, 1), np.float32)
        tr.train_step(x, y)
        snap = {k: np.asarray(v).copy() for k, v in tr.params.items()}
        count = opt._step_count
        xnan = x.copy()
        xnan[0, 0] = np.nan
        loss = tr.train_step(xnan, y)
        if not np.isnan(float(np.asarray(loss._data))):
            return [_finding("trainer_nonfinite", "error",
                             "poisoned batch did not produce a NaN loss — "
                             "the scenario itself is broken")]
        # ISSUE 11 deferred guard: the verdict is fetched at the next
        # step/stats boundary — force it so the skip is booked
        tr.guard_sync()
        drift = [k for k, v in tr.params.items()
                 if np.asarray(tr.params[k]).tobytes() != snap[k].tobytes()]
        if drift:
            return [_finding("trainer_nonfinite", "error",
                             "non-finite step leaked into parameters: "
                             f"{drift}")]
        if opt._step_count != count:
            return [_finding("trainer_nonfinite", "error",
                             "skipped step advanced the optimizer step "
                             "count")]
    finally:
        paddle.set_flags({"check_nan_inf": False})
    return [_ok("trainer_nonfinite",
                "NaN step skipped; parameters bit-identical")]


def _check_numerics_anomaly():
    """Chaos-injected drift: a trainer/batch=scale:1e4 failpoint blows
    one step's gradients up — finite, so the PR 4 guard stays silent,
    but the telescope's grad-spike detector must fire and NAME the
    layer. A scale:nan step afterwards trips the guard; the per-layer
    nonfinite detector must fire alongside it. Proves detection comes
    BEFORE the step is ruined."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.testing import failpoints as fp

    name = "numerics_anomaly"
    old = {k: paddle.get_flags(["FLAGS_" + k])["FLAGS_" + k]
           for k in ("numerics", "numerics_interval", "check_nan_inf")}
    paddle.set_flags({"numerics": True, "numerics_interval": 1,
                      "check_nan_inf": True})
    try:
        paddle.seed(0)
        model = paddle.nn.Linear(8, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=model.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        tr = SpmdTrainer(model, opt, loss_fn=paddle.nn.MSELoss(),
                         mesh=mesh)
        rng = np.random.RandomState(0)
        x = rng.randn(4, 8).astype(np.float32)
        y = rng.randn(4, 4).astype(np.float32)
        for _ in range(4):          # baseline: the EMA learns "normal"
            tr.train_step(x, y)
        if tr._numerics.anomalies:
            return [_finding(name, "error",
                             "detector cried wolf during baseline "
                             f"training: {list(tr._numerics.anomalies)}")]
        skipped = tr.stats()["breakdown"]["nonfinite_skipped_total"]
        with fp.scoped("trainer/batch=scale:10000"):
            tr.train_step(x, y)     # finite spike: detector territory
        spikes = [a for a in tr._numerics.anomalies
                  if a["kind"] == "grad_spike"]
        if not spikes:
            return [_finding(name, "error",
                             "injected gradient spike did not fire the "
                             "grad_spike detector")]
        if not spikes[0].get("layer"):
            return [_finding(name, "error",
                             "grad_spike anomaly does not name a layer")]
        after_spike = tr.stats()["breakdown"]["nonfinite_skipped_total"]
        if after_spike != skipped:
            return [_finding(name, "error",
                             "the finite spike tripped the non-finite "
                             "guard — the detector did not get there "
                             "first")]
        with fp.scoped("trainer/batch=scale:nan"):
            tr.train_step(x, y)     # poisoned step: guard territory
        if tr.stats()["breakdown"]["nonfinite_skipped_total"] \
                != skipped + 1:
            return [_finding(name, "error",
                             "scale:nan step did not trip the "
                             "FLAGS_check_nan_inf guard")]
        nonf = [a for a in tr._numerics.anomalies
                if a["kind"] == "nonfinite" and a.get("layer")]
        if not nonf:
            return [_finding(name, "error",
                             "poisoned step fired no per-layer "
                             "nonfinite anomaly — the guard knows the "
                             "step died but not WHERE")]
    finally:
        paddle.set_flags(old)
    return [_ok(name,
                f"grad_spike named layer {spikes[0]['layer']!r} before "
                "the non-finite guard tripped; the nan step then fired "
                f"nonfinite on {sorted({a['layer'] for a in nonf})}")]


def _check_quantized_nonfinite():
    """Chaos-injected poison under the quantized reduce: a scale:nan
    batch must trip the PR 4 guard THROUGH the int8 wire format (the NaN
    rides the fp32 block scales — the int8 payload never decides), and
    the where-select must restore params AND the error-feedback residuals
    bit-exactly, so no quantization poison leaks into the next step."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.testing import failpoints as fp

    name = "quantized_nonfinite"
    old = {k: paddle.get_flags(["FLAGS_" + k])["FLAGS_" + k]
           for k in ("quantized_allreduce", "quantized_allreduce_min_size",
                     "check_nan_inf")}
    paddle.set_flags({"quantized_allreduce": True,
                      "quantized_allreduce_min_size": 1,
                      "check_nan_inf": True})
    try:
        paddle.seed(0)
        model = paddle.nn.Linear(8, 4)
        opt = paddle.optimizer.AdamW(learning_rate=0.05,
                                     parameters=model.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        tr = SpmdTrainer(model, opt, loss_fn=paddle.nn.MSELoss(),
                         mesh=mesh)
        if not tr._quantized or not tr._qar_eligible:
            return [_finding(name, "error",
                             "scenario broken: the trainer did not arm "
                             "the quantized reduce")]
        rng = np.random.RandomState(0)
        x = rng.randn(4, 8).astype(np.float32)
        y = rng.randn(4, 4).astype(np.float32)
        for _ in range(2):
            tr.train_step(x, y)
        snap_p = {k: np.asarray(v).copy() for k, v in tr.params.items()}
        snap_r = {k: np.asarray(v).copy()
                  for k, v in tr.opt_state["__qar_residual__"].items()}
        if not any(np.any(v != 0) for v in snap_r.values()):
            return [_finding(name, "error",
                             "scenario broken: error-feedback residuals "
                             "never became non-zero during baseline "
                             "training")]
        skipped = tr.stats()["breakdown"]["nonfinite_skipped_total"]
        with fp.scoped("trainer/batch=scale:nan"):
            loss = tr.train_step(x, y)
        if not np.isnan(float(np.asarray(loss._data))):
            return [_finding(name, "error",
                             "poisoned batch did not produce a NaN loss "
                             "through the quantized reduce — the int8 "
                             "path swallowed the poison")]
        if tr.stats()["breakdown"]["nonfinite_skipped_total"] \
                != skipped + 1:
            return [_finding(name, "error",
                             "scale:nan step did not trip the "
                             "FLAGS_check_nan_inf guard under the "
                             "quantized path")]
        drift = [k for k in snap_p
                 if np.asarray(tr.params[k]).tobytes()
                 != snap_p[k].tobytes()]
        if drift:
            return [_finding(name, "error",
                             "non-finite quantized step leaked into "
                             f"parameters: {drift}")]
        poisoned = [k for k in snap_r
                    if np.asarray(
                        tr.opt_state["__qar_residual__"][k]).tobytes()
                    != snap_r[k].tobytes()]
        if poisoned:
            return [_finding(name, "error",
                             "error-feedback residuals were not "
                             "where-selected back on the skipped step — "
                             f"poison carried forward in: {poisoned}")]
        after = tr.train_step(x, y)
        if not np.isfinite(float(np.asarray(after._data))):
            return [_finding(name, "error",
                             "the step AFTER the skip is non-finite — "
                             "residual state carried poison")]
    finally:
        paddle.set_flags(old)
    return [_ok(name,
                "NaN step skipped through the int8 reduce; params and "
                "EF residuals bit-identical; next step trained clean")]


def _check_async_nonfinite():
    """Chaos-injected poison under FLAGS_async_dispatch: a scale:nan
    batch's verdict is only FETCHED up to FLAGS_async_window steps
    later — the deferred drain must still book the skip (within the
    window), the device-side where-select must have left params and
    schedule bit-identical, the next step must train clean, and a
    blackbox dump bundle must record how deep the in-flight window
    was."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import flags
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.monitor import blackbox as bb
    from paddle_tpu.testing import failpoints as fp

    name = "async_nonfinite"
    old = {k: paddle.get_flags(["FLAGS_" + k])["FLAGS_" + k]
           for k in ("async_dispatch", "async_window", "check_nan_inf")}
    paddle.set_flags({"async_dispatch": True, "async_window": 4,
                      "check_nan_inf": True})
    tmp_ctx = tempfile.TemporaryDirectory(
        prefix="paddle_tpu_chaos_async_blackbox_")
    old_dir = flags.get_flag("blackbox_dir", "")
    was_enabled = bb.is_enabled()
    bb.enable(install=False)
    flags.set_flags({"blackbox_dir": tmp_ctx.name})
    try:
        paddle.seed(0)
        model = paddle.nn.Linear(8, 4)
        opt = paddle.optimizer.AdamW(learning_rate=0.05,
                                     parameters=model.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        tr = SpmdTrainer(model, opt, loss_fn=paddle.nn.MSELoss(),
                         mesh=mesh)
        rng = np.random.RandomState(0)
        x = rng.randn(4, 8).astype(np.float32)
        y = rng.randn(4, 4).astype(np.float32)
        for _ in range(2):
            tr.train_step(x, y)
        tr.guard_sync()
        snap = {k: np.asarray(v).copy() for k, v in tr.params.items()}
        count = opt._step_count
        skipped = tr._nonfinite_total
        with fp.scoped("trainer/batch=scale:nan"):
            tr.train_step(x, y)
        if tr._nonfinite_total != skipped:
            return [_finding(name, "error",
                             "the verdict was fetched eagerly — the "
                             "async path did not defer it")]
        if len(tr._pending_verdicts) != 1:
            return [_finding(name, "error",
                             "poisoned step's verdict is not in the "
                             "deferred window")]
        dump_path = bb.dump("stall", site="trainer/step",
                            extra={"trigger": "chaos async_nonfinite"})
        tr.guard_sync()   # within the window: the host now learns
        if tr._nonfinite_total != skipped + 1:
            return [_finding(name, "error",
                             "deferred drain did not book the skipped "
                             "step within the window")]
        if opt._step_count != count:
            return [_finding(name, "error",
                             "skipped step left the optimizer schedule "
                             f"moved ({opt._step_count} != {count})")]
        drift = [k for k in snap
                 if np.asarray(tr.params[k]).tobytes()
                 != snap[k].tobytes()]
        if drift:
            return [_finding(name, "error",
                             "non-finite step leaked into parameters "
                             f"under async dispatch: {drift}")]
        if dump_path is None:
            return [_finding(name, "error",
                             "blackbox dump failed to write")]
        bundle = bb.load_bundle(dump_path)
        tables = [t["table"] for t in bundle.get("requests", [])
                  if t.get("kind") == "trainer_async" and "table" in t]
        if not tables:
            return [_finding(name, "error",
                             "dump bundle carries no trainer_async "
                             "in-flight window table")]
        tbl = tables[-1]
        if tbl.get("window") != 4 or tbl.get("pending") != 1:
            return [_finding(name, "error",
                             "bundle's window table does not record the "
                             f"in-flight depth (got {tbl})")]
        after = tr.train_step(x, y)
        tr.guard_sync()
        if not np.isfinite(float(np.asarray(after._data))):
            return [_finding(name, "error",
                             "the step AFTER the deferred skip is "
                             "non-finite")]
    finally:
        paddle.set_flags(old)
        flags.set_flags({"blackbox_dir": old_dir})
        bb.quiesce()
        bb.reset()
        if not was_enabled:
            bb.disable()
        tmp_ctx.cleanup()
    return [_ok(name,
                "nan step's verdict deferred 1-in-window, drain booked "
                "the skip, params/schedule bit-identical, bundle "
                "recorded window depth, next step trained clean")]


def _check_elastic_resume():
    """Chaos-injected preemption: kill a dp8 supervised run mid-step and
    mark the dp8 topology gone — the ElasticSupervisor must resume on
    dp4 (topology-aware restore: [dp, shard] moments re-laid), keep the
    loss trajectory within tolerance of an uninterrupted dp8 twin, and
    leave the recovery attributable (blackbox crash bundle at
    site=elastic/resume, elastic_resume_total{reason=failpoint})."""
    import glob

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import flags, monitor
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.monitor import blackbox as bb
    from paddle_tpu.testing import failpoints as fp

    name = "elastic_resume"
    old = {k: flags.get_flag(k)
           for k in ("elastic", "shard_weight_update", "blackbox_dir")}
    tmp_ctx = tempfile.TemporaryDirectory(prefix="paddle_tpu_chaos_elastic_")
    was_enabled = bb.is_enabled()
    bb.enable(install=False)
    paddle.set_flags({"elastic": True, "shard_weight_update": True,
                      "blackbox_dir": os.path.join(tmp_ctx.name, "bb")})
    try:
        class MLP(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.l1 = paddle.nn.Linear(64, 64)
                self.l2 = paddle.nn.Linear(64, 1)

            def forward(self, x):
                return self.l2(paddle.nn.functional.relu(self.l1(x)))

        def build(mesh):
            paddle.seed(0)
            m = MLP()
            opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                         parameters=m.parameters())
            return SpmdTrainer(
                m, opt, loss_fn=lambda p, y: ((p - y) ** 2).mean(),
                mesh=mesh)

        rng = np.random.RandomState(0)
        data = [(rng.randn(8, 64).astype(np.float32),
                 rng.randn(8, 1).astype(np.float32)) for _ in range(6)]

        # the uninterrupted dp8 twin
        twin = build(build_mesh((8,), ("dp",), devices=jax.devices()[:8]))
        twin_losses = [float(np.asarray(twin.train_step(x, y)._data))
                       for x, y in data]

        from paddle_tpu.distributed.elastic import ElasticSupervisor
        from paddle_tpu.incubate.checkpoint.auto_checkpoint import \
            CheckpointSaver

        alive = {"dp8": True}

        def dp8():
            return build_mesh((8,), ("dp",), devices=jax.devices()[:8]) \
                if alive["dp8"] else None

        def dp4():
            return build_mesh((4,), ("dp",), devices=jax.devices()[:4])

        class KillAt(list):
            def __init__(self, items, at):
                super().__init__(items)
                self.at, self.fired = at, False

            def __getitem__(self, i):
                if i == self.at and not self.fired:
                    self.fired = True
                    alive["dp8"] = False
                    fp.arm("trainer/step", "error:1")
                return super().__getitem__(i)

        sup = ElasticSupervisor(
            build, CheckpointSaver(os.path.join(tmp_ctx.name, "ckpt")),
            [dp8, dp4], checkpoint_interval=1)
        losses = sup.run(KillAt(data, 3))

        if not sup.recoveries:
            return [_finding(name, "error",
                             "the killed step produced no recovery")]
        rec = sup.recoveries[0]
        if rec["reason"] != "failpoint":
            return [_finding(name, "error",
                             f"recovery reason {rec['reason']!r}, "
                             "expected 'failpoint' (the injected kill)")]
        if int(sup.trainer.mesh.shape["dp"]) != 4:
            return [_finding(name, "error",
                             "supervisor did not resume on the shrunken "
                             "dp4 mesh")]
        drift = max(abs(a - b) for a, b in zip(losses, twin_losses))
        if not np.allclose(losses, twin_losses, rtol=1e-4, atol=5e-4):
            return [_finding(
                name, "error",
                f"resumed dp4 loss trajectory diverged from the "
                f"uninterrupted dp8 twin (max |diff|={drift:.3e}, "
                "band rtol=1e-4 atol=5e-4)")]
        # attribution: the crash bundle names the recovery site
        bundles = sorted(glob.glob(os.path.join(
            tmp_ctx.name, "bb", "blackbox-*.json")))
        if not bundles:
            return [_finding(name, "error",
                             "recovery wrote no blackbox crash bundle")]
        bundle = bb.load_bundle(bundles[0])
        if bundle.get("site") != "elastic/resume" \
                or bundle.get("reason") != "crash":
            return [_finding(
                name, "error",
                f"bundle names reason={bundle.get('reason')!r} "
                f"site={bundle.get('site')!r}, expected a crash bundle "
                "at elastic/resume")]
        # ...and the lazy counter carries the reason
        snap = monitor.snapshot()
        moved = [s for m in snap["metrics"]
                 if m["name"] == "elastic_resume_total"
                 for s in m["series"]
                 if s["labels"].get("reason") == "failpoint"
                 and s["value"] > 0]
        if not moved:
            return [_finding(name, "error",
                             "elastic_resume_total{reason=failpoint} "
                             "did not move")]
    finally:
        fp.reset()
        paddle.set_flags(old)
        bb.quiesce()
        bb.reset()
        if not was_enabled:
            bb.disable()
        tmp_ctx.cleanup()
    return [_ok(name,
                f"dp8 kill at step {rec['step'] - 1} resumed on dp4 "
                f"(reason={rec['reason']}, max loss drift "
                f"{drift:.1e}); bundle at site=elastic/resume + "
                "elastic_resume_total attribute the recovery")]


def _check_goodput_attribution():
    """Chaos-injected preemption under the goodput ledger: the dp8 kill +
    dp4 resume of elastic_resume re-run with FLAGS_goodput armed. The
    finalized run's ledger row must book NONZERO resume_backoff +
    ckpt_restore + reshard seconds, its buckets must sum to the run's
    wall time within 10% (exclusive attribution), its goodput must land
    BELOW an uninterrupted twin's, the twin must book >= 95% of its
    post-warmup wall as step+compile, and the recovery's crash bundle
    must carry the goodput provider naming the bucket active at kill
    time."""
    import glob

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import flags, monitor
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.monitor import blackbox as bb
    from paddle_tpu.monitor import goodput, perfledger
    from paddle_tpu.testing import failpoints as fp

    name = "goodput_attribution"
    old = {k: flags.get_flag(k)
           for k in ("goodput", "elastic", "shard_weight_update",
                     "blackbox_dir", "perf_ledger", "perf_ledger_path",
                     "perf_ledger_warmup", "perf_ledger_interval")}
    tmp_ctx = tempfile.TemporaryDirectory(prefix="paddle_tpu_chaos_goodput_")
    ledger_path = os.path.join(tmp_ctx.name, "perf.jsonl")
    was_enabled = bb.is_enabled()
    bb.enable(install=False)
    paddle.set_flags({"goodput": True, "elastic": True,
                      "shard_weight_update": True,
                      "blackbox_dir": os.path.join(tmp_ctx.name, "bb"),
                      "perf_ledger": True,
                      "perf_ledger_path": ledger_path,
                      "perf_ledger_warmup": 1, "perf_ledger_interval": 1})
    perfledger.reset_ledger()
    goodput.reset()
    try:
        class MLP(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.l1 = paddle.nn.Linear(64, 64)
                self.l2 = paddle.nn.Linear(64, 1)

            def forward(self, x):
                return self.l2(paddle.nn.functional.relu(self.l1(x)))

        def build(mesh):
            paddle.seed(0)
            m = MLP()
            opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                         parameters=m.parameters())
            return SpmdTrainer(
                m, opt, loss_fn=lambda p, y: ((p - y) ** 2).mean(),
                mesh=mesh)

        rng = np.random.RandomState(0)
        data = [(rng.randn(8, 64).astype(np.float32),
                 rng.randn(8, 1).astype(np.float32)) for _ in range(6)]

        # uninterrupted dp8 twin, post-warmup: one step outside its run
        # absorbs trainer build + first compile, the accounted window is
        # pure steady-state stepping
        twin = build(build_mesh((8,), ("dp",), devices=jax.devices()[:8]))
        twin.train_step(*data[0])
        goodput.start_run("chaos/goodput-twin")
        for x, y in data[1:]:
            twin.train_step(x, y)
        twin_row = goodput.end_run()
        productive = (twin_row["buckets"]["step"]
                      + twin_row["buckets"]["compile"])
        if productive < 0.95 * twin_row["wall_s"]:
            return [_finding(
                name, "error",
                f"uninterrupted twin booked only {productive:.3f}s of "
                f"{twin_row['wall_s']:.3f}s post-warmup wall as "
                "step+compile (< 95%)")]

        from paddle_tpu.distributed.elastic import ElasticSupervisor
        from paddle_tpu.incubate.checkpoint.auto_checkpoint import \
            CheckpointSaver

        alive = {"dp8": True}

        def dp8():
            return build_mesh((8,), ("dp",), devices=jax.devices()[:8]) \
                if alive["dp8"] else None

        def dp4():
            return build_mesh((4,), ("dp",), devices=jax.devices()[:4])

        class KillAt(list):
            def __init__(self, items, at):
                super().__init__(items)
                self.at, self.fired = at, False

            def __getitem__(self, i):
                if i == self.at and not self.fired:
                    self.fired = True
                    alive["dp8"] = False
                    fp.arm("trainer/step", "error:1")
                return super().__getitem__(i)

        goodput.start_run("chaos/goodput")
        sup = ElasticSupervisor(
            build, CheckpointSaver(os.path.join(tmp_ctx.name, "ckpt")),
            [dp8, dp4], checkpoint_interval=1)
        sup.run(KillAt(data, 3))
        row = goodput.end_run()
        if not sup.recoveries:
            return [_finding(name, "error",
                             "the killed step produced no recovery")]
        if int(sup.trainer.mesh.shape["dp"]) != 4:
            return [_finding(name, "error",
                             "supervisor did not resume on the shrunken "
                             "dp4 mesh")]
        # the recovery legs must be attributed, not lumped into step/other
        zero = [b for b in ("resume_backoff", "ckpt_restore", "reshard")
                if not row["buckets"].get(b, 0.0) > 0.0]
        if zero:
            return [_finding(
                name, "error",
                f"killed+resumed run booked no seconds in {zero} — "
                f"buckets: { {k: round(v, 4) for k, v in row['buckets'].items()} }")]
        booked = sum(row["buckets"].values())
        if abs(booked - row["wall_s"]) > 0.1 * row["wall_s"]:
            return [_finding(
                name, "error",
                f"buckets sum to {booked:.3f}s but the run walled "
                f"{row['wall_s']:.3f}s — outside the 10% band")]
        if not row["goodput"] < twin_row["goodput"]:
            return [_finding(
                name, "error",
                f"interrupted run's goodput {row['goodput']:.3f} is not "
                f"below the uninterrupted twin's "
                f"{twin_row['goodput']:.3f}")]
        # the ledger row landed at site=run/goodput with the breakdown
        rows = perfledger.load_rows(ledger_path)
        grows = [r for r in rows if r.get("site") == "run/goodput"
                 and r.get("sig") == "chaos/goodput"]
        if not grows:
            return [_finding(name, "error",
                             "finalized run appended no run/goodput "
                             "perf-ledger row")]
        # the crash bundle's goodput provider names the kill-time bucket
        bundles = sorted(glob.glob(os.path.join(
            tmp_ctx.name, "bb", "blackbox-*.json")))
        if not bundles:
            return [_finding(name, "error",
                             "recovery wrote no blackbox crash bundle")]
        bundle = bb.load_bundle(bundles[0])
        tables = [p for p in bundle.get("requests", [])
                  if p.get("kind") == "goodput"]
        if not tables:
            return [_finding(name, "error",
                             "crash bundle carries no goodput provider "
                             "table")]
        gp = tables[0].get("table", {})
        at_kill = gp.get("active_bucket") or gp.get("last_bucket")
        if at_kill != "step":
            return [_finding(
                name, "error",
                f"crash bundle's goodput table names {at_kill!r} at kill "
                "time, expected 'step' (the failpoint fired mid-step)")]
        if not gp.get("buckets", {}).get("step", 0.0) > 0.0:
            return [_finding(name, "error",
                             "crash bundle's goodput breakdown books no "
                             "step seconds before the kill")]
    finally:
        fp.reset()
        paddle.set_flags(old)
        perfledger.reset_ledger()
        goodput.reset()
        bb.quiesce()
        bb.reset()
        if not was_enabled:
            bb.disable()
        tmp_ctx.cleanup()
    return [_ok(name,
                f"killed dp8 run booked its recovery "
                f"(resume_backoff={row['buckets']['resume_backoff']:.3f}s,"
                f" ckpt_restore={row['buckets']['ckpt_restore']:.3f}s, "
                f"reshard={row['buckets']['reshard']:.3f}s; buckets sum "
                f"within 10% of {row['wall_s']:.3f}s wall); goodput "
                f"{row['goodput']:.3f} < twin {twin_row['goodput']:.3f}, "
                "crash bundle names bucket 'step' at kill time")]


def _check_stage_replace():
    """Chaos-injected stage death: kill one stage of a FLAGS_mpmd
    2-stage pipeline via stage/run, rebind JUST that stage onto a
    replacement mesh (replace_stage), and keep training — siblings'
    compiled programs must be untouched (object identity) and the
    rebind must disk-hit the warmed AOT cache; losses stay at parity
    with an uninterrupted twin."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import flags, monitor
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.pipeline import PipelineTrainer
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.testing import failpoints as fp

    name = "stage_replace"
    old = {k: flags.get_flag(k)
           for k in ("mpmd", "elastic", "jit_cache_dir")}
    tmp_ctx = tempfile.TemporaryDirectory(prefix="paddle_tpu_chaos_stage_")
    paddle.set_flags({"mpmd": True, "elastic": True,
                      "jit_cache_dir": os.path.join(tmp_ctx.name, "aot")})
    try:
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=2, max_seq_len=16, dropout=0.0)
        rng = np.random.RandomState(0)
        batches = [[rng.randint(0, 64, (2, 16)).astype(np.int32)
                    for _ in range(2)] for _ in range(4)]

        def build():
            paddle.seed(0)
            model = GPTForCausalLM(cfg)
            pre, stages, post = model.pipeline_split(2)
            opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                         parameters=model.parameters())
            mesh = build_mesh((2,), ("pp",), devices=jax.devices()[:2])
            return PipelineTrainer(pre, stages, post, opt, mesh=mesh,
                                   n_micro=2, schedule_mode="1F1B")

        twin = build()
        twin_losses = [float(np.asarray(twin.train_step(*b)._data))
                       for b in batches]

        tr = build()
        losses = [float(np.asarray(tr.train_step(*b)._data))
                  for b in batches[:2]]
        runner = tr._mpmd_runner
        sibling_jits = {n: p._jit for n, p in runner.programs.items()
                        if n not in ("fwd0", "bwd0")}
        fp.arm("stage/run", "error:1")
        try:
            tr.train_step(*batches[2])
            return [_finding(name, "error",
                             "armed stage/run failpoint did not fire")]
        except fp.FailpointError:
            pass
        # stage 0's slice died: rebind fwd0/bwd0 onto a replacement
        # device (same shape/kind -> same mesh fingerprint -> disk hit)
        replacement = build_mesh((1,), ("stage",),
                                 devices=[jax.devices()[2]])
        runner.replace_stage(0, replacement)
        losses += [float(np.asarray(tr.train_step(*b)._data))
                   for b in batches[2:]]

        drift = max(abs(a - b) for a, b in zip(losses, twin_losses))
        if not np.allclose(losses, twin_losses, rtol=1e-5, atol=1e-5):
            return [_finding(
                name, "error",
                f"post-replace loss trajectory diverged from the "
                f"uninterrupted twin (max |diff|={drift:.3e})")]
        recompiled = [n for n, j in sibling_jits.items()
                      if runner.programs[n]._jit is not j]
        if recompiled:
            return [_finding(name, "error",
                             "replace_stage touched sibling stage "
                             f"programs: {recompiled}")]
        if runner.stage_meshes[0] is not replacement:
            return [_finding(name, "error",
                             "replace_stage did not record the "
                             "replacement mesh")]
        snap = monitor.snapshot()
        disk_hits = sum(
            s["value"] for m in snap["metrics"]
            if m["name"] == "compile_cache_total" for s in m["series"]
            if s["labels"].get("site") == "stage"
            and s["labels"].get("source") == "disk")
        if not disk_hits:
            return [_finding(name, "error",
                             "rebound stage did not disk-hit the warmed "
                             "AOT cache (compile_cache_total"
                             "{site=stage,source=disk} empty)")]
        moved = [s for m in snap["metrics"]
                 if m["name"] == "elastic_resume_total"
                 for s in m["series"]
                 if s["labels"].get("reason") == "stage_replace"
                 and s["value"] > 0]
        if not moved:
            return [_finding(name, "error",
                             "elastic_resume_total{reason=stage_replace} "
                             "did not move")]
    finally:
        fp.reset()
        paddle.set_flags(old)
        tmp_ctx.cleanup()
    return [_ok(name,
                f"killed stage 0 rebound onto a replacement mesh "
                f"(siblings untouched, {int(disk_hits)} stage disk "
                f"hit(s)); loss parity with the twin (max drift "
                f"{drift:.1e})")]


def build_report(only=None):
    """Run the fault schedule; `only` restricts to a subset of PASSES
    (the model is only built when a serving check is selected)."""
    selected = set(only) if only else set(PASSES)
    unknown = selected - set(PASSES)
    if unknown:
        raise ValueError(f"unknown chaos pass(es) {sorted(unknown)}; "
                         f"known: {PASSES}")
    findings = []
    checks = [
        ("ckpt_atomic", _check_ckpt_atomic),
        ("ckpt_fallback", _check_ckpt_fallback),
        ("trainer_nonfinite", _check_trainer_nonfinite),
        ("numerics_anomaly", _check_numerics_anomaly),
        ("quantized_nonfinite", _check_quantized_nonfinite),
        ("async_nonfinite", _check_async_nonfinite),
        ("elastic_resume", _check_elastic_resume),
        ("stage_replace", _check_stage_replace),
        ("goodput_attribution", _check_goodput_attribution),
    ]
    if selected & {"serving_deadline", "serving_slot_error",
                   "serving_shed", "router_failover", "stall_dump",
                   "stage_backpressure", "adapter_evict_under_load",
                   "page_pool_full"}:
        m = _tiny_model()
        checks += [
            ("serving_deadline", lambda: _check_serving_deadline(m)),
            ("serving_slot_error", lambda: _check_serving_slot_error(m)),
            ("serving_shed", lambda: _check_serving_shed(m)),
            ("router_failover", lambda: _check_router_failover(m)),
            ("stall_dump", lambda: _check_stall_dump(m)),
            ("stage_backpressure",
             lambda: _check_stage_backpressure(m)),
            ("adapter_evict_under_load",
             lambda: _check_adapter_evict_under_load(m)),
            ("page_pool_full", lambda: _check_page_pool_full(m)),
        ]
    for name, fn in checks:
        if name not in selected:
            continue
        try:
            findings.extend(fn())
        except Exception as e:   # a crashed check IS a failed recovery path
            findings.append(_finding(
                name, "error",
                f"check crashed: {type(e).__name__}: {e}"))
    counts = {"error": 0, "warning": 0, "info": 0}
    for f in findings:
        counts[f["severity"]] = counts.get(f["severity"], 0) + 1
    return {
        "tool": "chaos_check",
        "passes": PASSES,
        "targets": {"chaos": {"name": "chaos", "counts": counts,
                              "findings": findings}},
        "totals": dict(counts),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine-readable report")
    ap.add_argument("--only", action="append", choices=PASSES,
                    help="run only this check (repeatable)")
    args = ap.parse_args(argv)

    from paddle_tpu.testing import failpoints as fp

    fp.reset()   # a canned schedule must start from a clean slate
    try:
        report = build_report(only=args.only)
    finally:
        fp.reset()
    if args.as_json:
        print(json.dumps(report, indent=1))
    else:
        for f in report["targets"]["chaos"]["findings"]:
            print(f"  [{f['severity']}] {f['pass']}: {f['message']}")
        t = report["totals"]
        print(f"total: {t['error']} error(s), {t['info']} recovery "
              f"path(s) verified")
    return 1 if report["totals"]["error"] else 0


if __name__ == "__main__":
    sys.exit(main())
