"""Per-op micro-benchmark harness + regression gate.

Reference parity: paddle/fluid/operators/benchmark/op_tester.cc (config-driven
op timing: OpTesterConfig{op, inputs, attrs, repeat}) and the CI gate
tools/test_op_benchmark.sh + tools/check_op_benchmark_result.py (relative
before/after comparison, no absolute thresholds).

TPU-native design: each case times the JITTED op (compile excluded by a
warmup; block_until_ready for honest walls). `run` writes a JSON profile;
`compare` diffs two profiles and fails on >tolerance regressions — wire it to
CI exactly like the reference's shell gate.

Usage:
  python tools/op_benchmark.py run  [--out ops_bench.json] [--repeat 50]
  python tools/op_benchmark.py compare base.json new.json [--tol 0.05]
"""
import argparse
import json
import os
import sys
import time

import numpy as np

# runnable from any cwd (the flash case imports paddle_tpu)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _cases():
    """The benchmark suite: (name, build() -> (fn, args)). Shapes mirror the
    reference configs' production-ish sizes, scaled to run on any backend."""
    import jax
    import jax.numpy as jnp

    r = np.random.RandomState(0)

    def f32(*s):
        return jnp.asarray(r.rand(*s).astype(np.float32))

    def i32(lo, hi, *s):
        return jnp.asarray(r.randint(lo, hi, s).astype(np.int32))

    return [
        ("matmul_1024", lambda: (lambda a, b: a @ b,
                                 (f32(1024, 1024), f32(1024, 1024)))),
        ("matmul_bf16_2048", lambda: (
            lambda a, b: (a @ b),
            (f32(2048, 2048).astype(jnp.bfloat16),
             f32(2048, 2048).astype(jnp.bfloat16)))),
        ("softmax_8kx512", lambda: (lambda x: jax.nn.softmax(x, axis=-1),
                                    (f32(8192, 512),))),
        ("layernorm_8kx768", lambda: (
            lambda x, g, b: g * (x - x.mean(-1, keepdims=True))
            / jnp.sqrt(x.var(-1, keepdims=True) + 1e-5) + b,
            (f32(8192, 768), f32(768), f32(768)))),
        ("gelu_16m", lambda: (jax.nn.gelu, (f32(4096, 4096),))),
        ("reduce_sum_16m", lambda: (lambda x: x.sum(), (f32(4096, 4096),))),
        ("transpose_4kx4k", lambda: (lambda x: x.T.copy() if hasattr(x, 'copy')
                                     else jnp.transpose(x),
                                     (f32(4096, 4096),))),
        ("embedding_1m", lambda: (
            lambda tbl, ids: jnp.take(tbl, ids, axis=0),
            (f32(65536, 128), i32(0, 65536, 8192)))),
        ("conv2d_128", lambda: (
            lambda x, w: jax.lax.conv_general_dilated(
                x, w, (1, 1), "SAME",
                dimension_numbers=("NCHW", "OIHW", "NCHW")),
            (f32(8, 64, 128, 128), f32(64, 64, 3, 3)))),
        ("attention_1k", lambda: (
            lambda q, k, v: jax.nn.softmax(
                (q @ k.transpose(0, 1, 3, 2)) / 8.0, axis=-1) @ v,
            (f32(4, 12, 1024, 64), f32(4, 12, 1024, 64),
             f32(4, 12, 1024, 64)))),
        ("cumsum_16m", lambda: (lambda x: jnp.cumsum(x, axis=-1),
                                (f32(4096, 4096),))),
        ("topk_1m", lambda: (lambda x: jax.lax.top_k(x, 128),
                             (f32(256, 16384),))),
        ("sgd_update_8m", lambda: (
            lambda p, g: p - 0.01 * g, (f32(2048, 4096), f32(2048, 4096)))),
        ("cross_entropy_lse_16kx50k", lambda: (
            # the r2 hard-label CE path: logsumexp+gather, no one_hot
            lambda lg, ids: (jax.nn.logsumexp(lg.astype(jnp.float32), axis=-1)
                             - jnp.take_along_axis(
                                 lg.astype(jnp.float32), ids[:, None],
                                 axis=-1)[:, 0]).mean(),
            (f32(2048, 8192).astype(jnp.bfloat16), i32(0, 8192, 2048)))),
        ("sequence_pool_sum_4kx128", lambda: (
            lambda x, ln: (x * (jnp.arange(x.shape[1])[None, :, None]
                                < ln[:, None, None])).sum(axis=1),
            (f32(4096, 128, 64), i32(1, 128, 4096)))),
        ("segment_sum_1m", lambda: (
            lambda d, ids: jax.ops.segment_sum(d, ids, num_segments=1024),
            (f32(1 << 20, 8), i32(0, 1024, 1 << 20)))),
        ("iou_matrix_2k", lambda: (
            lambda b: (lambda lt, rb: (jnp.maximum(rb - lt, 0).prod(-1)))(
                jnp.maximum(b[:, None, :2], b[None, :, :2]),
                jnp.minimum(b[:, None, 2:], b[None, :, 2:])),
            (f32(2048, 4),))),
        ("adam_update_8m", lambda: (
            lambda p, g, m, v: (
                p - 0.01 * (0.9 * m + 0.1 * g)
                / (jnp.sqrt(0.999 * v + 0.001 * g * g) + 1e-8)),
            (f32(2048, 4096), f32(2048, 4096), f32(2048, 4096),
             f32(2048, 4096)))),
        ("flash_attention", lambda: _flash_case(f32)),
        ("int8_kv_dequant_einsum_1k", lambda: (
            # the int8 KV-cache read path: dequant fused into the einsum
            lambda q, vals, scales: jnp.einsum(
                "bhtd,bhTd->bhtT", q,
                (vals.astype(jnp.float32) * scales)),
            (f32(1, 12, 1, 64), jnp.asarray(
                r.randint(-127, 128, (1, 12, 1024, 64)).astype(np.int8)),
             f32(1, 12, 1024, 1)))),
    ]


def _flash_case(f32):
    """The serving/training hot kernel: compiled at 2k seq on TPU;
    interpret mode off-TPU shrinks to 256 to stay tractable."""
    from paddle_tpu.ops.flash_attention import _on_tpu, flash_attention

    on_tpu = _on_tpu()
    s = 2048 if on_tpu else 256
    args = (f32(1, s, 4, 64), f32(1, s, 4, 64), f32(1, s, 4, 64))
    return (lambda q, k, v: flash_attention(q, k, v, causal=True,
                                            interpret=not on_tpu)), args


def run(out_path, repeat):
    import jax

    results = {}
    for name, build in _cases():
        fn, args = build()
        jitted = jax.jit(fn)
        jax.block_until_ready(jitted(*args))  # compile + warm
        t0 = time.perf_counter()
        for _ in range(repeat):
            out = jitted(*args)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / repeat
        results[name] = {"mean_us": round(dt * 1e6, 2)}
        print(f"{name:24s} {dt * 1e6:10.2f} us", file=sys.stderr)
    profile = {
        "platform": jax.devices()[0].platform,
        "repeat": repeat,
        "ops": results,
    }
    with open(out_path, "w") as f:
        json.dump(profile, f, indent=1)
    print(json.dumps({"wrote": out_path, "n_ops": len(results)}))
    return profile


def compare(base_path, new_path, tol):
    """check_op_benchmark_result.py parity: relative regression gate."""
    with open(base_path) as f:
        base = json.load(f)
    with open(new_path) as f:
        new = json.load(f)
    if base.get("platform") != new.get("platform"):
        print(f"WARNING: platform mismatch ({base.get('platform')} vs "
              f"{new.get('platform')}); timings not comparable",
              file=sys.stderr)
    regressions = []
    for name, b in base["ops"].items():
        n = new["ops"].get(name)
        if n is None:
            print(f"MISSING  {name} (removed from suite?)", file=sys.stderr)
            continue
        ratio = n["mean_us"] / max(b["mean_us"], 1e-9)
        flag = " "
        if ratio > 1 + tol:
            flag = "R"  # regression
            regressions.append((name, ratio))
        elif ratio < 1 - tol:
            flag = "+"  # improvement
        print(f"{flag} {name:24s} {b['mean_us']:10.2f} -> {n['mean_us']:10.2f}"
              f" us  ({ratio - 1:+.1%})", file=sys.stderr)
    # ops only in the NEW profile are un-gated until the baseline is
    # regenerated — surface them so added hot-path kernels aren't silently
    # excluded from the regression gate
    new_only = sorted(set(new["ops"]) - set(base["ops"]))
    for name in new_only:
        print(f"N {name:24s} {'':>10s}    {new['ops'][name]['mean_us']:10.2f}"
              f" us  (NEW — no baseline; regenerate to gate)",
              file=sys.stderr)
    if regressions:
        print(json.dumps({"status": "FAIL", "regressions": [
            {"op": n, "slowdown": round(r, 3)} for n, r in regressions]}))
        return 1
    print(json.dumps({"status": "OK", "n_compared": len(base["ops"]),
                      "n_new_ungated": len(new_only)}))
    return 0


def main():
    ap = argparse.ArgumentParser(__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_run = sub.add_parser("run")
    p_run.add_argument("--out", default="ops_bench.json")
    p_run.add_argument("--repeat", type=int, default=50)
    p_run.add_argument("--cpu", action="store_true",
                       help="force the CPU backend")
    p_cmp = sub.add_parser("compare")
    p_cmp.add_argument("base")
    p_cmp.add_argument("new")
    p_cmp.add_argument("--tol", type=float, default=0.05)
    args = ap.parse_args()
    if args.cmd == "run":
        if args.cpu:
            import jax

            jax.config.update("jax_platforms", "cpu")
        run(args.out, args.repeat)
        return 0
    return compare(args.base, args.new, args.tol)


if __name__ == "__main__":
    sys.exit(main())
