"""Graph lint CLI: run the analysis pass battery + source linter.

    python tools/graph_lint.py --model gpt            # one model, human
    python tools/graph_lint.py --model bert --json    # machine-readable
    python tools/graph_lint.py --all --json           # models + serving
                                                      # decode + source lint
                                                      # + contract auditor
                                                      # + sharding flow
    python tools/graph_lint.py --source               # source lint only
    python tools/graph_lint.py --contracts            # ISSUE 12 contract
                                                      # auditor passes
    python tools/graph_lint.py --sharding             # ISSUE 13: bundled
                                                      # distributed programs
                                                      # under their meshes
    python tools/graph_lint.py --sharding-target dp8_quantized   # one
    python tools/graph_lint.py --plan                 # ISSUE 16: auto-
                                                      # parallelism plan
                                                      # search over the
                                                      # bundled models
    python tools/graph_lint.py --tier1                # fast subset (models
                                                      # + source + contracts
                                                      # — no tracing-heavy
                                                      # sharding/plan/serving
                                                      # batteries)
    python tools/graph_lint.py --all --timings        # per-target wall secs
    python tools/graph_lint.py --list                 # registered passes
    python tools/graph_lint.py --list-rules           # rules + allow markers

Report format (shared with tools/op_coverage.py --json so the tier-1 gate
reads both through one schema):

    {"tool": ..., "passes": [...],
     "targets": {name: {"name", "counts": {error,warning,info},
                        "findings": [{"pass","severity","message","where"}]}},
     "totals": {error, warning, info}}

Exit code: 1 when any error-severity finding exists, else 0 — wired into
tier-1 by tests/test_graph_lint_gate.py, which also pins the warning
baseline (tests/lint_baseline.json).

Reference analog: `--print_pass_history`-style pass introspection over the
REGISTER_PASS registry (SURVEY §1 layer 3/4), as a standing CI gate.
"""
import argparse
import json
import os
import sys

# the sharding-flow targets trace dp8/pp4 programs: give the CPU backend
# its virtual devices BEFORE jax initializes (the tests/conftest.py mesh).
# APPEND to any user-set XLA_FLAGS — a plain setdefault would silently
# collapse the battery to 1 device (vacuously-clean reports) whenever the
# user exports XLA_FLAGS for unrelated tuning
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8"
                               ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_report(models=(), serving=False, source=False, training=False,
                 contracts=False, sharding=False, sharding_targets=None,
                 plan=False, plan_models=None):
    """Run the requested targets; returns the shared-format report dict.
    A ``timings`` key maps each target (group key ``contract``/
    ``sharding`` for the multi-target batteries, which run as one call)
    to its wall seconds — ``--timings`` prints it and the plan gate
    (tests/test_plan_gate.py) budgets the plan battery against it."""
    import time

    from paddle_tpu.analysis import registered_passes
    from paddle_tpu.analysis.registry import AnalysisReport
    from paddle_tpu.analysis.source_lint import RULES, lint_path
    from paddle_tpu.analysis.targets import (analyze_model,
                                             analyze_serving_decode)

    targets, timings = {}, {}

    def timed(key, fn):
        t0 = time.perf_counter()
        out = fn()
        timings[key] = round(time.perf_counter() - t0, 3)
        return out

    for name in models:
        targets[name] = timed(
            name, lambda n=name: analyze_model(n, training=training))
    if serving:
        targets["serving"] = timed("serving", analyze_serving_decode)
    if source:
        rep = AnalysisReport(name="source_lint")
        rep.extend(timed("source_lint", lint_path))
        targets["source_lint"] = rep.sort()
    if contracts:
        from paddle_tpu.analysis import contract_reports

        for name, rep in timed("contract", contract_reports).items():
            targets[f"contract_{name}"] = rep
    if sharding or sharding_targets:
        from paddle_tpu.analysis import sharding_reports

        for name, rep in timed(
                "sharding",
                lambda: sharding_reports(targets=sharding_targets)).items():
            targets[f"sharding_{name}"] = rep
    if plan:
        from paddle_tpu.analysis import plan_search

        for name in (plan_models or ("gpt", "bert")):
            targets[f"plan_{name}"] = timed(
                f"plan_{name}",
                lambda n=name: plan_search.search(n).to_report())

    totals = {"error": 0, "warning": 0, "info": 0}
    for rep in targets.values():
        for sev, n in rep.counts().items():
            totals[sev] = totals.get(sev, 0) + n
    return {
        "tool": "graph_lint",
        "passes": registered_passes(),
        "rules": sorted(RULES),
        "targets": {n: r.to_dict() for n, r in targets.items()},
        "totals": totals,
        "timings": timings,
    }


def main(argv=None):
    from paddle_tpu.analysis.targets import MODEL_TARGETS

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", choices=MODEL_TARGETS, action="append",
                    default=[], help="analyze one bundled model's forward")
    ap.add_argument("--all", action="store_true",
                    help="all models + serving decode + source lint")
    ap.add_argument("--serving", action="store_true",
                    help="analyze the serving engine decode step")
    ap.add_argument("--source", action="store_true",
                    help="run the AST source linter over paddle_tpu/")
    ap.add_argument("--contracts", action="store_true",
                    help="run the contract auditor (flag / lazy-import / "
                         "observability / thread / handoff / pallas "
                         "passes; same battery as tools/contract_audit.py)")
    ap.add_argument("--sharding", action="store_true",
                    help="run the sharding-flow battery over the bundled "
                         "distributed programs under their real meshes "
                         "(gpt/bert/ernie train + serving + dp8 "
                         "quantized + pipeline + disagg)")
    ap.add_argument("--sharding-target", action="append", default=[],
                    dest="sharding_targets", metavar="NAME",
                    help="one sharding target (repeatable; implies "
                         "--sharding for the picked subset)")
    ap.add_argument("--plan", action="store_true",
                    help="run the auto-parallelism plan search over the "
                         "bundled models (analysis/plan_search.py; full "
                         "surface: tools/plan_search.py)")
    ap.add_argument("--tier1", action="store_true",
                    help="the fast subset (models + source + contracts) "
                         "— skips the tracing-heavy serving/sharding/"
                         "plan batteries")
    ap.add_argument("--timings", action="store_true",
                    help="print per-target wall seconds after the report")
    ap.add_argument("--train", action="store_true",
                    help="trace models in training mode (dropout on)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine-readable report")
    ap.add_argument("--list", action="store_true",
                    help="list registered passes and lint rules")
    ap.add_argument("--list-rules", action="store_true", dest="list_rules",
                    help="list every source/contract rule with severity "
                         "and allow-marker spellings")
    args = ap.parse_args(argv)

    if args.list:
        from paddle_tpu.analysis import registered_passes
        from paddle_tpu.analysis.source_lint import RULES

        print("jaxpr passes:")
        for p in registered_passes():
            print(f"  {p}")
        print("source-lint rules:")
        for r, sev in sorted(RULES.items()):
            print(f"  {r} [{sev}]")
        return 0

    if args.list_rules:
        from paddle_tpu.analysis import rule_table

        print(rule_table())
        return 0

    models = list(args.model)
    serving, source, contracts = args.serving, args.source, args.contracts
    sharding, plan = args.sharding, args.plan
    sharding_targets = list(args.sharding_targets) or None
    if args.all:
        models = list(MODEL_TARGETS)
        serving = source = contracts = sharding = plan = True
    if args.tier1:
        models = list(MODEL_TARGETS)
        source = contracts = True
    if not models and not serving and not source and not contracts \
            and not sharding and not sharding_targets and not plan:
        ap.error("pick a target: --model NAME, --serving, --source, "
                 "--contracts, --sharding, --plan, --tier1 or --all")

    report = build_report(models=models, serving=serving, source=source,
                          training=args.train, contracts=contracts,
                          sharding=sharding,
                          sharding_targets=sharding_targets, plan=plan)
    if args.as_json:
        print(json.dumps(report, indent=1))
    else:
        for name, rep in report["targets"].items():
            c = rep["counts"]
            print(f"{name}: {c['error']} error(s), {c['warning']} "
                  f"warning(s), {c['info']} info")
            for f in rep["findings"]:
                loc = f" @ {f['where']}" if f["where"] else ""
                print(f"  [{f['severity']}] {f['pass']}: "
                      f"{f['message']}{loc}")
        t = report["totals"]
        print(f"total: {t['error']} error(s), {t['warning']} warning(s), "
              f"{t['info']} info across {len(report['targets'])} target(s); "
              f"{len(report['passes'])} passes registered")
    if args.timings and not args.as_json:
        print("timings:")
        for key, secs in sorted(report["timings"].items(),
                                key=lambda kv: -kv[1]):
            print(f"  {key:<24} {secs:7.3f}s")
    return 1 if report["totals"]["error"] else 0


if __name__ == "__main__":
    sys.exit(main())
