"""API-coverage report: reference public python surface vs paddle_tpu.

Walks the reference package's `__init__.py` import-as graph (textually — the
reference can't be imported here) to collect the public `paddle.*` names, then
checks each against the installed paddle_tpu package. Prints per-namespace
counts and the missing names; exits 0 always (informational).

Usage: python tools/api_coverage.py [--ref /root/reference/python/paddle]
                                    [--list-missing]
"""
import argparse
import ast
import importlib
import os
import sys


NAMESPACES = [
    ("paddle", "__init__.py"),
    ("paddle.nn", "nn/__init__.py"),
    ("paddle.nn.functional", "nn/functional/__init__.py"),
    ("paddle.tensor", "tensor/__init__.py"),
    ("paddle.optimizer", "optimizer/__init__.py"),
    ("paddle.metric", "metric/__init__.py"),
    ("paddle.vision.ops", "vision/ops.py"),
    ("paddle.vision.transforms", "vision/transforms/__init__.py"),
    ("paddle.vision.models", "vision/models/__init__.py"),
    ("paddle.text", "text/__init__.py"),
    ("paddle.io", "io/__init__.py"),
    ("paddle.jit", "jit/__init__.py"),
    ("paddle.static", "static/__init__.py"),
    ("paddle.distributed", "distributed/__init__.py"),
    ("paddle.distributed.fleet", "distributed/fleet/__init__.py"),
    ("paddle.amp", "amp/__init__.py"),
    ("paddle.utils", "utils/__init__.py"),
    ("paddle.incubate", "incubate/__init__.py"),
]


def public_names(path):
    """Names a module's __init__ exposes: __all__ if present, else top-level
    imports/defs/assigns (textual AST walk, no import)."""
    try:
        tree = ast.parse(open(path, encoding="utf-8").read())
    except (OSError, SyntaxError):
        return set()
    names = set()
    all_lists = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    try:
                        all_lists.append([ast.literal_eval(e) for e in
                                          node.value.elts])
                    except Exception:
                        pass
        if isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Name) and node.target.id == "__all__":
                all_lists.append(None)  # computed __all__ -> fall back
    if all_lists and all(a is not None for a in all_lists):
        for a in all_lists:
            names.update(a)
        return {n for n in names if isinstance(n, str)}
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                n = alias.asname or alias.name.split(".")[0]
                if not n.startswith("_"):
                    names.add(n)
        elif isinstance(node, (ast.FunctionDef, ast.ClassDef)):
            if not node.name.startswith("_"):
                names.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and not t.id.startswith("_"):
                    names.add(t.id)
    return names


# names that are build-system/compat internals in the reference, not API
NOISE = {"core", "core_avx", "core_noavx", "libpaddle", "monkey_patch_varbase",
         "monkey_patch_math_varbase", "proto", "cpt", "six", "np", "numpy",
         "sys", "os", "re", "warnings", "functools", "collections", "copy",
         "inspect", "math", "json", "pickle", "paddle", "fluid", "logging",
         "itertools", "contextlib", "threading", "time", "types", "typing",
         "struct", "subprocess", "tempfile", "textwrap", "traceback",
         # parser artifacts, not APIs: "*" comes from computed __all__
         # (e.g. `__all__ = mod.__all__ + [...]`), print_function from a
         # `from __future__ import` leaking into the reference's list
         "*", "print_function"}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ref", default="/root/reference/python/paddle")
    ap.add_argument("--list-missing", action="store_true")
    args = ap.parse_args()

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu

    total_ref = total_have = 0
    rows = []
    all_missing = {}
    for ns, rel in NAMESPACES:
        ref_path = os.path.join(args.ref, rel)
        ref_names = {n for n in public_names(ref_path) if n not in NOISE}
        if not ref_names:
            continue
        mod_name = ns.replace("paddle", "paddle_tpu", 1)
        try:
            mod = importlib.import_module(mod_name)
        except ImportError:
            mod = None
        have = {n for n in ref_names if mod is not None and hasattr(mod, n)}
        missing = sorted(ref_names - have)
        rows.append((ns, len(have), len(ref_names)))
        all_missing[ns] = missing
        total_ref += len(ref_names)
        total_have += len(have)

    if not rows or total_ref == 0:
        print(f"no reference namespaces found under {args.ref} — nothing to "
              "compare (informational; exiting 0)")
        return
    width = max(len(r[0]) for r in rows)
    for ns, h, r in rows:
        pct = 100.0 * h / r
        print(f"{ns:<{width}}  {h:>4}/{r:<4}  {pct:5.1f}%")
    print("-" * (width + 20))
    print(f"{'TOTAL':<{width}}  {total_have:>4}/{total_ref:<4}  "
          f"{100.0 * total_have / total_ref:5.1f}%")
    if args.list_missing:
        for ns, missing in all_missing.items():
            if missing:
                print(f"\n[{ns}] missing ({len(missing)}):")
                print("  " + ", ".join(missing))


if __name__ == "__main__":
    main()
