"""Metrics dump CLI: run an instrumented workload, print the monitor snapshot.

    python tools/metrics_dump.py --model gpt              # one gpt train step
    python tools/metrics_dump.py --serving                # serving decode loop
    python tools/metrics_dump.py --router                 # multi-engine tier
    python tools/metrics_dump.py --blackbox               # flight recorder
    python tools/metrics_dump.py --federated              # 2-client FedAvg
    python tools/metrics_dump.py --numerics               # numerics telescope
    python tools/metrics_dump.py --quantized              # int8 grad reduce
    python tools/metrics_dump.py --mpmd                   # stage-graph pipeline
    python tools/metrics_dump.py --ledger                 # perf ledger + sentinel
    python tools/metrics_dump.py --paged                  # paged KV + multi-LoRA
    python tools/metrics_dump.py --goodput                # goodput ledger + lineage
    python tools/metrics_dump.py --model bert --prometheus
    python tools/metrics_dump.py --all --json             # machine-readable
    python tools/metrics_dump.py --serving --trace        # + span summary

Each target resets the default registry, runs the workload at CPU-shrunk
shapes (the analysis/targets.py convention — 2 steps, so BOTH the
compile-cache miss and the hit counters move), then exports the registry.

Default output is the snapshot JSON (one schema for all exporters);
--prometheus prints the text exposition of the SAME snapshot. --json
emits the tools/graph_lint.py report schema ({"tool", "passes",
"targets": {name: {"name", "counts", "findings"}}, "totals"}, plus a
per-target "snapshot") so CI reads all three audit tools through one
loader; a target whose snapshot is MISSING a required metric family
(compile-cache + step-latency for train, TTFT + inter-token for serving)
reports an error-severity finding and the exit code is 1 — the
acceptance-criterion check in executable form.
"""
import argparse
import json
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MODEL_TARGETS = ("gpt", "bert", "ernie")

# metric families that MUST be non-empty in a target's snapshot
_REQUIRED = {
    "train": ("compile_cache_total", "compile_total", "step_latency_ms"),
    "serving": ("serving_ttft_ms", "serving_inter_token_ms",
                "serving_requests_submitted_total", "serving_tokens_total"),
    "router": ("router_requests_total", "kv_handoff_bytes_total",
               "kv_handoff_total", "serving_requests_submitted_total"),
    # the flight-recorder families (monitor/blackbox.py): a dump and its
    # ring events must land in the registry when the recorder runs
    "blackbox": ("blackbox_dump_total", "blackbox_ring_events_total",
                 "serving_requests_submitted_total"),
    # the federated tier (docs/FEDERATED.md): round + per-client-examples
    # families, and the aggregation bytes through the collective chokepoint
    "federated": ("federated_round_total", "federated_client_examples",
                  "collective_bytes_total"),
    # the numerics telescope (docs/OBSERVABILITY.md): per-layer health
    # gauges plus at least one detector fire from the loop's deliberate
    # lr blow-up step
    "numerics": ("numerics_grad_norm", "numerics_update_ratio",
                 "numerics_anomaly_total"),
    # the quantized all-reduce (docs/DISTRIBUTED.md): wire + saved bytes
    # through the collective chokepoint, and the lazily-published
    # error-feedback norm gauge; a label check below additionally pins
    # the op=quantized_all_reduce series
    "quantized": ("collective_bytes_total", "collective_bytes_saved_total",
                  "quantize_error_norm", "compile_cache_total"),
    # async double-buffered dispatch (docs/PERF.md): the deferred-guard
    # drain families plus the TPP kernel-call counter from the armed
    # tiny-GPT loop (the loop arms both ISSUE 11 flags)
    "async": ("async_verdict_fetch_total", "async_window_depth",
              "tpp_kernel_calls_total", "compile_cache_total"),
    # the MPMD stage runtime (docs/DISTRIBUTED.md "Stage programs"): edge
    # wire bytes, the quantized-edge savings through the collective
    # chokepoint, and per-stage compiles through the shared AOT cache;
    # run_mpmd_loop additionally asserts the stage_step spans of one
    # traced step share their stage_graph root's trace_id
    "mpmd": ("kv_handoff_bytes_total", "collective_bytes_saved_total",
             "collective_bytes_total", "compile_cache_total"),
    # the perf ledger (docs/OBSERVABILITY.md "Perf ledger"): rows landing
    # per armed trainer step plus one sentinel fire from the loop's
    # deliberate failpoint-delayed step
    "ledger": ("perf_ledger_rows_total", "perf_regression_total",
               "step_latency_ms", "compile_cache_total"),
    # the paged-KV serving tier (docs/SERVING.md "Paged KV & multi-LoRA"):
    # block churn by temperature, at least one copy-on-write boundary
    # clone, and the adapter-registry lifecycle counters from the armed
    # 2-adapter loop
    "paged": ("kv_page_blocks_total", "kv_page_cow_total",
              "serving_adapter_total", "serving_requests_submitted_total",
              "serving_ttft_ms"),
    # elastic training (docs/DISTRIBUTED.md "Elastic training"): a
    # supervised dp2 run killed mid-step resumes on dp1 — the recovery
    # counter, the topology-aware restore's reshard actions, and the
    # recovery-cost ledger row at site elastic/resume
    "elastic": ("elastic_resume_total", "checkpoint_reshard_total",
                "perf_ledger_rows_total", "step_latency_ms"),
    # the goodput ledger (docs/OBSERVABILITY.md "Goodput ledger"): a
    # supervised run with FLAGS_goodput armed, killed once mid-step, must
    # book the recovery into the exclusive buckets and finalize the
    # fraction gauge; the serving leg's engine publishes its
    # weight-version lineage gauge
    "goodput": ("goodput_seconds_total", "goodput_fraction",
                "serving_weight_version", "perf_ledger_rows_total"),
}

#: (family, label, value) series that must exist in a target's snapshot,
#: beyond the family-level check — compressed ops share their families
#: with the uncompressed world, so the op label is the contract
_REQUIRED_SERIES = {
    "quantized": (("collective_bytes_total", "op", "quantized_all_reduce"),
                  ("collective_bytes_saved_total", "op",
                   "quantized_all_reduce")),
    "async": (("tpp_kernel_calls_total", "op", "ln_matmul"),
              ("tpp_kernel_calls_total", "op", "fused_mlp")),
    "mpmd": (("collective_bytes_saved_total", "op", "stage_edge"),
             ("collective_bytes_total", "op", "stage_edge")),
    "ledger": (("perf_ledger_rows_total", "site", "trainer"),
               ("perf_regression_total", "metric", "step_ms")),
    "paged": (("kv_page_blocks_total", "state", "hot"),
              ("kv_page_blocks_total", "state", "cold"),
              ("serving_adapter_total", "event", "load"),
              ("serving_adapter_total", "event", "hit"),
              ("serving_adapter_total", "event", "evict")),
    "elastic": (("elastic_resume_total", "reason", "failpoint"),
                ("checkpoint_reshard_total", "action", "moment_reshard"),
                ("perf_ledger_rows_total", "site", "elastic/resume")),
    # per-bucket attribution: the killed+resumed run must book productive
    # steps, checkpoint traffic both ways, the recovery leg, and the
    # dp2->dp1 cross-topology restore — plus the per-run ledger row
    "goodput": (("goodput_seconds_total", "bucket", "step"),
                ("goodput_seconds_total", "bucket", "ckpt_save"),
                ("goodput_seconds_total", "bucket", "ckpt_restore"),
                ("goodput_seconds_total", "bucket", "resume_backoff"),
                ("goodput_seconds_total", "bucket", "reshard"),
                ("perf_ledger_rows_total", "site", "run/goodput")),
}

_DIMS = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
             dropout=0.0)


def run_train_step(name, steps=2):
    """One jitted train step (+1 cache-hit step) for a bundled model."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.models import (BertConfig, BertForPretraining,
                                   BertPretrainLoss, ErnieConfig,
                                   ErnieForPretraining, ErniePretrainLoss,
                                   GPTConfig, GPTForCausalLM,
                                   GPTPretrainLoss)

    paddle.seed(0)
    rng = np.random.RandomState(0)
    b, s = 2, 16
    if name == "gpt":
        model = GPTForCausalLM(GPTConfig(max_seq_len=64, **_DIMS))
        loss = GPTPretrainLoss()
        batch = (rng.randint(0, 256, (b, s)).astype(np.int32),
                 rng.randint(0, 256, (b, s)).astype(np.int32))
    elif name == "bert":
        model = BertForPretraining(BertConfig(max_position=64,
                                              intermediate_size=256, **_DIMS))
        loss = BertPretrainLoss()
        batch = (rng.randint(0, 256, (b, s)).astype(np.int32),
                 np.zeros((b, s), np.int32),
                 rng.randint(0, 256, (b, s)).astype(np.int32))
    elif name == "ernie":
        model = ErnieForPretraining(ErnieConfig(max_position=64,
                                                intermediate_size=256,
                                                **_DIMS))
        loss = ErniePretrainLoss()
        batch = (rng.randint(0, 256, (b, s)).astype(np.int32),
                 np.zeros((b, s), np.int32),
                 rng.randint(0, 256, (b, s)).astype(np.int32))
    else:
        raise ValueError(f"unknown model {name!r}; choose from "
                         f"{MODEL_TARGETS}")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
    trainer = SpmdTrainer(model, opt, loss_fn=loss, mesh=mesh)
    tensors = [paddle.to_tensor(a) for a in batch]
    for _ in range(steps):
        out = trainer.train_step(*tensors)
    return float(np.asarray(out._data))


def run_serving_loop(new_tokens=6):
    """A small ServingEngine decode loop: two mixed-length prompts drained
    through the continuous-batching step()."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig(max_seq_len=64, **_DIMS))
    model.eval()
    eng = ServingEngine(model, max_batch=2)
    rng = np.random.RandomState(0)
    eng.submit(rng.randint(0, 256, (8,)).astype(np.int32),
               max_new_tokens=new_tokens)
    eng.submit(rng.randint(0, 256, (12,)).astype(np.int32),
               max_new_tokens=new_tokens - 2)
    eng.run_until_complete()
    return eng.stats()


def run_router_loop(new_tokens=4):
    """The multi-engine serving tier: a 2-engine Router fanning three
    session-keyed prompts, then a DisaggregatedPool (1 prefill worker ->
    1 decode engine) handing off two prefilled KV rows — exercises
    router_requests_total AND the kv_handoff familes in one target."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving.disagg import DisaggregatedPool
    from paddle_tpu.serving.router import Router

    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig(max_seq_len=64, **_DIMS))
    model.eval()
    rng = np.random.RandomState(0)
    router = Router({"a": ServingEngine(model, max_batch=2),
                     "b": ServingEngine(model, max_batch=2)})
    for i in range(3):
        router.submit(rng.randint(0, 256, (6 + i,)).astype(np.int32),
                      max_new_tokens=new_tokens, session_id=i)
    router.run_until_complete()
    pool = DisaggregatedPool(model, prefill_workers=1, decode_engines=1,
                             max_batch=2)
    for n in (5, 9):
        pool.submit(rng.randint(0, 256, (n,)).astype(np.int32),
                    max_new_tokens=new_tokens)
    pool.run_until_complete()
    return {"router": router.stats()["router"],
            "pool": pool.stats()["pool"]}


def run_federated_loop(rounds=1):
    """The federated tier target: a 2-client LoRA FedAvg round — moves
    federated_round_total, federated_client_examples, and the
    collective_bytes_total{op=federated_sum} aggregation bytes in one
    pass."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.federated import FederatedAverager, partition_clients
    from paddle_tpu.incubate.lora import apply_lora

    paddle.seed(0)
    rng = np.random.RandomState(0)
    net = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 4))
    apply_lora(net, r=2, alpha=4)
    X = rng.randn(16, 8).astype(np.float32)
    Y = rng.randn(16, 4).astype(np.float32)
    fed = FederatedAverager(
        net, nn.MSELoss(), partition_clients((X, Y), 2, batch_size=8),
        local_steps=2, local_lr=0.1, seed=0)
    stats = fed.run(rounds)
    return {"rounds": stats, "loss": fed.evaluate()}


def run_numerics_loop(steps=5):
    """The numerics-telescope target: a tiny-GPT train loop with
    FLAGS_numerics armed (interval=1), plus one deliberately blown-up
    learning-rate step so the update-ratio drift detector fires — moves
    numerics_grad_norm/numerics_update_ratio gauges AND
    numerics_anomaly_total in one pass."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import flags
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainLoss

    old = {k: flags.get_flag(k) for k in ("numerics", "numerics_interval")}
    paddle.set_flags({"numerics": True, "numerics_interval": 1})
    try:
        paddle.seed(0)
        rng = np.random.RandomState(0)
        model = GPTForCausalLM(GPTConfig(max_seq_len=64, **_DIMS))
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        trainer = SpmdTrainer(model, opt, loss_fn=GPTPretrainLoss(),
                              mesh=mesh)
        batch = [paddle.to_tensor(
            rng.randint(0, 256, (2, 16)).astype(np.int32))
            for _ in range(2)]
        for _ in range(steps - 1):
            trainer.train_step(*batch)
        opt.set_lr(50.0)   # one rewriting step: the detector's job
        trainer.train_step(*batch)
        return trainer.stats()["numerics"]
    finally:
        paddle.set_flags(old)


def run_quantized_loop(steps=2):
    """The quantized all-reduce target: a tiny-GPT dp train step with
    FLAGS_quantized_allreduce armed (consumed at trainer construction) —
    moves collective_bytes_total{op=quantized_all_reduce} and
    collective_bytes_saved_total through the chokepoint's trace-time
    metering, and stats() publishes the quantize_error_norm gauge."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import flags
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainLoss

    old = {k: flags.get_flag(k)
           for k in ("quantized_allreduce", "quantized_allreduce_min_size")}
    paddle.set_flags({"quantized_allreduce": True,
                      "quantized_allreduce_min_size": 1024})
    try:
        paddle.seed(0)
        rng = np.random.RandomState(0)
        model = GPTForCausalLM(GPTConfig(max_seq_len=64, **_DIMS))
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        trainer = SpmdTrainer(model, opt, loss_fn=GPTPretrainLoss(),
                              mesh=mesh)
        batch = [paddle.to_tensor(
            rng.randint(0, 256, (2, 16)).astype(np.int32))
            for _ in range(2)]
        for _ in range(steps):
            trainer.train_step(*batch)
        st = trainer.stats()
        return {"quantize_error_norm": st["quantize_error_norm"],
                "steps": st["steps"]}
    finally:
        paddle.set_flags(old)


def run_async_loop(steps=5):
    """The async-dispatch target: a tiny-GPT train loop with
    FLAGS_async_dispatch + FLAGS_check_nan_inf + FLAGS_tpp_kernels all
    armed (window 2, so >= 2 deferred drains happen inside the loop) —
    moves async_verdict_fetch_total / async_window_depth and the
    tpp_kernel_calls_total{op=...} series in one pass."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import flags
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainLoss

    old = {k: flags.get_flag(k)
           for k in ("async_dispatch", "async_window", "check_nan_inf",
                     "tpp_kernels")}
    paddle.set_flags({"async_dispatch": True, "async_window": 2,
                      "check_nan_inf": True, "tpp_kernels": True})
    try:
        paddle.seed(0)
        rng = np.random.RandomState(0)
        model = GPTForCausalLM(GPTConfig(max_seq_len=64, **_DIMS))
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        trainer = SpmdTrainer(model, opt, loss_fn=GPTPretrainLoss(),
                              mesh=mesh)
        batch = [paddle.to_tensor(
            rng.randint(0, 256, (2, 16)).astype(np.int32))
            for _ in range(2)]
        for _ in range(steps):
            trainer.train_step(*batch)
        trainer.guard_sync()
        st = trainer.stats()
        return {"verdict_fetches": st["breakdown"]["verdict_fetches"],
                "window_max_depth": st["breakdown"]["window_max_depth"],
                "steps": st["steps"]}
    finally:
        paddle.set_flags(old)


def run_mpmd_loop(steps=2):
    """The MPMD stage-runtime target: a 2-stage pipeline trainer rebased
    onto StageGraph (FLAGS_mpmd armed at construction) with a compress=8
    activation edge — moves kv_handoff_bytes_total (edge wire bytes),
    collective_bytes_{total,saved_total}{op=stage_edge} (quantized-edge
    wire vs logical accounting) and compile_cache_total{site=stage} in
    one pass. The last step runs under trace and the loop asserts every
    stage_step span shares its stage_graph root's trace_id — the span
    contract in executable form, independent of --trace."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import flags, trace
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.pipeline import PipelineTrainer
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    old = {"mpmd": flags.get_flag("mpmd")}
    paddle.set_flags({"mpmd": True})
    was_tracing = trace.is_enabled()
    try:
        paddle.seed(0)
        rng = np.random.RandomState(0)
        model = GPTForCausalLM(GPTConfig(max_seq_len=64, **_DIMS))
        pre, stages, post = model.pipeline_split(2)
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
        mesh = build_mesh((2,), ("pp",), devices=jax.devices()[:2])
        trainer = PipelineTrainer(pre, stages, post, opt, mesh=mesh,
                                  n_micro=2, schedule_mode="1F1B",
                                  compress=8)
        batch = [rng.randint(0, 256, (2, 16)).astype(np.int32)
                 for _ in range(2)]
        for _ in range(steps):
            trainer.train_step(*batch)
        if not was_tracing:
            trace.enable()
        trainer.train_step(*batch)
        roots = [s for s in trace.spans() if s.name == "stage_graph"]
        ticks = [s for s in trace.spans() if s.name == "stage_step"]
        if not roots or not ticks:
            raise RuntimeError("traced MPMD step recorded no stage_graph/"
                               "stage_step spans")
        root_ids = {s.trace_id for s in roots}
        stray = {s.trace_id for s in ticks} - root_ids
        if stray:
            raise RuntimeError("stage_step spans carry trace_ids with no "
                               f"stage_graph root: {sorted(stray)}")
        es = trainer._mpmd_runner.stats()
        return {"steps": steps + 1,
                "stage_step_spans": len(ticks),
                "trace_ids": len(root_ids),
                "edges": es["edges"]}
    finally:
        if not was_tracing:
            trace.disable()
        paddle.set_flags(old)


def run_ledger_loop(steps=6, delay_ms=400):
    """The perf-ledger target: a tiny-GPT train loop with
    FLAGS_perf_ledger armed (interval=1, warmup=3, rows into a
    throwaway JSONL) — every warm step appends a row
    (perf_ledger_rows_total{site=trainer}) and builds the sentinel's
    EMA baseline; one final step runs under a planted
    ``trainer/batch=delay:MS`` failpoint (inside the step-timer window,
    before the exec window) so its step_ms lands sigma-out-of-band and
    perf_regression_total{site=trainer,metric=step_ms} fires — the
    regression sentinel's whole loop in one target."""
    import os as _os
    import tempfile

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import flags
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainLoss
    from paddle_tpu.monitor import perfledger
    from paddle_tpu.testing import failpoints

    old = {k: flags.get_flag(k)
           for k in ("perf_ledger", "perf_ledger_path",
                     "perf_ledger_warmup", "perf_ledger_interval")}
    fd, path = tempfile.mkstemp(suffix=".jsonl",
                                prefix="paddle_tpu_ledger_")
    _os.close(fd)
    paddle.set_flags({"perf_ledger": True, "perf_ledger_path": path,
                      "perf_ledger_warmup": 3, "perf_ledger_interval": 1})
    perfledger.reset_ledger()   # re-read the knobs just set
    try:
        paddle.seed(0)
        rng = np.random.RandomState(0)
        model = GPTForCausalLM(GPTConfig(max_seq_len=64, **_DIMS))
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        trainer = SpmdTrainer(model, opt, loss_fn=GPTPretrainLoss(),
                              mesh=mesh)
        batch = [paddle.to_tensor(
            rng.randint(0, 256, (2, 16)).astype(np.int32))
            for _ in range(2)]
        for _ in range(steps):
            trainer.train_step(*batch)
        with failpoints.scoped(f"trainer/batch=delay:{delay_ms}"):
            trainer.train_step(*batch)   # the sentinel's job
        led = perfledger.get_ledger()
        rows = perfledger.load_rows(path)
        if not rows:
            raise RuntimeError("armed trainer appended no ledger rows")
        if not any(r["metric"] == "step_ms" for r in led.regressions):
            raise RuntimeError(
                "planted trainer/batch delay fired no step_ms regression")
        return {"rows": len(rows), "rows_written": led.rows_written,
                "regressions": list(led.regressions),
                "sites": sorted({r.get("site") for r in rows})}
    finally:
        paddle.set_flags(old)
        perfledger.reset_ledger()
        try:
            _os.unlink(path)
        except OSError:
            pass


def run_elastic_loop(steps=5, kill_at=2):
    """The elastic-training target (docs/DISTRIBUTED.md "Elastic
    training"): an ElasticSupervisor drives a tiny dp2 MLP trainer with
    FLAGS_elastic + FLAGS_shard_weight_update armed, checkpointing every
    step; a ``trainer/step=error:1`` failpoint kills step ``kill_at``
    and marks the dp2 topology gone, so the supervisor resumes — on dp1,
    through the topology-aware restore — moving
    elastic_resume_total{reason=failpoint}, the reshard actions in
    checkpoint_reshard_total{action=...}, and (FLAGS_perf_ledger armed
    too) the recovery-cost row at site ``elastic/resume``."""
    import os as _os
    import shutil
    import tempfile

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import flags
    from paddle_tpu.distributed.elastic import ElasticSupervisor
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.incubate.checkpoint.auto_checkpoint import \
        CheckpointSaver
    from paddle_tpu.monitor import perfledger
    from paddle_tpu.testing import failpoints

    old = {k: flags.get_flag(k)
           for k in ("elastic", "shard_weight_update", "perf_ledger",
                     "perf_ledger_path", "perf_ledger_warmup",
                     "perf_ledger_interval")}
    fd, path = tempfile.mkstemp(suffix=".jsonl",
                                prefix="paddle_tpu_elastic_")
    _os.close(fd)
    ckpt_dir = tempfile.mkdtemp(prefix="paddle_tpu_elastic_ckpt_")
    paddle.set_flags({"elastic": True, "shard_weight_update": True,
                      "perf_ledger": True, "perf_ledger_path": path,
                      "perf_ledger_warmup": 1, "perf_ledger_interval": 1})
    perfledger.reset_ledger()
    try:
        class MLP(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.l1 = paddle.nn.Linear(64, 64)
                self.l2 = paddle.nn.Linear(64, 1)

            def forward(self, x):
                return self.l2(paddle.nn.functional.relu(self.l1(x)))

        def build(mesh):
            paddle.seed(0)
            m = MLP()
            opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                         parameters=m.parameters())
            return SpmdTrainer(
                m, opt, loss_fn=lambda p, y: ((p - y) ** 2).mean(),
                mesh=mesh)

        alive = {"dp2": True}

        def dp2():
            return build_mesh((2,), ("dp",),
                              devices=jax.devices()[:2]) \
                if alive["dp2"] else None

        def dp1():
            return build_mesh((1,), ("dp",), devices=jax.devices()[:1])

        rng = np.random.RandomState(0)
        data = [(rng.randn(8, 64).astype(np.float32),
                 rng.randn(8, 1).astype(np.float32))
                for _ in range(steps)]

        class KillAt(list):
            """Arms the kill from inside the batch lookup, so the
            failpoint fires on exactly the requested step."""

            def __init__(self, items, at):
                super().__init__(items)
                self.at, self.fired = at, False

            def __getitem__(self, i):
                if i == self.at and not self.fired:
                    self.fired = True
                    alive["dp2"] = False
                    failpoints.arm("trainer/step", "error:1")
                return super().__getitem__(i)

        sup = ElasticSupervisor(build, CheckpointSaver(ckpt_dir),
                                [dp2, dp1], checkpoint_interval=1)
        losses = sup.run(KillAt(data, kill_at))
        if not sup.recoveries:
            raise RuntimeError("the killed step produced no recovery")
        if int(sup.trainer.mesh.shape["dp"]) != 1:
            raise RuntimeError("supervisor did not resume on the "
                               "shrunken dp1 mesh")
        rows = perfledger.load_rows(path)
        if not any(r.get("site") == "elastic/resume" for r in rows):
            raise RuntimeError("recovery appended no elastic/resume "
                               "perf-ledger row")
        return {"losses": losses,
                "recoveries": list(sup.recoveries),
                "ledger_sites": sorted({r.get("site") for r in rows})}
    finally:
        failpoints.reset()
        paddle.set_flags(old)
        perfledger.reset_ledger()
        shutil.rmtree(ckpt_dir, ignore_errors=True)
        try:
            _os.unlink(path)
        except OSError:
            pass


def run_goodput_loop(steps=5, kill_at=2, new_tokens=3):
    """The goodput-ledger target (docs/OBSERVABILITY.md "Goodput
    ledger"): the elastic dp2->dp1 kill-and-resume loop re-run with
    FLAGS_goodput armed — every wall-second of the supervised run books
    into an exclusive bucket (productive ``step``, checkpoint traffic,
    the ``resume_backoff`` recovery leg, the cross-topology ``reshard``
    restore), ``end_run()`` finalizes the fraction gauge and appends the
    ``site=run/goodput`` perf-ledger row — then a tiny ServingEngine
    serves one completion across a same-weights ``hot_swap()``, moving
    the ``serving_weight_version`` lineage gauge and the stale-session
    counter."""
    import os as _os
    import shutil
    import tempfile

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import flags
    from paddle_tpu.distributed.elastic import ElasticSupervisor
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.incubate.checkpoint.auto_checkpoint import \
        CheckpointSaver
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.monitor import goodput, perfledger
    from paddle_tpu.testing import failpoints

    old = {k: flags.get_flag(k)
           for k in ("goodput", "elastic", "shard_weight_update",
                     "perf_ledger", "perf_ledger_path",
                     "perf_ledger_warmup", "perf_ledger_interval")}
    fd, path = tempfile.mkstemp(suffix=".jsonl",
                                prefix="paddle_tpu_goodput_")
    _os.close(fd)
    ckpt_dir = tempfile.mkdtemp(prefix="paddle_tpu_goodput_ckpt_")
    paddle.set_flags({"goodput": True, "elastic": True,
                      "shard_weight_update": True,
                      "perf_ledger": True, "perf_ledger_path": path,
                      "perf_ledger_warmup": 1, "perf_ledger_interval": 1})
    perfledger.reset_ledger()
    goodput.reset()
    try:
        class MLP(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.l1 = paddle.nn.Linear(64, 64)
                self.l2 = paddle.nn.Linear(64, 1)

            def forward(self, x):
                return self.l2(paddle.nn.functional.relu(self.l1(x)))

        def build(mesh):
            paddle.seed(0)
            m = MLP()
            opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                         parameters=m.parameters())
            return SpmdTrainer(
                m, opt, loss_fn=lambda p, y: ((p - y) ** 2).mean(),
                mesh=mesh)

        alive = {"dp2": True}

        def dp2():
            return build_mesh((2,), ("dp",),
                              devices=jax.devices()[:2]) \
                if alive["dp2"] else None

        def dp1():
            return build_mesh((1,), ("dp",), devices=jax.devices()[:1])

        rng = np.random.RandomState(0)
        data = [(rng.randn(8, 64).astype(np.float32),
                 rng.randn(8, 1).astype(np.float32))
                for _ in range(steps)]

        class KillAt(list):
            """Arms the kill from inside the batch lookup, so the
            failpoint fires on exactly the requested step."""

            def __init__(self, items, at):
                super().__init__(items)
                self.at, self.fired = at, False

            def __getitem__(self, i):
                if i == self.at and not self.fired:
                    self.fired = True
                    alive["dp2"] = False
                    failpoints.arm("trainer/step", "error:1")
                return super().__getitem__(i)

        goodput.start_run("metrics_dump/goodput")
        sup = ElasticSupervisor(build, CheckpointSaver(ckpt_dir),
                                [dp2, dp1], checkpoint_interval=1)
        sup.run(KillAt(data, kill_at))
        row = goodput.end_run()
        if row is None:
            raise RuntimeError("no goodput run was open at end_run()")
        for b in ("step", "ckpt_save", "ckpt_restore", "resume_backoff",
                  "reshard"):
            if not row["buckets"].get(b, 0.0) > 0.0:
                raise RuntimeError(
                    f"killed+resumed run booked no {b!r} seconds: "
                    f"{row['buckets']}")
        booked = sum(row["buckets"].values())
        if abs(booked - row["wall_s"]) > 0.1 * row["wall_s"]:
            raise RuntimeError(
                f"buckets sum to {booked:.3f}s but the run walled "
                f"{row['wall_s']:.3f}s — exclusive attribution leaked")
        rows = perfledger.load_rows(path)
        if not any(r.get("site") == "run/goodput" for r in rows):
            raise RuntimeError("finalized run appended no run/goodput "
                               "perf-ledger row")

        # serving lineage leg: one completion finishes under the swapped
        # engine's OLD version (stale), the next under the bumped one
        paddle.seed(0)
        model = GPTForCausalLM(GPTConfig(max_seq_len=64, **_DIMS))
        model.eval()
        eng = ServingEngine(model, max_batch=2)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 256, (8,)).astype(np.int32)
        rid0 = eng.submit(ids, max_new_tokens=new_tokens)
        v1 = eng.hot_swap(model)   # same weights: outputs bit-identical
        if v1.counter != 1 or v1.origin != "hot_swap":
            raise RuntimeError(f"hot_swap minted {v1} — expected "
                               "counter 1, origin hot_swap")
        eng.run_until_complete()
        rid1 = eng.submit(ids, max_new_tokens=new_tokens)
        eng.run_until_complete()
        s0 = eng.get_request(rid0).stats()
        s1 = eng.get_request(rid1).stats()
        if s0.get("weight_version", "").split(":")[1:2] != ["0"]:
            raise RuntimeError(f"pre-swap completion carries "
                               f"{s0.get('weight_version')!r}, not v0")
        if s1.get("weight_version", "").split(":")[1:2] != ["1"]:
            raise RuntimeError(f"post-swap completion carries "
                               f"{s1.get('weight_version')!r}, not v1")
        return {"run": row, "ledger_sites":
                sorted({r.get("site") for r in rows}),
                "serving_versions": [s0.get("weight_version"),
                                     s1.get("weight_version")]}
    finally:
        failpoints.reset()
        paddle.set_flags(old)
        perfledger.reset_ledger()
        goodput.reset()
        shutil.rmtree(ckpt_dir, ignore_errors=True)
        try:
            _os.unlink(path)
        except OSError:
            pass


def run_paged_loop(new_tokens=4):
    """The paged-KV target: an armed (FLAGS_paged_kv) 2-adapter engine —
    a registered shared prefix whose length straddles a block boundary
    (copy-on-write fires at admission), adapter-routed sessions (load +
    hit events), idle sweeps past page_cold_steps (blocks demote to int8
    cold pages) and one explicit evict — moves kv_page_blocks_total
    {state=hot|cold}, kv_page_cow_total and serving_adapter_total
    {event=load|hit|evict} in one pass."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import flags
    from paddle_tpu.incubate.lora import apply_lora, export_lora
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    old = {"paged_kv": flags.get_flag("paged_kv")}
    paddle.set_flags({"paged_kv": True})
    try:
        paddle.seed(0)
        rng = np.random.RandomState(0)
        model = GPTForCausalLM(GPTConfig(max_seq_len=64, **_DIMS))
        model.eval()

        def _adapter(seed):
            m2 = GPTForCausalLM(GPTConfig(max_seq_len=64, **_DIMS))
            m2.load_dict(model.state_dict())
            apply_lora(m2, r=4, alpha=8)
            r = np.random.RandomState(seed)
            for n_, p_ in m2.named_parameters():
                if "lora_B" in n_:
                    p_.set_value(paddle.to_tensor(
                        r.normal(0, 0.1, p_.shape).astype(np.float32)))
            return export_lora(m2)

        eng = ServingEngine(model, max_batch=4, max_adapters=2,
                            page_cold_steps=2)
        eng.load_adapter("bot-a", _adapter(1))
        eng.load_adapter("bot-b", _adapter(2))
        # prefix of 20 tokens with 16-token blocks: the boundary block is
        # partial, so every admission clones it (kv_page_cow_total)
        pid = eng.register_prefix(
            rng.randint(0, 256, (20,)).astype(np.int32))
        for i in range(3):
            eng.submit(rng.randint(0, 256, (2 + i,)).astype(np.int32),
                       max_new_tokens=new_tokens, prefix_id=pid)
        for name in ("bot-a", "bot-b"):
            eng.submit(rng.randint(0, 256, (6,)).astype(np.int32),
                       max_new_tokens=new_tokens, adapter=name)
        eng.run_until_complete()
        for _ in range(4):
            eng.step()   # idle sweeps: the prefix blocks age cold
        eng.evict_adapter("bot-b")
        return eng.stats()["paging"]
    finally:
        paddle.set_flags(old)


def run_blackbox_loop(new_tokens=4):
    """The flight-recorder target: a short serving loop with the
    recorder ON, then one on-demand dump bundle into a throwaway dir —
    moves blackbox_ring_events_total (beacon ring feeds) and
    blackbox_dump_total{reason=signal} in one pass."""
    import shutil
    import tempfile

    from paddle_tpu.monitor import blackbox

    was = blackbox.is_enabled()
    blackbox.enable(install=False)
    d = tempfile.mkdtemp(prefix="paddle_tpu_blackbox_dump_")
    try:
        run_serving_loop(new_tokens=new_tokens)
        path = blackbox.dump("signal", site="metrics_dump", dir_=d)
        if path is None:
            raise RuntimeError("blackbox.dump() wrote no bundle")
        bundle = blackbox.load_bundle(path)
        return {"bundle": os.path.basename(path),
                "ring": blackbox.ring_summary(3),
                "providers": [t.get("kind")
                              for t in bundle.get("requests", [])]}
    finally:
        blackbox.quiesce()
        blackbox.reset()
        if not was:
            blackbox.disable()
        shutil.rmtree(d, ignore_errors=True)


def _series_moved(m, s):
    if m["type"] == "histogram":
        return s["count"] > 0
    if m["type"] == "counter":
        return s["value"] != 0
    return True                      # a gauge legitimately reads 0


def _histogram_summaries():
    """p50/p90/p99 digests (registry ``summary()``) of every live
    histogram series — keyed ``family{labels}``; what the human output
    prints under ``# histograms`` and --json carries per target."""
    from paddle_tpu import monitor

    out = {}
    for m in monitor.default_registry().metrics():
        if m.kind != "histogram":
            continue
        for s in m.series():
            if not s.count:
                continue
            lab = ",".join(f"{k}={v}" for k, v in sorted(s.labels.items()))
            out[m.name + ("{" + lab + "}" if lab else "")] = s.summary()
    return out


def _metric_families(snap):
    """Families with at least one live series. A counter/histogram family
    whose every series is zero counts as EMPTY: monitor.reset() keeps
    registered metric objects (zeroed), so an in-process caller that ran
    other workloads first would otherwise see families 'present' that the
    target never touched — the subprocess and in-process verdicts must
    agree."""
    return {m["name"]: m for m in snap["metrics"]
            if any(_series_moved(m, s) for s in m["series"])}


def run_target(name, with_trace=False):
    """Run one target against a freshly-reset registry; returns
    (snapshot, findings, trace_summary) with findings in the graph_lint
    format. with_trace=True runs the workload under FLAGS_trace and
    attaches the compact span summary (count + top-3 totals — the same
    view bench.py's phase heartbeats carry)."""
    from paddle_tpu import monitor, trace

    monitor.reset()
    trace_summary = None
    kind = (name if name in ("serving", "router", "blackbox", "federated",
                             "numerics", "quantized", "async", "mpmd",
                             "ledger", "paged", "elastic", "goodput")
            else "train")
    if with_trace:
        trace.clear()
        trace.enable()
    try:
        if kind == "serving":
            run_serving_loop()
        elif kind == "router":
            run_router_loop()
        elif kind == "blackbox":
            run_blackbox_loop()
        elif kind == "federated":
            run_federated_loop()
        elif kind == "numerics":
            run_numerics_loop()
        elif kind == "quantized":
            run_quantized_loop()
        elif kind == "async":
            run_async_loop()
        elif kind == "mpmd":
            run_mpmd_loop()
        elif kind == "ledger":
            run_ledger_loop()
        elif kind == "paged":
            run_paged_loop()
        elif kind == "elastic":
            run_elastic_loop()
        elif kind == "goodput":
            run_goodput_loop()
        else:
            run_train_step(name)
    finally:
        if with_trace:
            trace_summary = trace.snapshot_summary(3)
            trace.disable()
    snap = monitor.snapshot()
    summaries = _histogram_summaries()
    fams = _metric_families(snap)
    findings = []
    for req in _REQUIRED[kind]:
        if req not in fams:
            findings.append({
                "pass": "metrics-present", "severity": "error",
                "message": f"required metric family {req!r} missing or "
                           f"empty after the {name} run", "where": name})
    for fam_name, lkey, lval in _REQUIRED_SERIES.get(kind, ()):
        series = fams.get(fam_name, {}).get("series", [])
        if not any(s.get("labels", {}).get(lkey) == lval for s in series):
            findings.append({
                "pass": "metrics-present", "severity": "error",
                "message": f"required series {fam_name}{{{lkey}={lval}}} "
                           f"missing after the {name} run", "where": name})
    from paddle_tpu.monitor import flatten

    for key, val in sorted(flatten(snap).items()):
        findings.append({"pass": "metrics", "severity": "info",
                         "message": f"{key} = {val}", "where": name})
    return snap, findings, trace_summary, summaries


def build_report(targets, with_trace=False):
    """The tools/graph_lint.py-schema report over the requested targets."""
    report = {"tool": "metrics_dump", "passes": [], "targets": {},
              "totals": {"error": 0, "warning": 0, "info": 0}}
    for name in targets:
        snap, findings, trace_summary, summaries = run_target(
            name, with_trace=with_trace)
        counts = {"error": 0, "warning": 0, "info": 0}
        for f in findings:
            counts[f["severity"]] += 1
        report["targets"][name] = {"name": name, "counts": counts,
                                   "findings": findings, "snapshot": snap}
        if summaries:
            report["targets"][name]["histograms"] = summaries
        if trace_summary is not None:
            report["targets"][name]["trace"] = trace_summary
        for sev, n in counts.items():
            report["totals"][sev] += n
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", choices=MODEL_TARGETS, action="append",
                    default=[], help="run one bundled model's train step")
    ap.add_argument("--serving", action="store_true",
                    help="run the ServingEngine decode loop")
    ap.add_argument("--router", action="store_true", dest="router",
                    help="run the multi-engine tier (Router fan-out + "
                         "disaggregated prefill/decode handoff); exit 1 "
                         "when the router/kv_handoff metric families are "
                         "missing")
    ap.add_argument("--blackbox", action="store_true", dest="blackbox",
                    help="run the flight-recorder target (serving loop "
                         "with FLAGS_blackbox + one dump bundle); exit 1 "
                         "when the blackbox_* metric families are "
                         "missing")
    ap.add_argument("--federated", action="store_true", dest="federated",
                    help="run the federated tier (2-client LoRA FedAvg "
                         "round); exit 1 when the federated_round_total/"
                         "federated_client_examples metric families are "
                         "missing")
    ap.add_argument("--numerics", action="store_true", dest="numerics",
                    help="run the numerics telescope (tiny-GPT train "
                         "loop with FLAGS_numerics armed + one blown-up "
                         "lr step); exit 1 when the numerics_grad_norm/"
                         "numerics_update_ratio/numerics_anomaly_total "
                         "families are missing")
    ap.add_argument("--quantized", action="store_true", dest="quantized",
                    help="run the quantized all-reduce target (tiny-GPT "
                         "dp step with FLAGS_quantized_allreduce armed); "
                         "exit 1 unless collective_bytes_total"
                         "{op=quantized_all_reduce} and "
                         "collective_bytes_saved_total are present")
    ap.add_argument("--async", action="store_true", dest="async_",
                    help="run the async-dispatch target (tiny-GPT loop "
                         "with FLAGS_async_dispatch + FLAGS_tpp_kernels "
                         "armed); exit 1 unless the "
                         "async_verdict_fetch_total/async_window_depth "
                         "families and tpp_kernel_calls_total{op=...} "
                         "series are present")
    ap.add_argument("--mpmd", action="store_true", dest="mpmd",
                    help="run the MPMD stage-runtime target (2-stage "
                         "pipeline on StageGraph with FLAGS_mpmd armed "
                         "and a compress=8 activation edge); exit 1 "
                         "unless kv_handoff_bytes_total and "
                         "collective_bytes_{total,saved_total}"
                         "{op=stage_edge} are present")
    ap.add_argument("--ledger", action="store_true", dest="ledger",
                    help="run the perf-ledger target (tiny-GPT loop with "
                         "FLAGS_perf_ledger armed + one failpoint-delayed "
                         "step); exit 1 unless perf_ledger_rows_total"
                         "{site=trainer} and perf_regression_total"
                         "{metric=step_ms} are present")
    ap.add_argument("--paged", action="store_true", dest="paged",
                    help="run the paged-KV target (FLAGS_paged_kv engine "
                         "with 2 LoRA adapters, a boundary-straddling "
                         "shared prefix and cold sweeps); exit 1 unless "
                         "kv_page_blocks_total{state=hot|cold}, "
                         "kv_page_cow_total and serving_adapter_total"
                         "{event=load|hit|evict} are present")
    ap.add_argument("--elastic", action="store_true", dest="elastic",
                    help="run the elastic-training target (supervised "
                         "dp2 MLP killed mid-step via failpoint, resumed "
                         "on dp1 through the topology-aware restore); "
                         "exit 1 unless elastic_resume_total"
                         "{reason=failpoint}, checkpoint_reshard_total"
                         "{action=moment_reshard} and the elastic/resume "
                         "perf-ledger row are present")
    ap.add_argument("--goodput", action="store_true", dest="goodput",
                    help="run the goodput-ledger target (the elastic "
                         "kill-and-resume loop with FLAGS_goodput armed, "
                         "plus one served completion across a hot_swap); "
                         "exit 1 unless goodput_seconds_total{bucket=...}"
                         " per attribution bucket, goodput_fraction, "
                         "serving_weight_version and the run/goodput "
                         "perf-ledger row are present")
    ap.add_argument("--all", action="store_true",
                    help="all models + the serving loop + the router, "
                         "flight-recorder, federated, numerics, "
                         "quantized, async, mpmd, perf-ledger, paged-KV, "
                         "elastic and goodput tiers")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the graph_lint-schema machine report")
    ap.add_argument("--prometheus", action="store_true",
                    help="emit Prometheus text exposition instead of JSON")
    ap.add_argument("--trace", action="store_true", dest="with_trace",
                    help="run targets under FLAGS_trace and attach the "
                         "span summary (count + top-3 totals) per target")
    args = ap.parse_args(argv)

    targets = list(args.model)
    if args.serving:
        targets.append("serving")
    if args.router:
        targets.append("router")
    if args.blackbox:
        targets.append("blackbox")
    if args.federated:
        targets.append("federated")
    if args.numerics:
        targets.append("numerics")
    if args.quantized:
        targets.append("quantized")
    if args.async_:
        targets.append("async")
    if args.mpmd:
        targets.append("mpmd")
    if args.ledger:
        targets.append("ledger")
    if args.paged:
        targets.append("paged")
    if args.elastic:
        targets.append("elastic")
    if args.goodput:
        targets.append("goodput")
    if args.all:
        targets = list(MODEL_TARGETS) + ["serving", "router", "blackbox",
                                         "federated", "numerics",
                                         "quantized", "async", "mpmd",
                                         "ledger", "paged", "elastic",
                                         "goodput"]
    if not targets:
        ap.error("pick a target: --model NAME, --serving, --router, "
                 "--blackbox, --federated, --numerics, --quantized, "
                 "--async, --mpmd, --ledger, --paged, --elastic, "
                 "--goodput or --all")

    report = build_report(targets, with_trace=args.with_trace)
    if args.as_json:
        print(json.dumps(report, indent=1))
    elif args.prometheus:
        from paddle_tpu.monitor import to_prometheus

        for name, t in report["targets"].items():
            print(f"# target: {name}")
            # summaries= folds the p50/p90/p99 digests in as standard
            # quantile samples, so parse_prometheus round-trips the
            # percentiles instead of dropping them
            print(to_prometheus(t["snapshot"],
                                summaries=t.get("histograms")))
    else:
        for name, t in report["targets"].items():
            print(f"# target: {name}")
            if "trace" in t:
                print(json.dumps({"trace": t["trace"]}, sort_keys=True))
            print(json.dumps(t["snapshot"], indent=1, sort_keys=True))
            if "histograms" in t:
                print("# histograms (p50/p90/p99)")
                for key, d in sorted(t["histograms"].items()):
                    print(f"{key}: " + json.dumps(d, sort_keys=True))
    return 1 if report["totals"]["error"] else 0


if __name__ == "__main__":
    sys.exit(main())
