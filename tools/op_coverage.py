"""Operator-coverage report: reference REGISTER_OPERATOR surface vs this
package. Aliases map reference op names to the 2.x API names they became;
the INFRA pattern classifies framework/fused/PS-wire ops that are N/A by
design on this architecture (XLA fusion, collective API, tensor arrays,
DataLoader, quantization/ package). Prints the residual list.

Usage: python tools/op_coverage.py
"""
import jax; jax.config.update("jax_platforms", "cpu")
import glob, os, re, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
names = set()
for f in glob.glob("/root/reference/paddle/fluid/operators/**/*.cc", recursive=True):
    try: t = open(f, errors="ignore").read()
    except: continue
    for m in re.finditer(r"REGISTER_OPERATOR\(\s*([a-z0-9_]+)", t):
        names.add(m.group(1))
names = {n for n in names if not n.endswith("_grad")}
import paddle_tpu as paddle
from paddle_tpu.nn import functional as F
import paddle_tpu.nn as nn
import paddle_tpu.vision.ops as V
import paddle_tpu.text as T
import paddle_tpu.incubate as I
import paddle_tpu.static as S
import paddle_tpu.distributed as D
import paddle_tpu.metric as M

ALIAS = {  # op name -> our API name
 "elementwise_add":"add","elementwise_sub":"subtract","elementwise_mul":"multiply","elementwise_div":"divide",
 "elementwise_max":"maximum","elementwise_min":"minimum","elementwise_pow":"pow","elementwise_mod":"mod",
 "elementwise_floordiv":"floor_divide","reduce_sum":"sum","reduce_mean":"mean","reduce_max":"max","reduce_min":"min",
 "reduce_prod":"prod","reduce_all":"all","reduce_any":"any","matmul_v2":"matmul","mul":"matmul","fc":"linear",
 "lookup_table":"embedding","lookup_table_v2":"embedding","top_k":"topk","top_k_v2":"topk","arg_max":"argmax",
 "arg_min":"argmin","fill_constant":"full","fill_any_like":"full_like","fill_zeros_like2":"zeros_like","fill":"full",
 "uniform_random":"uniform","gaussian_random":"normal","truncated_gaussian_random":"normal","randint":"randint",
 "randperm":"randperm","multinomial":"multinomial","bernoulli":"bernoulli","one_hot":"one_hot","one_hot_v2":"one_hot",
 "expand_v2":"expand","expand_as_v2":"expand_as","tile":"tile","reshape2":"reshape","transpose2":"transpose",
 "squeeze2":"squeeze","unsqueeze2":"unsqueeze","flatten2":"flatten","flatten_contiguous_range":"flatten",
 "slice":"slice","strided_slice":"strided_slice","pad":"pad","pad2d":"pad","pad3d":"pad","pad_constant_like":"pad_constant_like",
 "cast":"cast","assign":"assign","assign_value":"assign","scale":"scale","increment":"increment","shape":"shape",
 "size":"numel","is_empty":"is_empty","crop":"crop","crop_tensor":"crop","reverse":"reverse","gather_tree":"gather_tree",
 "cross_entropy":"cross_entropy","cross_entropy2":"cross_entropy","softmax_with_cross_entropy":"softmax_with_cross_entropy",
 "sigmoid_cross_entropy_with_logits":"binary_cross_entropy_with_logits","bce_loss":"binary_cross_entropy",
 "huber_loss":"smooth_l1_loss","smooth_l1_loss":"smooth_l1_loss","kldiv_loss":"kl_div","margin_rank_loss":"margin_ranking_loss",
 "nll_loss":"nll_loss","log_loss":"log_loss","hinge_loss":"hinge_loss","rank_loss":"rank_loss","bpr_loss":"bpr_loss",
 "center_loss":"center_loss","modified_huber_loss":"modified_huber_loss","teacher_student_sigmoid_loss":"teacher_student_sigmoid_loss",
 "sigmoid_focal_loss":"sigmoid_focal_loss","warpctc":"ctc_loss","ctc_align":"ctc_align","edit_distance":"edit_distance",
 "linear_chain_crf":"linear_chain_crf","crf_decoding":"viterbi_decode","nce":"nce","hierarchical_sigmoid":"hsigmoid_loss",
 "batch_norm":"batch_norm","sync_batch_norm":"SyncBatchNorm","layer_norm":"layer_norm","instance_norm":"instance_norm",
 "group_norm":"group_norm","data_norm":"data_norm","lrn":"local_response_norm","spectral_norm":"SpectralNorm",
 "conv2d":"conv2d","conv3d":"conv3d","conv2d_transpose":"conv2d_transpose","conv3d_transpose":"conv3d_transpose",
 "depthwise_conv2d":"conv2d","depthwise_conv2d_transpose":"conv2d_transpose","deformable_conv":"deform_conv2d",
 "deformable_conv_v1":"deform_conv2d","pool2d":"max_pool2d","pool3d":"max_pool3d","max_pool2d_with_index":"max_pool2d",
 "max_pool3d_with_index":"max_pool3d","spp":"spp","unpool":"max_unpool2d","maxout":"maxout","prelu":"prelu","selu":"selu",
 "mish":"mish","grid_sampler":"grid_sample","affine_grid":"affine_grid","affine_channel":"affine_channel",
 "pixel_shuffle":"pixel_shuffle","shuffle_channel":"channel_shuffle","space_to_depth":"space_to_depth","unfold":"unfold",
 "temporal_shift":"temporal_shift","im2sequence":"im2sequence","row_conv":"row_conv","conv_shift":"conv_shift",
 "cos_sim":"cos_sim","bilinear_tensor_product":"bilinear_tensor_product","l1_norm":"l1_norm","squared_l2_norm":"squared_l2_norm",
 "squared_l2_distance":"dist","dist":"dist","p_norm":"norm","frobenius_norm":"norm","norm":"norm",
 "bilinear_interp":"interpolate","bilinear_interp_v2":"interpolate","nearest_interp":"interpolate","nearest_interp_v2":"interpolate",
 "bicubic_interp":"interpolate","bicubic_interp_v2":"interpolate","trilinear_interp":"interpolate","trilinear_interp_v2":"interpolate",
 "linear_interp":"interpolate","linear_interp_v2":"interpolate","dropout":"dropout","label_smooth":"label_smooth",
 "diag_v2":"diag","diag_embed":"diag_embed","tril_triu":"tril","meshgrid":"meshgrid","multiplex":"multiplex",
 "eye":"eye","empty":"empty","inverse":"inverse","cholesky":"cholesky","matrix_nms":"matrix_nms","multiclass_nms":"multiclass_nms",
 "multiclass_nms2":"multiclass_nms","multiclass_nms3":"multiclass_nms","locality_aware_nms":"locality_aware_nms",
 "yolo_box":"yolo_box","yolov3_loss":"yolov3_loss","prior_box":"prior_box","density_prior_box":"density_prior_box",
 "anchor_generator":"anchor_generator","box_coder":"box_coder","box_clip":"box_clip","box_decoder_and_assign":"box_decoder_and_assign",
 "iou_similarity":"iou_similarity","bipartite_match":"bipartite_match","target_assign":"target_assign","rpn_target_assign":"rpn_target_assign",
 "retinanet_detection_output":"retinanet_detection_output","generate_proposals":"generate_proposals","generate_proposals_v2":"generate_proposals",
 "generate_proposal_labels":"generate_proposal_labels","distribute_fpn_proposals":"distribute_fpn_proposals",
 "collect_fpn_proposals":"collect_fpn_proposals","roi_align":"roi_align","roi_pool":"roi_pool","psroi_pool":"psroi_pool",
 "prroi_pool":"prroi_pool","roi_perspective_transform":"roi_perspective_transform","mine_hard_examples":"mine_hard_examples",
 "polygon_box_transform":"polygon_box_transform","similarity_focus":"similarity_focus","var_conv_2d":"var_conv_2d",
 "match_matrix_tensor":"match_matrix_tensor","tdm_child":"tdm_child","tdm_sampler":"tdm_sampler","segment_pool":"segment_sum",
 "cvm":"cvm","fsp":"fsp_matrix","accuracy":"accuracy","auc":"Auc","mean_iou":"mean_iou","precision_recall":"Precision",
 "detection_map":"Auc","scatter_nd_add":"scatter_nd_add","gather_nd":"gather_nd","sample_logits":"nce",
 "add_position_encoding":"add_position_encoding","partial_concat":"partial_concat","partial_sum":"partial_sum",
 "shuffle_batch":"shuffle_batch","sampling_id":"sampling_id","random_crop":"RandomCrop","rnn":"RNN","cudnn_lstm":"LSTM",
 "lstm":"LSTM","lstmp":"LSTM","gru":"GRU","gru_unit":"GRUCell","lstm_unit":"LSTMCell","attention_lstm":"LSTMCell",
 "beam_search":"BeamSearchDecoder","beam_search_decode":"dynamic_decode","recurrent":"RNN","while":"while_loop",
 "conditional_block":"cond","conditional_block_infer":"cond","print":"Print","assert":"Assert","py_func":"py_func",
 "mean":"mean","sum":"add_n","minus":"subtract","grad_add":"add","sgd":"SGD","momentum":"Momentum","lars_momentum":"Lars",
 "adam":"Adam","adamax":"Adamax","adagrad":"Adagrad","rmsprop":"RMSProp","ftrl":"Ftrl","dpsgd":"Dpsgd","lamb":"Lamb",
 "average_accumulates":"ModelAverage","check_finite_and_unscale":"GradScaler","update_loss_scaling":"GradScaler",
 "clip":"clip","clip_by_norm":"clip","hard_sigmoid":"hardsigmoid","hard_swish":"hardswish","hard_shrink":"hardshrink",
 # int8 serving table: pull() dequantizes (tests/test_xla_fusion_na.py)
 "lookup_table_dequant":"SparseTable.quantize",
}
import paddle_tpu.vision.transforms as VTR
import paddle_tpu.distributed.ps.tables as PST
MODS = [paddle, F, nn, V, T, I, S, D, M, VTR, PST, paddle.optimizer, paddle.amp, paddle.metric, paddle.static.nn]
def have(n):
    target = ALIAS.get(n, n)
    # dotted targets resolve attribute chains (e.g. a class method:
    # "SparseTable.quantize" — the int8 table realizing lookup_table_dequant)
    def _has(m, tgt):
        for part in tgt.split("."):
            if not hasattr(m, part):
                return False
            m = getattr(m, part)
        return True
    # Tensor methods count (e.g. set_value — the reference's set_value op
    # surfaces as Tensor.set_value in 2.x)
    return any(_has(m, target) for m in MODS) or \
        hasattr(paddle.Tensor, target)
missing = sorted(n for n in names if not have(n))
# infra/framework ops that are N/A by design on this architecture
INFRA = re.compile(r"^(c_|fake_|fused_|fusion_|lookup_sparse_table|pull_|push_|quantize|dequantize|requantize|moving_average_abs_max|send|recv|listen|fetch|feed|load|save|memcpy|delete_var|get_places|enqueue|dequeue|checkpoint|prefetch|create_custom_reader|gen_nccl|gen_bkcl|nccl|ascend|heter|ref_by_trainer|rank_attention|batch_fc|pyramid_hash|filter_by_instag|tensorrt|lite_engine|run_program|seed|dgc|distributed_|split_byref|split_ids|merge_ids|split_selected_rows|merge_selected_rows|get_tensor_from_selected_rows|beam_search$|read|write_to_array|read_from_array|array_to_lod|lod_|merge_lod|split_lod|reorder_lod|max_sequence_len|shrink_rnn|rnn_memory|select_input|select_output|tensor_array|sparse_tensor_load|coalesce_tensor|share_data|update_loss|mul$|inplace_abn|sequence_)")
# CUDA hand-fused kernels whose role XLA's own fusion plays — each claim is
# ASSERTED on optimized HLO by tests/test_xla_fusion_na.py (epilogues fused,
# no standalone elementwise in ENTRY), not just argued
FUSED_XLA = {"conv2d_fusion", "conv2d_inception_fusion", "multi_gru"}
# grad registrations are realized by the generic tape/vjp autodiff (SURVEY
# layer 4c), not per-op grad kernels. `*_grad` names are already dropped at
# the scan; cross_entropy2's separately-registered `_grad2` is the one
# residual that reaches here. Backed by the analytic-gradient check in
# tests/test_xla_fusion_na.py::TestGradOpsAutodiffRealized.
GRAD_REALIZED = re.compile(r".*_grad2$")
core_missing = [n for n in missing
                if not INFRA.match(n) and n not in FUSED_XLA
                and not GRAD_REALIZED.match(n)]

if __name__ == "__main__":
    print("reference ops:", len(names), "| unmatched:", len(missing),
          "| core unmatched:", len(core_missing))
    print(core_missing)
