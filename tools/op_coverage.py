"""Operator-coverage report: reference REGISTER_OPERATOR surface vs this
package. Aliases map reference op names to the 2.x API names they became.

Every reference op with no name/alias match gets an EXPLICIT per-op entry in
DISPOSITION (VERDICT r4 #2 — no prefix regex sweeping): either
`implemented-as <dotted api>` (target resolved against the live package),
`N/A <reason>` (the role exists but the architecture dissolves the op —
XLA fusion, jit feed binding, padded LoD), or `descoped <reason>` (a
conscious, documented non-goal). The audit test
(tests/test_op_coverage_audit.py) pins: zero unclassified ops, zero stale
entries, every implemented-as target resolvable.

Usage: python tools/op_coverage.py [-v] [--json]

--json emits the machine-readable report in the same schema as
tools/graph_lint.py --json (tool/targets/counts/findings/totals), so the
lint gate and the coverage audit share one report format.
"""
import jax; jax.config.update("jax_platforms", "cpu")
import glob, os, re, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
names = set()
for f in glob.glob("/root/reference/paddle/fluid/operators/**/*.cc", recursive=True):
    try: t = open(f, errors="ignore").read()
    except: continue
    for m in re.finditer(r"REGISTER_OPERATOR\(\s*([a-z0-9_]+)", t):
        names.add(m.group(1))
names = {n for n in names if not n.endswith("_grad")}
import paddle_tpu as paddle
from paddle_tpu.nn import functional as F
import paddle_tpu.nn as nn
import paddle_tpu.vision.ops as V
import paddle_tpu.text as T
import paddle_tpu.incubate as I
import paddle_tpu.static as S
import paddle_tpu.distributed as D
import paddle_tpu.metric as M
import paddle_tpu.quantization as Q
import paddle_tpu.distributed.ps  # noqa: F401 — resolves ps.* targets
import paddle_tpu.distributed.ps.tables  # noqa: F401
import paddle_tpu.io.multislot  # noqa: F401 — resolves io.multislot targets
import paddle_tpu.jit  # noqa: F401

ALIAS = {  # op name -> our API name
 "elementwise_add":"add","elementwise_sub":"subtract","elementwise_mul":"multiply","elementwise_div":"divide",
 "elementwise_max":"maximum","elementwise_min":"minimum","elementwise_pow":"pow","elementwise_mod":"mod",
 "elementwise_floordiv":"floor_divide","reduce_sum":"sum","reduce_mean":"mean","reduce_max":"max","reduce_min":"min",
 "reduce_prod":"prod","reduce_all":"all","reduce_any":"any","matmul_v2":"matmul","mul":"matmul","fc":"linear",
 "lookup_table":"embedding","lookup_table_v2":"embedding","top_k":"topk","top_k_v2":"topk","arg_max":"argmax",
 "arg_min":"argmin","fill_constant":"full","fill_any_like":"full_like","fill_zeros_like2":"zeros_like","fill":"full",
 "uniform_random":"uniform","gaussian_random":"normal","truncated_gaussian_random":"normal","randint":"randint",
 "randperm":"randperm","multinomial":"multinomial","bernoulli":"bernoulli","one_hot":"one_hot","one_hot_v2":"one_hot",
 "expand_v2":"expand","expand_as_v2":"expand_as","tile":"tile","reshape2":"reshape","transpose2":"transpose",
 "squeeze2":"squeeze","unsqueeze2":"unsqueeze","flatten2":"flatten","flatten_contiguous_range":"flatten",
 "slice":"slice","strided_slice":"strided_slice","pad":"pad","pad2d":"pad","pad3d":"pad","pad_constant_like":"pad_constant_like",
 "cast":"cast","assign":"assign","assign_value":"assign","scale":"scale","increment":"increment","shape":"shape",
 "size":"numel","is_empty":"is_empty","crop":"crop","crop_tensor":"crop","reverse":"reverse","gather_tree":"gather_tree",
 "cross_entropy":"cross_entropy","cross_entropy2":"cross_entropy","softmax_with_cross_entropy":"softmax_with_cross_entropy",
 "sigmoid_cross_entropy_with_logits":"binary_cross_entropy_with_logits","bce_loss":"binary_cross_entropy",
 "huber_loss":"smooth_l1_loss","smooth_l1_loss":"smooth_l1_loss","kldiv_loss":"kl_div","margin_rank_loss":"margin_ranking_loss",
 "nll_loss":"nll_loss","log_loss":"log_loss","hinge_loss":"hinge_loss","rank_loss":"rank_loss","bpr_loss":"bpr_loss",
 "center_loss":"center_loss","modified_huber_loss":"modified_huber_loss","teacher_student_sigmoid_loss":"teacher_student_sigmoid_loss",
 "sigmoid_focal_loss":"sigmoid_focal_loss","warpctc":"ctc_loss","ctc_align":"ctc_align","edit_distance":"edit_distance",
 "linear_chain_crf":"linear_chain_crf","crf_decoding":"viterbi_decode","nce":"nce","hierarchical_sigmoid":"hsigmoid_loss",
 "batch_norm":"batch_norm","sync_batch_norm":"SyncBatchNorm","layer_norm":"layer_norm","instance_norm":"instance_norm",
 "group_norm":"group_norm","data_norm":"data_norm","lrn":"local_response_norm","spectral_norm":"SpectralNorm",
 "conv2d":"conv2d","conv3d":"conv3d","conv2d_transpose":"conv2d_transpose","conv3d_transpose":"conv3d_transpose",
 "depthwise_conv2d":"conv2d","depthwise_conv2d_transpose":"conv2d_transpose","deformable_conv":"deform_conv2d",
 "deformable_conv_v1":"deform_conv2d","pool2d":"max_pool2d","pool3d":"max_pool3d","max_pool2d_with_index":"max_pool2d",
 "max_pool3d_with_index":"max_pool3d","spp":"spp","unpool":"max_unpool2d","maxout":"maxout","prelu":"prelu","selu":"selu",
 "mish":"mish","grid_sampler":"grid_sample","affine_grid":"affine_grid","affine_channel":"affine_channel",
 "pixel_shuffle":"pixel_shuffle","shuffle_channel":"channel_shuffle","space_to_depth":"space_to_depth","unfold":"unfold",
 "temporal_shift":"temporal_shift","im2sequence":"im2sequence","row_conv":"row_conv","conv_shift":"conv_shift",
 "cos_sim":"cos_sim","bilinear_tensor_product":"bilinear_tensor_product","l1_norm":"l1_norm","squared_l2_norm":"squared_l2_norm",
 "squared_l2_distance":"dist","dist":"dist","p_norm":"norm","frobenius_norm":"norm","norm":"norm",
 "bilinear_interp":"interpolate","bilinear_interp_v2":"interpolate","nearest_interp":"interpolate","nearest_interp_v2":"interpolate",
 "bicubic_interp":"interpolate","bicubic_interp_v2":"interpolate","trilinear_interp":"interpolate","trilinear_interp_v2":"interpolate",
 "linear_interp":"interpolate","linear_interp_v2":"interpolate","dropout":"dropout","label_smooth":"label_smooth",
 "diag_v2":"diag","diag_embed":"diag_embed","tril_triu":"tril","meshgrid":"meshgrid","multiplex":"multiplex",
 "eye":"eye","empty":"empty","inverse":"inverse","cholesky":"cholesky","matrix_nms":"matrix_nms","multiclass_nms":"multiclass_nms",
 "multiclass_nms2":"multiclass_nms","multiclass_nms3":"multiclass_nms","locality_aware_nms":"locality_aware_nms",
 "yolo_box":"yolo_box","yolov3_loss":"yolov3_loss","prior_box":"prior_box","density_prior_box":"density_prior_box",
 "anchor_generator":"anchor_generator","box_coder":"box_coder","box_clip":"box_clip","box_decoder_and_assign":"box_decoder_and_assign",
 "iou_similarity":"iou_similarity","bipartite_match":"bipartite_match","target_assign":"target_assign","rpn_target_assign":"rpn_target_assign",
 "retinanet_detection_output":"retinanet_detection_output","generate_proposals":"generate_proposals","generate_proposals_v2":"generate_proposals",
 "generate_proposal_labels":"generate_proposal_labels","distribute_fpn_proposals":"distribute_fpn_proposals",
 "collect_fpn_proposals":"collect_fpn_proposals","roi_align":"roi_align","roi_pool":"roi_pool","psroi_pool":"psroi_pool",
 "prroi_pool":"prroi_pool","roi_perspective_transform":"roi_perspective_transform","mine_hard_examples":"mine_hard_examples",
 "polygon_box_transform":"polygon_box_transform","similarity_focus":"similarity_focus","var_conv_2d":"var_conv_2d",
 "match_matrix_tensor":"match_matrix_tensor","tdm_child":"tdm_child","tdm_sampler":"tdm_sampler","segment_pool":"segment_sum",
 "cvm":"cvm","fsp":"fsp_matrix","accuracy":"accuracy","auc":"Auc","mean_iou":"mean_iou","precision_recall":"Precision",
 "detection_map":"Auc","scatter_nd_add":"scatter_nd_add","gather_nd":"gather_nd","sample_logits":"nce",
 "add_position_encoding":"add_position_encoding","partial_concat":"partial_concat","partial_sum":"partial_sum",
 "shuffle_batch":"shuffle_batch","sampling_id":"sampling_id","random_crop":"RandomCrop","rnn":"RNN","cudnn_lstm":"LSTM",
 "lstm":"LSTM","lstmp":"LSTM","gru":"GRU","gru_unit":"GRUCell","lstm_unit":"LSTMCell","attention_lstm":"LSTMCell",
 "beam_search":"BeamSearchDecoder","beam_search_decode":"dynamic_decode","recurrent":"RNN","while":"while_loop",
 "conditional_block":"cond","conditional_block_infer":"cond","print":"Print","assert":"Assert","py_func":"py_func",
 "mean":"mean","sum":"add_n","minus":"subtract","grad_add":"add","sgd":"SGD","momentum":"Momentum","lars_momentum":"Lars",
 "adam":"Adam","adamax":"Adamax","adagrad":"Adagrad","rmsprop":"RMSProp","ftrl":"Ftrl","dpsgd":"Dpsgd","lamb":"Lamb",
 "average_accumulates":"ModelAverage","check_finite_and_unscale":"GradScaler","update_loss_scaling":"GradScaler",
 "clip":"clip","clip_by_norm":"clip","hard_sigmoid":"hardsigmoid","hard_swish":"hardswish","hard_shrink":"hardshrink",
 # int8 serving table: pull() dequantizes (tests/test_xla_fusion_na.py)
 "lookup_table_dequant":"SparseTable.quantize",
 # r5: hashed n-gram embeddings (pyramid_hash_op.cc) under the fluid
 # contrib wrapper's name
 "pyramid_hash":"search_pyramid_hash",
 # QAT channel-wise quant: same op, 2.x argument order in the name
 "fake_channel_wise_quantize_abs_max":"fake_quantize_channel_wise_abs_max",
}
import paddle_tpu.vision.transforms as VTR
import paddle_tpu.distributed.ps.tables as PST
MODS = [paddle, F, nn, V, T, I, S, D, M, Q, VTR, PST, paddle.optimizer,
        paddle.amp, paddle.metric, paddle.static.nn]
def have(n):
    target = ALIAS.get(n, n)
    # dotted targets resolve attribute chains (e.g. a class method:
    # "SparseTable.quantize" — the int8 table realizing lookup_table_dequant)
    def _has(m, tgt):
        for part in tgt.split("."):
            if not hasattr(m, part):
                return False
            m = getattr(m, part)
        return True
    # Tensor methods count (e.g. set_value — the reference's set_value op
    # surfaces as Tensor.set_value in 2.x)
    return any(_has(m, target) for m in MODS) or \
        hasattr(paddle.Tensor, target)
missing = sorted(n for n in names if not have(n))


def IMPL(target, note=""):
    """Realized by a live API; `target` is a dotted path from the paddle
    root, verified resolvable by resolve_target()."""
    return ("implemented-as", target, note)


def NA(reason):
    """The op's ROLE exists but this architecture dissolves the op itself
    (XLA owns it, jit binding owns it, padded LoD removes it)."""
    return ("N/A", "", reason)


def DESCOPED(reason):
    """Conscious non-goal, recorded in PARITY.md."""
    return ("descoped", "", reason)


_XLA_FUSED = ("CUDA hand-fused kernel; XLA fuses the same pattern — "
              "ENTRY-block-asserted in tests/test_xla_fusion_na.py")
_STREAM = ("CUDA stream ordering; XLA schedules compute and collectives "
           "inside one program, no stream-sync ops exist")
_RANK_TABLE = ("fluid DynamicRNN LoD-rank-table machinery; lax.scan over "
               "padded batches (nn.RNN / nn.LSTM) replaces DynamicRNN")
_SELROWS = ("SelectedRows sparse-gradient container; gradients are dense "
            "by design (PARITY — XLA has no ragged rows), PS sparse paths "
            "use the C++ table engine instead")
_BOXPS = ("BoxPS — Baidu's GPU-box embedded-PS appliance path; "
          "hardware-specific, descoped with heter-PS (PARITY §descopes)")

# Every unmatched reference op, individually adjudicated. Order mirrors the
# reference source tree: collectives, PS wire, quantization, fused kernels,
# LoD/array control flow, executor plumbing, engines.
DISPOSITION = {
    # --- collective comm (operators/collective/) -------------------------
    "c_allgather": IMPL("distributed.all_gather"),
    "c_allreduce_sum": IMPL("distributed.all_reduce"),
    "c_reducescatter": IMPL("distributed.reduce_scatter"),
    "c_comm_init": IMPL("distributed.init_parallel_env",
                        "NCCL communicator bootstrap -> mesh construction"),
    "c_comm_init_all": IMPL("distributed.init_parallel_env"),
    "c_gen_nccl_id": IMPL("distributed.init_parallel_env",
                          "ncclUniqueId TCP exchange -> "
                          "jax.distributed.initialize"),
    "c_gen_bkcl_id": IMPL("distributed.init_parallel_env"),
    "gen_nccl_id": IMPL("distributed.init_parallel_env"),
    "gen_bkcl_id": IMPL("distributed.init_parallel_env"),
    "c_sync_calc_stream": NA(_STREAM),
    "c_sync_comm_stream": NA(_STREAM),
    "c_wait_comm": NA(_STREAM),
    "c_wait_compute": NA(_STREAM),
    "nccl": NA("raw ncclAllReduce/Bcast/Reduce op wrappers; XLA ICI "
               "collectives are the duals (distributed/collective.py)"),
    "ascend_trigger": NA("Ascend-NPU scheduling hook; TPU is the "
                         "first-class device here"),
    # --- PS wire ops (operators/distributed/, pscore) --------------------
    "listen_and_serv": IMPL("distributed.ps.server"),
    "heter_listen_and_serv": DESCOPED("heter-PS GPU-cache serving path "
                                      "(PARITY §descopes)"),
    "send_and_recv": IMPL("distributed.ps.rpc"),
    "send_barrier": IMPL("distributed.barrier"),
    "fetch_barrier": IMPL("distributed.barrier"),
    "prefetch": IMPL("distributed.ps.rpc",
                     "sparse-row prefetch rides the same RPC pull"),
    "recv_save": IMPL("distributed.ps.runtime",
                      "server-side snapshot save"),
    "checkpoint_notify": IMPL("distributed.ps.runtime",
                              "snapshot trigger RPC"),
    "distributed_lookup_table": IMPL("distributed.ps.tables.SparseTable"),
    "lookup_sparse_table_read": IMPL("distributed.ps.tables.SparseTable"),
    "lookup_sparse_table_write": IMPL("distributed.ps.tables.SparseTable"),
    "lookup_sparse_table_init": IMPL("distributed.ps.tables.SparseTable"),
    "lookup_sparse_table_merge": IMPL("distributed.ps.tables.SparseTable"),
    "lookup_sparse_table_grad_split": IMPL(
        "distributed.ps.tables.SparseTable"),
    "lookup_sparse_table_fuse_adam": IMPL(
        "distributed.ps.tables.SparseTable",
        "server-side fused optimizer update (C++ sparse_table.cc)"),
    "lookup_sparse_table_fuse_sgd": IMPL(
        "distributed.ps.tables.SparseTable"),
    "pull_sparse": IMPL("distributed.ps.rpc"),
    "pull_sparse_v2": IMPL("distributed.ps.rpc"),
    "push_sparse": IMPL("distributed.ps.rpc"),
    "push_sparse_v2": IMPL("distributed.ps.rpc"),
    "push_dense": IMPL("distributed.ps.rpc"),
    "pull_box_sparse": DESCOPED(_BOXPS),
    "pull_box_extended_sparse": DESCOPED(_BOXPS),
    "push_box_sparse": DESCOPED(_BOXPS),
    "push_box_extended_sparse": DESCOPED(_BOXPS),
    "split_ids": IMPL("distributed.ps.server",
                      "id->shard routing lives in the server"),
    "merge_ids": IMPL("distributed.ps.server"),
    "split_byref": NA("zero-copy row split feeding per-server sends; the "
                      "RPC layer shards rows itself (distributed/ps/rpc.py)"),
    "fake_init": NA("trainer-side placeholder init for remote params; "
                    "params live server-side (distributed/ps/server.py)"),
    "sparse_tensor_load": IMPL("distributed.ps.runtime",
                               "PS snapshot load path"),
    # --- quantization (operators/fake_quantize_op.cc etc.) ---------------
    "fake_quantize_dequantize_abs_max": IMPL(
        "quantization.fake_quantize_abs_max",
        "fake_quantize_* IS quantize-dequantize with straight-through grad"),
    "fake_quantize_dequantize_moving_average_abs_max": IMPL(
        "quantization.fake_quantize_moving_average_abs_max"),
    "fake_channel_wise_quantize_dequantize_abs_max": IMPL(
        "quantization.fake_quantize_channel_wise_abs_max"),
    "fake_dequantize_max_abs": IMPL("quantization.dequantize"),
    "fake_channel_wise_dequantize_max_abs": IMPL("quantization.dequantize"),
    "dequantize_abs_max": IMPL("quantization.dequantize"),
    "moving_average_abs_max_scale": IMPL(
        "quantization.fake_quantize_moving_average_abs_max",
        "scale-tracking-only variant of the same observer"),
    "quantize": NA("oneDNN int8 graph-pass op pair; TPU int8 deployment is "
                   "the quantize_to_int8 artifact (quantization/ptq.py)"),
    "requantize": NA("oneDNN int8 re-scale between int8 kernels; XLA owns "
                     "the int8 dataflow"),
    "dequantize_log": DESCOPED("log-scale quantization table (mobile slim "
                               "artifact); abs-max int8 is the supported "
                               "deployment format"),
    # --- CUDA/oneDNN hand-fused kernels (operators/fused/) ---------------
    "conv2d_fusion": NA(_XLA_FUSED),
    "conv2d_inception_fusion": NA(_XLA_FUSED),
    "multi_gru": NA(_XLA_FUSED),
    "fused_batch_norm_act": NA(_XLA_FUSED),
    "fused_bn_add_activation": NA(_XLA_FUSED),
    "fused_elemwise_activation": NA(_XLA_FUSED),
    "fused_elemwise_add_activation": NA(_XLA_FUSED),
    "fused_embedding_fc_lstm": NA(_XLA_FUSED),
    "fused_embedding_seq_pool": NA(_XLA_FUSED),
    "fused_fc_elementwise_layernorm": NA(_XLA_FUSED),
    "fusion_group": NA("runtime CUDA codegen for elementwise groups; "
                       "XLA's fusion pass is this, always on"),
    "fusion_gru": NA(_XLA_FUSED),
    "fusion_lstm": NA(_XLA_FUSED),
    "fusion_repeated_fc_relu": NA(_XLA_FUSED),
    "fusion_seqconv_eltadd_relu": NA(_XLA_FUSED),
    "fusion_seqexpand_concat_fc": NA(_XLA_FUSED),
    "fusion_seqpool_concat": NA(_XLA_FUSED),
    "fusion_seqpool_cvm_concat": NA(_XLA_FUSED),
    "fusion_squared_mat_sub": NA(_XLA_FUSED),
    "fusion_transpose_flatten_concat": NA(_XLA_FUSED),
    "inplace_abn": NA("in-place activated BN saves activation memory; "
                      "jax.checkpoint/remat owns the memory trade "
                      "(distributed/spmd.py recompute)"),
    # --- LoD / TensorArray control flow (operators/lod_*, *_array) -------
    "write_to_array": IMPL("array_write"),
    "read_from_array": IMPL("array_read"),
    "lod_array_length": IMPL("array_length"),
    "array_to_lod_tensor": NA("TensorArray->LoD glue; LoD is padded+mask "
                              "by design (PARITY), arrays stack via "
                              "paddle.concat/stack"),
    "lod_tensor_to_array": NA("LoD->TensorArray glue; same padded design"),
    "tensor_array_to_tensor": IMPL("create_array",
                                   "array list + paddle.concat/stack"),
    "lod_rank_table": NA(_RANK_TABLE),
    "max_sequence_len": NA(_RANK_TABLE),
    "reorder_lod_tensor_by_rank": NA(_RANK_TABLE),
    "shrink_rnn_memory": NA(_RANK_TABLE),
    "rnn_memory_helper": NA(_RANK_TABLE),
    "lod_reset": NA("rewrites LoD metadata in place; padded+mask carries "
                    "explicit length tensors instead (nn/functional/"
                    "sequence.py family)"),
    "split_lod_tensor": NA("fluid IfElse mask-split plumbing; lax.cond "
                           "traces both branches (paddle.static.nn.cond)"),
    "merge_lod_tensor": NA("fluid IfElse merge; jnp.where/lax.cond"),
    "merge_lod_tensor_infer": NA("inference-mode IfElse merge; lax.cond"),
    "select_input": NA("cond-block input router; lax.cond"),
    "select_output": NA("cond-block output router; lax.cond"),
    # --- SelectedRows plumbing -------------------------------------------
    "get_tensor_from_selected_rows": NA(_SELROWS),
    "merge_selected_rows": NA(_SELROWS),
    "split_selected_rows": NA(_SELROWS),
    # --- executor / scope / IO plumbing ----------------------------------
    "feed": NA("Executor feed slot; jit argument binding "
               "(static/__init__.py Executor.run feed dict)"),
    "fetch": NA("Executor fetch slot; jit result binding"),
    "delete_var": NA("scope GC op; XLA buffer liveness + python GC"),
    "memcpy": NA("explicit H2D/D2H staging between scopes; "
                 "jax.device_put and XLA manage placement"),
    "get_places": IMPL("static.cpu_places",
                       "device enumeration (paddle.static.cuda_places / "
                       "paddle.get_device)"),
    "load_combine": IMPL("static.load",
                         "combined-file parameter bundle load"),
    "save_combine": IMPL("static.save"),
    "create_custom_reader": IMPL("io.DataLoader",
                                 "decorated reader pipeline"),
    "read": IMPL("io.DataLoader", "reader-op dequeue = loader iteration"),
    "run_program": IMPL("jit.load",
                        "dygraph sub-Program execution for loaded models"),
    "coalesce_tensor": NA("grad-buffer fusion for allreduce bucketing; "
                          "XLA's all-reduce combiner + SPMD own it "
                          "(distributed/spmd.py)"),
    "cross_entropy_grad2": NA("separately-registered grad kernel; tape "
                              "autodiff realizes it, analytic-grad-checked "
                              "(tests/test_xla_fusion_na.py::"
                              "TestGradOpsAutodiffRealized)"),
    # --- alternate inference engines -------------------------------------
    "tensorrt_engine": NA("TensorRT subgraph offload; XLA is the compiler "
                          "on TPU (inference/ Predictor AOT path)"),
    "lite_engine": DESCOPED("Paddle-Lite mobile subgraph engine; "
                            "deployment here is jit.save / ONNX export"),
}


def resolve_target(target):
    """Dotted path from the paddle root (submodules imported above)."""
    m = paddle
    for part in target.split("."):
        if not hasattr(m, part):
            return False
        m = getattr(m, part)
    return True


undispositioned = [n for n in missing if n not in DISPOSITION]
stale = sorted(set(DISPOSITION) - set(missing))
bad_targets = [n for n, (kind, tgt, _) in sorted(DISPOSITION.items())
               if kind == "implemented-as" and not resolve_target(tgt)]
core_missing = undispositioned + bad_targets
# ops whose N/A cites the HLO-fusion assertion file — the audit test checks
# the three specifically-asserted kernels appear there by name
FUSED_XLA = {"conv2d_fusion", "conv2d_inception_fusion", "multi_gru"}

def json_report():
    """Shared graph_lint report schema: every audit failure (unclassified
    op, stale entry, unresolvable target) is an error-severity finding."""
    kinds = {}
    for n in missing:
        k = DISPOSITION.get(n, ("UNCLASSIFIED", "", ""))[0]
        kinds[k] = kinds.get(k, 0) + 1
    findings = []
    # without the reference checkout (names empty) the unclassified/stale
    # checks are vacuous — every DISPOSITION entry would read as "stale".
    # Only the target-resolution audit stays meaningful: it validates
    # against the LIVE package, no reference tree needed.
    if names:
        for n in undispositioned:
            findings.append({"pass": "op-unclassified", "severity": "error",
                             "message": f"reference op '{n}' has no API "
                                        "match and no DISPOSITION entry",
                             "where": n})
        for n in stale:
            findings.append({"pass": "op-stale-disposition",
                             "severity": "error",
                             "message": f"DISPOSITION entry '{n}' no longer "
                                        "matches a missing reference op",
                             "where": n})
    for n in bad_targets:
        findings.append({"pass": "op-unresolvable-target",
                         "severity": "error",
                         "message": f"implemented-as target for '{n}' does "
                                    f"not resolve: {DISPOSITION[n][1]}",
                         "where": n})
    counts = {"error": len(findings), "warning": 0, "info": 0}
    return {
        "tool": "op_coverage",
        "passes": ["op-unclassified", "op-stale-disposition",
                   "op-unresolvable-target"],
        "targets": {"op_coverage": {"name": "op_coverage",
                                    "counts": counts,
                                    "findings": findings}},
        "totals": dict(counts),
        "meta": {"reference_ops": len(names), "unmatched": len(missing),
                 "reference_available": bool(names),
                 "dispositions": dict(sorted(kinds.items()))},
    }


if __name__ == "__main__":
    if "--json" in sys.argv:
        import json as _json

        rep = json_report()
        print(_json.dumps(rep, indent=1))
        sys.exit(1 if rep["totals"]["error"] else 0)
    kinds = {}
    for n in missing:
        k = DISPOSITION.get(n, ("UNCLASSIFIED", "", ""))[0]
        kinds[k] = kinds.get(k, 0) + 1
    print("reference ops:", len(names), "| unmatched:", len(missing),
          "| dispositions:", dict(sorted(kinds.items())),
          "| unclassified:", len(undispositioned),
          "| stale entries:", len(stale),
          "| unresolvable targets:", len(bad_targets))
    if "-v" in sys.argv or undispositioned or stale or bad_targets:
        width = max((len(n) for n in missing), default=10)
        for n in missing:
            kind, tgt, note = DISPOSITION.get(n, ("UNCLASSIFIED", "", ""))
            detail = tgt if kind == "implemented-as" else note
            if kind == "implemented-as" and note:
                detail += f"  ({note})"
            print(f"  {n:<{width}}  {kind:<15} {detail}")
        for n in stale:
            print(f"  STALE entry (op now matched or gone): {n}")
        for n in bad_targets:
            print(f"  UNRESOLVABLE target: {n} -> {DISPOSITION[n][1]}")
