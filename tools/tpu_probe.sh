#!/bin/bash
# ONE patient TPU probe. Writes an unbuffered timeline to /tmp/tpu_probe.log so
# a partial run shows exactly where init/compile/execute stalled. Never run
# two TPU processes at once; go quiet 30+ min between probes (see
# .claude/skills/verify/SKILL.md). On success, chain the full measurement
# batch (tools/tpu_session.sh) immediately — same process chain, one client
# at a time.
set -u
cd "$(dirname "$0")/.."

stdbuf -oL -eL timeout "${1:-3000}" python -u - <<'EOF' > /tmp/tpu_probe.log 2>&1
import time, sys
t0 = time.time()
def mark(msg):
    print(f"[{time.time()-t0:7.1f}s] {msg}", flush=True)
mark("python up")
import jax, jax.numpy as jnp
mark("jax imported")
d = jax.devices()
mark(f"devices: {d}")
x = jnp.ones((1024, 1024), jnp.bfloat16)
mark("array placed")
y = (x @ x).block_until_ready()
mark("matmul done — tunnel HEALTHY")
EOF
rc=$?
echo "[tpu_probe] exit=$rc" >> /tmp/tpu_probe.log
if grep -q "HEALTHY" /tmp/tpu_probe.log; then
  echo "[tpu_probe] healthy — chaining measurement batch" >> /tmp/tpu_probe.log
  bash tools/tpu_session.sh >> /tmp/tpu_probe.log 2>&1
fi
