"""Pipeline schedule peak-memory measurement (VERDICT r2 #5).

Compiles the SAME pipeline train step under schedule_mode='F-then-B' (GPipe:
all per-tick residuals retained, O(n_ticks)) and '1F1B' (per-tick remat:
live memory bounded to the scan carries) and reports XLA's memory analysis
for both — temp_size is the transient working set the schedule exists to
bound (reference framework/section_worker.cc:98-141 built 1F1B for exactly
this). Runs on the real TPU when available (single chip: pp=1, the remat
effect is per-micro-batch and does not need multiple stages) or on a virtual
CPU mesh (pp=4) under XLA_FLAGS=--xla_force_host_platform_device_count=8.

Usage: python tools/pipeline_memory.py [--layers N] [--hidden H] [--seq S]
                                       [--n-micro M]
Prints one JSON line: {"gpipe_temp_bytes", "1f1b_temp_bytes", "ratio", ...}.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure(mode, pp, layers, hidden, seq, n_micro, devices, vocab=8192):
    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import optimizer as popt
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.pipeline import PipelineTrainer
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden, num_layers=layers,
                    num_heads=8, max_seq_len=seq, dropout=0.0)
    model = GPTForCausalLM(cfg)
    pre, stages, post = model.pipeline_split(pp)
    opt = popt.AdamW(learning_rate=1e-4, parameters=model.parameters())
    mesh = build_mesh((pp,), ("pp",), devices=devices[:pp])
    tr = PipelineTrainer(pre, stages, post, opt, mesh=mesh, n_micro=n_micro,
                         schedule_mode=mode)
    rng = np.random.RandomState(0)
    mb = 2
    x = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                (n_micro, mb, seq)).astype(np.int32))
    y = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                (n_micro, mb, seq)).astype(np.int32))
    step = tr._build()
    lr = jnp.asarray(1e-4, jnp.float32)
    compiled = step.lower(tr.params, tr.opt_state, tr.frozen, lr, x,
                          y).compile()
    ma = compiled.memory_analysis()
    return {"temp_bytes": int(ma.temp_size_in_bytes),
            "arg_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--n-micro", type=int, default=8)
    args = ap.parse_args()

    import jax

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    if on_tpu:
        import bench

        bench.enable_tpu_compile_cache()
    devices = jax.devices()
    pp = args.layers if len(devices) >= args.layers else max(
        d for d in (4, 2, 1) if len(devices) >= d)
    if on_tpu and len(devices) == 1:
        pp = 1  # single chip: remat-per-tick still bounds the residuals

    res = {}
    for mode, key in (("F-then-B", "gpipe"), ("1F1B", "1f1b")):
        m = measure(mode, pp, args.layers, args.hidden, args.seq,
                    args.n_micro, devices)
        res[f"{key}_temp_bytes"] = m["temp_bytes"]
        res[f"{key}_arg_bytes"] = m["arg_bytes"]
    res["ratio"] = round(res["gpipe_temp_bytes"]
                         / max(res["1f1b_temp_bytes"], 1), 3)
    res["pp"] = pp
    res["platform"] = devices[0].platform
    res["config"] = {"layers": args.layers, "hidden": args.hidden,
                     "seq": args.seq, "n_micro": args.n_micro}
    print(json.dumps(res))


if __name__ == "__main__":
    main()
