#!/bin/bash
# One TPU session: everything we need from a healthy tunnel, sequentially in
# ONE process chain (never two TPU clients at once — see
# .claude/skills/verify/SKILL.md). Each step's JSON lands in /tmp.
set -u
cd "$(dirname "$0")/.."

echo "[tpu_session] bench (gpt2s + canary + resnet50/decode extras)..." >&2
# budget = worst-case sum of bench.py's internal watchdog windows
# (900 init+canary, 1200 probes, 900 headline, 1200 resnet, 1200 decode)
# + slack: the OUTER timeout must never fire while an inner window is
# still open, or a slow-but-healthy run is killed with rc=124 after its
# headline already landed
timeout 6000 python bench.py > /tmp/tpu_bench.json 2>/tmp/tpu_bench.log
echo "[tpu_session] bench exit=$? $(cat /tmp/tpu_bench.json 2>/dev/null)" >&2

# gate on the HEADLINE metric, not any '"metric"' — the wedge-canary line
# alone must not green-light five staged heavy compiles against a tunnel
# that wedged during the gpt2s compile. (The default run's decode extra
# intentionally duplicates the staged bf16 decode half below: the extra is
# the wedge-proof capture for the driver's standalone `python bench.py`,
# which records only that one process's lines.)
# ... and bail on any watchdog rescue ("watchdog_note"): a rescued run means
# the tunnel wedged mid-session — don't burn hours of staged compiles on it
if grep -q '"gpt2s_train_tokens_per_sec_per_chip"' /tmp/tpu_bench.json 2>/dev/null \
    && ! grep -q '"watchdog_note"' /tmp/tpu_bench.json 2>/dev/null; then
  echo "[tpu_session] pipeline memory on chip..." >&2
  timeout 1800 python tools/pipeline_memory.py \
    > /tmp/tpu_pipeline_memory.json 2>/tmp/tpu_pipeline_memory.log
  echo "[tpu_session] pipmem exit=$? $(cat /tmp/tpu_pipeline_memory.json 2>/dev/null)" >&2

  echo "[tpu_session] bert_dp config..." >&2
  timeout 1800 python bench.py --config bert_dp \
    > /tmp/tpu_bench_bert.json 2>/tmp/tpu_bench_bert.log
  echo "[tpu_session] bert exit=$? $(cat /tmp/tpu_bench_bert.json 2>/dev/null)" >&2

  echo "[tpu_session] decode config (bf16 + int8 + fp8 KV A/B)..." >&2
  # r5: three legs, inner watchdog windows ~900+1500+1500+1500 — the
  # outer budget must cover them all
  timeout 6500 python bench.py --config gpt2s_decode \
    > /tmp/tpu_bench_decode.json 2>/tmp/tpu_bench_decode.log
  echo "[tpu_session] decode exit=$? $(cat /tmp/tpu_bench_decode.json 2>/dev/null)" >&2

  echo "[tpu_session] gpt2m config..." >&2
  timeout 3500 python bench.py --config gpt2m \
    > /tmp/tpu_bench_gpt2m.json 2>/tmp/tpu_bench_gpt2m.log
  echo "[tpu_session] gpt2m exit=$? $(cat /tmp/tpu_bench_gpt2m.json 2>/dev/null)" >&2

  echo "[tpu_session] gpt2s_16k long-context config..." >&2
  timeout 3500 python bench.py --config gpt2s_16k \
    > /tmp/tpu_bench_16k.json 2>/tmp/tpu_bench_16k.log
  echo "[tpu_session] 16k exit=$? $(cat /tmp/tpu_bench_16k.json 2>/dev/null)" >&2

  echo "[tpu_session] continuous-batching serve config..." >&2
  # r5: the serve config runs TWO phases (drain + mixed-realism) with
  # inner watchdog windows of 2500 + 1500; the outer budget must cover
  # both plus init or a slow-but-healthy mixed phase dies at rc=124
  timeout 6000 python bench.py --config gpt2s_serve \
    > /tmp/tpu_bench_serve.json 2>/tmp/tpu_bench_serve.log
  echo "[tpu_session] serve exit=$? $(cat /tmp/tpu_bench_serve.json 2>/dev/null)" >&2

  echo "[tpu_session] ppyolo config..." >&2
  # two fresh heavy compiles (train step + to_static infer+NMS): give it the
  # same worst-case budget as the main bench so timeout never kills mid-compile
  timeout 3500 python bench.py --config ppyolo \
    > /tmp/tpu_bench_ppyolo.json 2>/tmp/tpu_bench_ppyolo.log
  echo "[tpu_session] ppyolo exit=$? $(cat /tmp/tpu_bench_ppyolo.json 2>/dev/null)" >&2
fi
echo "[tpu_session] done" >&2
