"""AOT warm CLI: pre-populate a persistent compile cache before traffic.

    python tools/aot_warm.py --cache-dir /var/cache/paddle_tpu_aot --model gpt
    python tools/aot_warm.py --cache-dir /var/cache/paddle_tpu_aot --serving
    python tools/aot_warm.py --all --json        # cache dir from FLAGS env

Each target compiles its site's executables from SHAPE SPECS only — no
real batches, nothing executed — through the persistent AOT cache
(paddle_tpu/framework/aot.py): ``SpmdTrainer.aot_build`` for the bundled
models' train steps, ``ServingEngine.warmup`` for the serving program
family. A later process (bench.py, a serving deploy) started with the
same ``FLAGS_jit_cache_dir`` then deserializes executables instead of
recompiling — the serve-deploy recipe in docs/AOT.md.

--json emits the tools/graph_lint.py report schema ({"tool", "passes",
"targets": {name: {"name", "counts", "findings"}}, "totals"}) so CI reads
all the audit tools through one loader. Exit code 1 when any site failed
to SERIALIZE an executable (aot_store_total{event="error"} moved — the
compile still ran, but the cache gained nothing, which a warm-start
deploy must treat as a failure) or when no cache dir is configured.

Shapes are the CPU-shrunk tools/metrics_dump.py dims; a production warm
run would import its real model config and call the same three APIs
(aot_build / warmup / Program.aot_compile) directly.
"""
import argparse
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MODEL_TARGETS = ("gpt", "bert", "ernie")

# deliberately tiny: this tool demonstrates/pins the warm recipe; a
# production warm run imports its real model config instead
_DIMS = dict(vocab_size=128, hidden_size=32, num_layers=1, num_heads=2,
             dropout=0.0)
_B, _S = 2, 16


def warm_train(name):
    """AOT-build one bundled model's train step from batch specs; returns
    where the executable came from (disk|fresh)."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.models import (BertConfig, BertForPretraining,
                                   BertPretrainLoss, ErnieConfig,
                                   ErnieForPretraining, ErniePretrainLoss,
                                   GPTConfig, GPTForCausalLM,
                                   GPTPretrainLoss)

    paddle.seed(0)
    ids = ((_B, _S), "int32")
    if name == "gpt":
        model = GPTForCausalLM(GPTConfig(max_seq_len=64, **_DIMS))
        loss, specs = GPTPretrainLoss(), [ids, ids]
    elif name == "bert":
        model = BertForPretraining(BertConfig(max_position=64,
                                              intermediate_size=256, **_DIMS))
        loss, specs = BertPretrainLoss(), [ids, ids, ids]
    elif name == "ernie":
        model = ErnieForPretraining(ErnieConfig(max_position=64,
                                                intermediate_size=256,
                                                **_DIMS))
        loss, specs = ErniePretrainLoss(), [ids, ids, ids]
    else:
        raise ValueError(f"unknown model {name!r}; choose from "
                         f"{MODEL_TARGETS}")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
    trainer = SpmdTrainer(model, opt, loss_fn=loss, mesh=mesh)
    return {"train_step": trainer.aot_build(specs)}


def warm_serving():
    """Warm the ServingEngine program family from shape specs."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig(max_seq_len=64, **_DIMS))
    model.eval()
    eng = ServingEngine(model, max_batch=2)
    return eng.warmup()


def _store_counts():
    """(ok, error) totals of aot_store_total across all sites."""
    from paddle_tpu import monitor

    ok = err = 0
    metric = monitor.default_registry().get("aot_store_total")
    if metric is not None:
        for s in metric.series():
            if s.labels.get("event") == "error":
                err += int(s.value)
            elif s.labels.get("event") == "ok":
                ok += int(s.value)
    return ok, err


def run_target(name):
    """Warm one target; returns findings in the graph_lint format."""
    ok0, err0 = _store_counts()
    findings = []
    try:
        detail = warm_serving() if name == "serving" else warm_train(name)
    except Exception as e:
        findings.append({
            "pass": "aot-warm", "severity": "error",
            "message": f"warmup raised {type(e).__name__}: {e}",
            "where": name})
        return findings
    ok1, err1 = _store_counts()
    if err1 > err0:
        findings.append({
            "pass": "aot-serialize", "severity": "error",
            "message": f"{err1 - err0} executable(s) failed to serialize "
                       "into the cache (compiled fine, but a warm-start "
                       "deploy would recompile them)", "where": name})
    for prog, got in sorted(detail.items()):
        findings.append({"pass": "aot-warm", "severity": "info",
                         "message": f"{prog}: {got}", "where": name})
    findings.append({"pass": "aot-warm", "severity": "info",
                     "message": f"cache entries written: {ok1 - ok0}",
                     "where": name})
    return findings


def build_report(targets):
    """The tools/graph_lint.py-schema report over the requested targets."""
    from paddle_tpu.framework import aot

    report = {"tool": "aot_warm", "passes": ["aot-warm", "aot-serialize"],
              "targets": {}, "totals": {"error": 0, "warning": 0, "info": 0}}
    for name in targets:
        findings = []
        if not aot.enabled():
            findings.append({
                "pass": "aot-warm", "severity": "error",
                "message": "FLAGS_jit_cache_dir is not set — nothing to "
                           "populate (pass --cache-dir or export "
                           "FLAGS_jit_cache_dir)", "where": name})
        else:
            findings.extend(run_target(name))
        counts = {"error": 0, "warning": 0, "info": 0}
        for f in findings:
            counts[f["severity"]] += 1
        report["targets"][name] = {"name": name, "counts": counts,
                                   "findings": findings}
        for sev, n in counts.items():
            report["totals"][sev] += n
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", choices=MODEL_TARGETS, action="append",
                    default=[], help="warm one bundled model's train step")
    ap.add_argument("--serving", action="store_true",
                    help="warm the ServingEngine program family")
    ap.add_argument("--all", action="store_true",
                    help="all models + the serving family")
    ap.add_argument("--cache-dir", default=None,
                    help="sets FLAGS_jit_cache_dir for this run")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the graph_lint-schema machine report")
    args = ap.parse_args(argv)

    if args.cache_dir:
        from paddle_tpu import flags

        flags.set_flags({"jit_cache_dir": args.cache_dir})

    targets = list(args.model)
    if args.serving:
        targets.append("serving")
    if args.all:
        targets = list(MODEL_TARGETS) + ["serving"]
    if not targets:
        ap.error("pick a target: --model NAME, --serving or --all")

    report = build_report(targets)
    if args.as_json:
        print(json.dumps(report, indent=1))
    else:
        for name, t in report["targets"].items():
            c = t["counts"]
            print(f"{name}: {c['error']} error(s), {c['info']} info")
            for f in t["findings"]:
                print(f"  [{f['severity']}] {f['pass']}: {f['message']}")
    return 1 if report["totals"]["error"] else 0


if __name__ == "__main__":
    sys.exit(main())
