"""Profile a bench.py GPT train step (gpt2s — the BENCH headline config —
or gpt2m via --model) on the current backend and print ONE JSON line with
the numbers a tuning session needs:

- XLA cost analysis of the compiled step: model FLOPs, bytes accessed (HBM
  traffic), and the flops/byte arithmetic intensity — tells whether the step
  is MXU-bound or HBM-bound.
- XLA memory analysis: peak temp allocation + argument/output footprint —
  tells how much batch headroom remains before OOM.
- Measured step time + achieved TFLOP/s vs the analysis FLOPs.
- Optional: --trace DIR dumps a jax.profiler trace for offline tensorboard.

The model/trainer/data come from bench._gpt2s_setup, so the profiled program
IS the benchmarked one, and the step is compiled exactly ONCE (AOT
lower+compile; the timed loop runs the same compiled executable).

Run on the real TPU during a healthy window (tools/tpu_session.sh chains the
bench first; run this after). CPU runs shrink the model like bench.py does.

Usage: python tools/profile_gpt.py [--batch B] [--seq S] [--steps N]
                                   [--trace DIR] [--model gpt2s|gpt2m]
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--trace", default=None,
                    help="dump a jax.profiler trace to this directory")
    ap.add_argument("--model", default="gpt2s", choices=["gpt2s", "gpt2m"],
                    help="config family (matches bench.py --config)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import bench

    if jax.devices()[0].platform in ("tpu", "axon"):
        bench.enable_tpu_compile_cache()
    import paddle_tpu as paddle
    from paddle_tpu.core.generator import default_generator

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    # defaults match bench.py's per-config TPU batches (gpt2s probes 16/24;
    # gpt2m runs 8) so the profiled program is the benchmarked one
    batch = args.batch or ((8 if args.model == "gpt2m" else 16)
                           if on_tpu else 2)
    seq = args.seq if on_tpu else min(args.seq, 128)
    steps = args.steps if on_tpu else 2

    cfg_fn = bench._gpt2m_cfg if args.model == "gpt2m" else None
    on_tpu, cfg, trainer, ids, labels = bench._gpt2s_setup(batch, seq, cfg_fn)
    batch_arrays = (ids._data, labels._data)
    lr = jnp.asarray(trainer.optimizer.get_lr(), dtype=jnp.float32)
    key = default_generator().fold_in(0)

    with paddle.amp.auto_cast(True, dtype="bfloat16"):
        # ONE compile: AOT lower+compile of the exact trainer step; the timed
        # loop below runs this same executable (no second jit-cache compile)
        step_fn = trainer._build(list(batch_arrays))
        lowered = step_fn.lower(trainer.params, trainer.opt_state,
                                trainer.buffers, lr, key, *batch_arrays)
        compiled = lowered.compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    mem = compiled.memory_analysis()

    # warmup run (first dispatch), rebinding donated params/opt_state
    params, opt_state, buffers = trainer.params, trainer.opt_state, \
        trainer.buffers
    loss, params, opt_state, buffers = compiled(
        params, opt_state, buffers, lr, key, *batch_arrays)
    np.asarray(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, params, opt_state, buffers = compiled(
            params, opt_state, buffers, lr, key, *batch_arrays)
    np.asarray(loss)
    dt = (time.perf_counter() - t0) / steps

    if args.trace:
        with jax.profiler.trace(args.trace):
            for _ in range(3):
                loss, params, opt_state, buffers = compiled(
                    params, opt_state, buffers, lr, key, *batch_arrays)
            np.asarray(loss)

    flops = float(cost.get("flops", 0.0)) if cost else 0.0
    bytes_acc = float(cost.get("bytes accessed", 0.0)) if cost else 0.0
    line = {
        "config": {"model": args.model, "batch": batch, "seq": seq,
                   "platform": jax.devices()[0].platform},
        "step_time_s": round(dt, 4),
        "tokens_per_sec": round(batch * seq / dt, 1),
        "xla_flops_per_step": flops,
        "xla_bytes_accessed_per_step": bytes_acc,
        "arithmetic_intensity_flops_per_byte":
            round(flops / bytes_acc, 2) if bytes_acc else None,
        "achieved_tflops_per_sec": round(flops / dt / 1e12, 2) if flops else None,
    }
    if mem is not None:
        for attr in ("temp_size_in_bytes", "argument_size_in_bytes",
                     "output_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(mem, attr, None)
            if v is not None:
                line.setdefault("memory", {})[attr] = int(v)
    if args.trace:
        line["trace_dir"] = args.trace
    print(json.dumps(line))


if __name__ == "__main__":
    main()
