"""A/B loss-parity gate CLI: run lockstep trainer pairs under reference vs
candidate flag-sets and fail loudly (exit 1, naming the diverging step and
stat) when a pair leaves its declared tolerance band.

    python tools/parity_check.py --ab check_nan_inf        # PR 4 guard: exact
    python tools/parity_check.py --ab use_bfloat16         # flag A/B: exact
    python tools/parity_check.py --ab amp_bf16             # bf16 amp: banded
    python tools/parity_check.py --ab quantized_allreduce  # int8 reduce: banded
    python tools/parity_check.py --ab shard_weight_update  # ZeRO-ish: EXACT
    python tools/parity_check.py --ab multi_lora           # pooled vs dedicated
    python tools/parity_check.py --ab paged_kv             # armed vs dense
    python tools/parity_check.py --ab reshard              # dp8 ckpt -> dp4/dp2xmp2
    python tools/parity_check.py --all
    python tools/parity_check.py --perturb-lr 5 --json     # negative control
    python tools/parity_check.py --ab quantized_allreduce --perturb-lr 6
    # ^ runs the target AND its in-band negative control (must exit 1)

The harness is paddle_tpu/testing/parity.py (docs/OBSERVABILITY.md
"Numerics telescope"): both sides train the SAME seeded tiny GPT over
IDENTICAL batches with the numerics telescope armed, and every step's
loss + per-layer grad stats are compared within each target's DECLARED
tolerance. ``--perturb-lr F`` runs the harness's own negative control — a
candidate whose learning rate is scaled by F must diverge, and the run
exits 1 naming where; CI uses it to prove the gate can actually fail.

This IS the acceptance gate ROADMAP item 2 named: `quantized_allreduce`
runs FLAGS_quantized_allreduce as the candidate inside its declared loss
band, `shard_weight_update` pins FLAGS_shard_weight_update EXACT, and
`--perturb-lr F` combined with `--ab NAME` re-runs each named target with
the candidate's lr scaled by F under the SAME band — which must diverge
(exit 1), proving the band is a gate and not a rubber stamp.

Report format: the tools/graph_lint.py schema ({"tool", "passes",
"targets": {name: {"name", "counts", "findings", "report"}}, "totals"})
so CI reads every audit tool through one loader.
"""
import argparse
import functools
import json
import os
import sys

# 8 host devices BEFORE jax loads — the MPMD pipeline targets need a
# real 2-device pp mesh (same forcing as tests/conftest.py)
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_trainer(lr=1e-2, amp_dtype=None):
    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainLoss)

    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=2, max_seq_len=32, dropout=0.0)
    model = GPTForCausalLM(cfg)
    loss = GPTPretrainLoss()
    opt = paddle.optimizer.AdamW(learning_rate=lr,
                                 parameters=model.parameters())
    mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
    return SpmdTrainer(model, opt, loss_fn=loss, mesh=mesh,
                       amp_dtype=amp_dtype)


def _build_pipeline_trainer(lr=1e-2, compress=None):
    """2-stage pipeline twin of _build_trainer for the MPMD A/Bs: the
    armed/disarmed sides build the SAME seeded split model; only the
    scheduler differs. compress=8 quantizes the activation edges
    (meaningful only under FLAGS_mpmd — run_lockstep arms it via
    candidate_flags before build())."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.pipeline import PipelineTrainer
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=32, dropout=0.0)
    model = GPTForCausalLM(cfg)
    pre, stages, post = model.pipeline_split(2)
    opt = paddle.optimizer.AdamW(learning_rate=lr,
                                 parameters=model.parameters())
    mesh = build_mesh((2,), ("pp",), devices=jax.devices()[:2])
    kw = {"compress": compress} if compress is not None else {}
    return PipelineTrainer(pre, stages, post, opt, mesh=mesh, n_micro=2,
                           schedule_mode="1F1B", **kw)


def _batches(steps, batch=2, seq=12):
    import numpy as np

    rng = np.random.RandomState(0)
    return [(rng.randint(0, 64, (batch, seq)).astype(np.int32),
             rng.randint(0, 64, (batch, seq)).astype(np.int32))
            for _ in range(steps)]


#: each target declares ITS tolerance — exact for program-identical or
#: bit-exact-by-contract A/Bs, a written band for genuinely lossy ones
AB_TARGETS = {
    # FLAGS_use_bfloat16 keys the AOT cache today and grows real lowering
    # the day ROADMAP item 3 widens MXU coverage — the A/B pins EXACT
    # parity now and becomes the alarm that rings then
    "use_bfloat16": dict(
        reference_flags={"use_bfloat16": False},
        candidate_flags={"use_bfloat16": True},
        loss_rtol=0.0, loss_atol=0.0, stat_rtol=0.0, stat_atol=0.0),
    # the PR 4 guard rebuilds the step with the fused finiteness verdict
    # + where-selects; on finite data its contract is BIT-exact
    "check_nan_inf": dict(
        reference_flags={},
        candidate_flags={"check_nan_inf": True},
        loss_rtol=0.0, loss_atol=0.0, stat_rtol=0.0, stat_atol=0.0),
    # bf16 autocast genuinely changes the numbers; the declared band is
    # the acceptance envelope (one part in 2^8 mantissa, headroom for
    # accumulation) — the shape every lossy candidate (ROADMAP item 2's
    # quantized all-reduce) will reuse
    "amp_bf16": dict(
        candidate_build=functools.partial(_build_trainer,
                                          amp_dtype="bfloat16"),
        reference_flags={}, candidate_flags={},
        loss_rtol=0.08, loss_atol=0.05, stat_rtol=0.6, stat_atol=0.1),
    # ROADMAP item 2's quantized all-reduce (distributed/compress.py):
    # int8 block-max quantization with stochastic rounding + error
    # feedback is a genuinely lossy reduce — the declared band matches
    # amp_bf16's (per-element error ~blockmax/127 ≈ bf16's 2^-8
    # mantissa step, residual feedback keeping the drift bounded). THIS
    # is the ship gate the flag must pass (docs/DISTRIBUTED.md)
    "quantized_allreduce": dict(
        reference_flags={},
        candidate_flags={"quantized_allreduce": True},
        loss_rtol=0.08, loss_atol=0.05, stat_rtol=0.6, stat_atol=0.1),
    # arXiv:2004.13336 update sharding re-distributes WHERE the
    # optimizer update is computed, not WHAT it computes: elementwise
    # rules on 1/dp shards are the same arithmetic — verified EXACT
    "shard_weight_update": dict(
        reference_flags={},
        candidate_flags={"shard_weight_update": True},
        loss_rtol=0.0, loss_atol=0.0, stat_rtol=0.0, stat_atol=0.0),
    # ISSUE 11 async dispatch changes NOTHING the device computes —
    # the compiled step is byte-identical; only the host's verdict
    # fetches move to window boundaries. Deferred fetches must not
    # change a single bit of the loss trajectory: EXACT
    "async_dispatch": dict(
        reference_flags={"check_nan_inf": True},
        candidate_flags={"check_nan_inf": True, "async_dispatch": True,
                         "async_window": 4},
        loss_rtol=0.0, loss_atol=0.0, stat_rtol=0.0, stat_atol=0.0),
    # ISSUE 11 TPP registry (ops/tpp.py): the ported fused-MLP /
    # ln->matmul kernels accumulate in fp32 with a blocked summation
    # order and a reference-math backward — a genuinely (minutely)
    # different float program. The band is tight: per-step loss within
    # 1e-3 relative, per-layer grad stats within 5%
    "tpp_kernels": dict(
        reference_flags={},
        candidate_flags={"tpp_kernels": True},
        loss_rtol=1e-3, loss_atol=1e-4, stat_rtol=0.05, stat_atol=1e-3),
    # ISSUE 15 MPMD runtime (distributed/stage.py): the same 2-stage
    # split model trained by the monolithic scanned schedule (reference)
    # vs per-stage programs + typed edges (candidate). The arithmetic is
    # the same matmuls, but grad accumulation is restructured (per-micro
    # vjp sums vs autodiff-of-scan) — a minutely different float
    # program, pinned in the tpp_kernels-class band
    "mpmd_pipeline": dict(
        reference_build=_build_pipeline_trainer,
        reference_flags={},
        candidate_flags={"mpmd": True},
        loss_rtol=1e-3, loss_atol=1e-4, stat_rtol=0.05, stat_atol=1e-3),
    # armed-vs-armed with the activation edges quantized (compress=8,
    # int8 row codec): genuinely lossy transfers — the declared band is
    # the quantized_allreduce envelope (per-element error ~rowmax/127)
    "mpmd_quantized_edge": dict(
        reference_build=_build_pipeline_trainer,
        candidate_build=functools.partial(_build_pipeline_trainer,
                                          compress=8),
        reference_flags={"mpmd": True},
        candidate_flags={"mpmd": True},
        loss_rtol=0.08, loss_atol=0.05, stat_rtol=0.6, stat_atol=0.1),
}


def _finding(name, severity, message, where=""):
    return {"pass": name, "severity": severity, "message": message,
            "where": where}


def _serving_fixture():
    """Seeded tiny GPT + two exported LoRA adapters shared by the
    serving-side parity targets (multi_lora / paged_kv)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.incubate.lora import apply_lora, export_lora
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=64, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()

    def _adapter(seed):
        m2 = GPTForCausalLM(cfg)
        m2.load_dict(model.state_dict())
        apply_lora(m2, r=4, alpha=8)
        rng = np.random.RandomState(seed)
        for n_, p_ in m2.named_parameters():
            if "lora_B" in n_:
                p_.set_value(paddle.to_tensor(
                    rng.normal(0, 0.3, p_.shape).astype(np.float32)))
        return export_lora(m2)

    return model, {"alpha": _adapter(1), "beta": _adapter(2)}


def _drain(eng, jobs):
    """Submit [(prompt, kwargs)] jobs and return their outputs as
    int-token tuples, in job order."""
    rids = [eng.submit(list(p), **kw) for p, kw in jobs]
    res = eng.run_until_complete()
    return [tuple(int(t) for t in res[r].output_ids) for r in rids]


def run_multi_lora(steps=4):
    """ONE pooled multi-adapter engine vs a dedicated single-adapter
    engine per adapter (same batched-LoRA math, adapter alone in its
    pool): every session — greedy and seeded-sampled, base and
    adapter-routed — must be BYTE-identical. The acceptance bar for
    FLAGS_paged_kv batched multi-LoRA decode (docs/SERVING.md)."""
    import paddle_tpu as paddle
    from paddle_tpu import flags
    from paddle_tpu.inference.serving import ServingEngine

    old = {"paged_kv": flags.get_flag("paged_kv")}
    paddle.set_flags({"paged_kv": True})
    try:
        model, adapters = _serving_fixture()
        prompts = [[3, 14, 15, 9, 2, 6], [7, 1, 19], [21, 22, 23, 24]]
        n_new = 4 + steps

        def _jobs(adapter):
            out = []
            for i, p in enumerate(prompts):
                kw = dict(max_new_tokens=n_new, adapter=adapter)
                if i == 2:   # one seeded-sampled session per adapter
                    kw.update(temperature=0.8, top_k=16, seed=11)
                out.append((p, kw))
            return out

        pooled = ServingEngine(model, max_batch=4, max_adapters=4)
        for name, exp in adapters.items():
            pooled.load_adapter(name, exp)
        pooled_out = {name: _drain(pooled, _jobs(name))
                      for name in list(adapters) + [None]}

        findings, sessions = [], 0
        for name in list(adapters) + [None]:
            dedicated = ServingEngine(model, max_batch=4,
                                      max_adapters=4)
            if name is not None:
                dedicated.load_adapter(name, adapters[name])
            ded_out = _drain(dedicated, _jobs(name))
            for i, (a, b) in enumerate(zip(pooled_out[name], ded_out)):
                sessions += 1
                if a != b:
                    findings.append(_finding(
                        "multi_lora", "error",
                        f"adapter={name!r} session {i}: pooled engine "
                        f"diverged from its dedicated twin — pooled="
                        f"{list(a)} dedicated={list(b)}",
                        where=f"adapter={name}/session{i}"))
        if not findings:
            findings.append(_finding(
                "multi_lora", "info",
                f"{sessions} sessions ({len(adapters)} adapters + base, "
                "greedy + seeded-sampled) byte-identical between the "
                "pooled engine and dedicated per-adapter engines"))
        report = {"sessions": sessions, "adapters": sorted(adapters),
                  "diverged": any(f["severity"] == "error"
                                  for f in findings)}
        return report, findings
    finally:
        paddle.set_flags(old)


def run_paged_kv(steps=4):
    """FLAGS_paged_kv armed vs disarmed: the paged engine's dense decode
    must be BYTE-identical to the contiguous-cache engine (junk/null
    page columns are causally masked — exact by contract). Plus the int8
    cold-page band: a prefix block compressed cold and decompressed on
    touch must sit within the deterministic row codec's quantization
    step (|err| <= row absmax / 127)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import flags
    from paddle_tpu.inference.serving import ServingEngine

    model, _ = _serving_fixture()
    prompts = [[3, 14, 15, 9, 2, 6], [7, 1, 19], [21, 22, 23, 24]]
    n_new = 4 + steps

    def _jobs():
        out = []
        for i, p in enumerate(prompts):
            kw = dict(max_new_tokens=n_new)
            if i == 2:
                kw.update(temperature=0.8, top_k=16, seed=11)
            out.append((p, kw))
        return out

    old = {"paged_kv": flags.get_flag("paged_kv")}
    findings = []
    try:
        paddle.set_flags({"paged_kv": False})
        dense_out = _drain(ServingEngine(model, max_batch=4), _jobs())
        paddle.set_flags({"paged_kv": True})
        paged_out = _drain(ServingEngine(model, max_batch=4), _jobs())
        for i, (a, b) in enumerate(zip(dense_out, paged_out)):
            if a != b:
                findings.append(_finding(
                    "paged_kv", "error",
                    f"session {i}: armed paged engine diverged from the "
                    f"disarmed dense engine — dense={list(a)} "
                    f"paged={list(b)}", where=f"session{i}"))

        # int8 cold band: hot frame -> sweep cold -> touch decompress
        eng = ServingEngine(model, max_batch=2, page_cold_steps=1)
        pool = eng._pool
        pid = eng.register_prefix(list(range(2, 34)))   # 2 full blocks
        frames = pool.prefix_frames(pid)
        hot_k = np.asarray(pool.kp[np.array(frames)])
        for _ in range(4):
            pool.sweep()
        if pool.stats()["cold_pages"] == 0:
            findings.append(_finding(
                "paged_kv", "error",
                "prefix blocks never compressed cold under "
                "page_cold_steps=1 idle sweeps", where="cold"))
        else:
            frames2 = pool.prefix_frames(pid)   # touch: decompress
            back_k = np.asarray(pool.kp[np.array(frames2)])
            err = np.abs(back_k.astype(np.float64)
                         - hot_k.astype(np.float64))
            # per-row band of the row codec: absmax/127 (+ float eps)
            band = np.abs(hot_k).max(axis=-1, keepdims=True) / 127.0 \
                + 1e-6
            worst = float((err - band).max())
            if worst > 0:
                findings.append(_finding(
                    "paged_kv", "error",
                    f"cold int8 round-trip left the row-codec band by "
                    f"{worst:.3g}", where="cold"))
            else:
                findings.append(_finding(
                    "paged_kv", "info",
                    f"{len(dense_out)} sessions byte-identical armed vs "
                    f"disarmed; int8 cold round-trip within the "
                    f"rowmax/127 band (max err {float(err.max()):.3g})"))
        report = {"sessions": len(dense_out),
                  "diverged": any(f["severity"] == "error"
                                  for f in findings)}
        return report, findings
    finally:
        paddle.set_flags(old)


#: serving-side parity targets — engine-vs-engine token comparisons, not
#: trainer lockstep A/Bs; they run through their own runners and skip
#: the --perturb-lr trainer companion machinery
SERVING_TARGETS = {"multi_lora": run_multi_lora, "paged_kv": run_paged_kv}


def _reshard_counts():
    """{action: value} of checkpoint_reshard_total right now (0-dict when
    the family hasn't been created yet)."""
    from paddle_tpu import monitor

    out = {}
    for m in monitor.snapshot()["metrics"]:
        if m["name"] != "checkpoint_reshard_total":
            continue
        for s in m["series"]:
            out[s["labels"]["action"]] = s["value"]
    return out


def run_reshard(steps=4, perturb_lr=None):
    """Topology-aware checkpoint reshard A/B (the FLAGS_elastic
    tentpole, docs/DISTRIBUTED.md "Elastic training"): a dp8 trainer
    with FLAGS_shard_weight_update ([dp, shard] moments) checkpoints at
    the midpoint, and the state_dict — carrying its ``shard_specs``
    topology leaf — restores onto a FRESH dp4 trainer AND a FRESH
    dp2x2 (dp x mp factorization of the same 4 devices) trainer. Each
    continuation must track the uninterrupted dp8 twin within the
    declared band (loss_rtol=1e-3, loss_atol=1e-4: re-layout changes
    psum order, the only float freedom — the moments themselves re-lay
    bit-exactly, pinned by tests/test_elastic_gate.py). The restore is
    also required to ATTRIBUTE itself: checkpoint_reshard_total
    {action=moment_reshard} must move, proving the topology-aware path
    engaged rather than a lucky same-layout load.

    ``perturb_lr`` scales the CONTINUATION trainers' lr — the
    ``--perturb-lr`` companion negative control, which must leave the
    band (exit 1), proving the band is a gate and not a rubber stamp."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import flags
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainLoss)

    name = "reshard" if perturb_lr is None else "reshard+perturb_lr"
    LOSS_RTOL, LOSS_ATOL = 1e-3, 1e-4
    if steps < 2:
        raise ValueError("the reshard A/B needs >= 2 steps (train, "
                         "checkpoint at the midpoint, continue)")
    split = steps // 2

    def _build(shape, axes, ndev, lr=1e-2):
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_heads=2, max_seq_len=32, dropout=0.0)
        model = GPTForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=lr,
                                     parameters=model.parameters())
        return SpmdTrainer(model, opt, loss_fn=GPTPretrainLoss(),
                           mesh=build_mesh(shape, axes,
                                           devices=jax.devices()[:ndev]))

    old = {k: flags.get_flag(k)
           for k in ("elastic", "shard_weight_update")}
    paddle.set_flags({"elastic": True, "shard_weight_update": True})
    try:
        data = _batches(steps, batch=8)   # 8 divides dp8 / dp4 / dp2

        def _loss(tr, x, y):
            return float(np.asarray(tr.train_step(x, y)._data))

        twin = _build((8,), ("dp",), 8)
        twin_losses = [_loss(twin, x, y) for x, y in data]

        primary = _build((8,), ("dp",), 8)
        head = [_loss(primary, x, y) for x, y in data[:split]]

        lr = 1e-2 * (perturb_lr if perturb_lr is not None else 1.0)
        findings, worst = [], 0.0
        for label, shape, axes, ndev in (
                ("dp4", (4,), ("dp",), 4),
                ("dp2xmp2", (2, 2), ("dp", "mp"), 4)):
            before = _reshard_counts()
            cont = _build(shape, axes, ndev, lr=lr)
            # a fresh gather per continuation: restore re-lays the
            # [dp, shard] moments in place of the writer's layout
            cont.set_state_dict(primary.state_dict())
            relaid = _reshard_counts().get("moment_reshard", 0) \
                - before.get("moment_reshard", 0)
            if relaid <= 0:
                findings.append(_finding(
                    name, "error",
                    f"{label}: restore onto a different factorization "
                    "never re-laid a moment (checkpoint_reshard_total"
                    "{action=moment_reshard} did not move)",
                    where=label))
                continue
            losses = head + [_loss(cont, x, y) for x, y in data[split:]]
            for i, (a, b) in enumerate(zip(losses, twin_losses)):
                diff = abs(a - b)
                worst = max(worst, diff)
                if diff > LOSS_ATOL + LOSS_RTOL * abs(b):
                    findings.append(_finding(
                        name, "error",
                        f"{label}: continuation left the declared band "
                        f"at step {i}: twin={b:.6g} resumed={a:.6g} "
                        f"(|diff|={diff:.3g}, loss_rtol={LOSS_RTOL} "
                        f"loss_atol={LOSS_ATOL})",
                        where=f"{label}/step{i}"))
                    break
        if not findings:
            findings.append(_finding(
                name, "info",
                f"dp8 checkpoint at step {split} continued on dp4 and "
                f"dp2xmp2 within the declared band (max |loss diff| "
                f"{worst:.3g}; moments re-laid, attributed via "
                "checkpoint_reshard_total)"))
        report = {"steps": steps, "split": split,
                  "tolerances": {"loss_rtol": LOSS_RTOL,
                                 "loss_atol": LOSS_ATOL},
                  "max_abs_loss_diff": worst,
                  "reshard_actions": _reshard_counts(),
                  "diverged": any(f["severity"] == "error"
                                  for f in findings)}
        return report, findings
    finally:
        paddle.set_flags(old)


#: self-running trainer-side targets that manage their own twin AND
#: their own --perturb-lr companion (the factor reaches them as a
#: kwarg instead of riding the lockstep harness)
CUSTOM_TARGETS = {"reshard": run_reshard}


def run_target(name, steps=4, perturb_lr=None):
    """Run one A/B; returns (report, findings). `perturb_lr` builds a
    negative-control variant instead (candidate lr scaled — MUST
    diverge): standalone (`name == "perturb_lr"`) under zero tolerance,
    or — when `name` is a real target — under THAT target's own flags
    and declared band, proving the band itself can fail (the CI lane's
    companion run for the banded quantized_allreduce gate)."""
    from paddle_tpu.testing import parity

    if name in CUSTOM_TARGETS:
        return CUSTOM_TARGETS[name](steps=steps, perturb_lr=perturb_lr)
    if perturb_lr is None and name in SERVING_TARGETS:
        return SERVING_TARGETS[name](steps=steps)
    if perturb_lr is not None:
        if name in AB_TARGETS:
            spec = dict(AB_TARGETS[name])
            base = (spec.get("candidate_build")
                    or spec.get("reference_build", _build_trainer))
            base_fn = base.func if isinstance(base, functools.partial) \
                else base
            kw = dict(getattr(base, "keywords", None) or {})
            kw["lr"] = 1e-2 * perturb_lr
            spec["candidate_build"] = functools.partial(base_fn, **kw)
        else:
            spec = dict(
                candidate_build=functools.partial(_build_trainer,
                                                  lr=1e-2 * perturb_lr),
                reference_flags={}, candidate_flags={},
                loss_rtol=0.0, loss_atol=0.0, stat_rtol=0.0,
                stat_atol=0.0)
    else:
        spec = AB_TARGETS[name]
    report = parity.run_parity(
        spec.get("reference_build", _build_trainer), _batches(steps),
        build_candidate=spec.get("candidate_build"),
        reference_flags=spec["reference_flags"],
        candidate_flags=spec["candidate_flags"],
        loss_rtol=spec["loss_rtol"], loss_atol=spec["loss_atol"],
        stat_rtol=spec["stat_rtol"], stat_atol=spec["stat_atol"])
    findings = []
    if report["diverged"]:
        d = report["first_divergence"]
        where = d["stat"] + (f"[{d['layer']}]" if d.get("layer") else "")
        findings.append(_finding(
            name, "error",
            f"A/B diverged at step {d['step']} on {where}: "
            f"reference={d['reference']:.6g} "
            f"candidate={d['candidate']:.6g} "
            f"(|diff|={d['abs_diff']:.3g}, tolerances "
            f"{report['tolerances']})", where=where))
    else:
        findings.append(_finding(
            name, "info",
            f"{report['steps']} lockstep steps within declared "
            f"tolerance (max |loss diff| "
            f"{report['max_abs_loss_diff']:.3g})"))
    return report, findings


def build_report(targets, steps=4, perturb_lr=None):
    report = {"tool": "parity_check", "passes": list(targets), "targets": {},
              "totals": {"error": 0, "warning": 0, "info": 0}}
    jobs = [(t, t, None) for t in targets]
    if perturb_lr is not None:
        if targets:
            # negative control per named target, in ITS band — MUST
            # diverge (exit 1), proving each new gate can actually fail
            # (trainer A/Bs only: the serving targets have no lr to turn)
            for t in targets:
                if t in SERVING_TARGETS:
                    continue
                jobs.append((f"{t}+perturb_lr", t, perturb_lr))
                report["passes"].append(f"{t}+perturb_lr")
        else:
            jobs.append(("perturb_lr", "perturb_lr", perturb_lr))
            report["passes"].append("perturb_lr")
    for label, name, factor in jobs:
        try:
            ab_report, findings = run_target(name, steps=steps,
                                             perturb_lr=factor)
        except Exception as e:   # a crashed A/B is a failed gate
            ab_report = None
            findings = [_finding(label, "error",
                                 f"A/B crashed: {type(e).__name__}: {e}")]
        counts = {"error": 0, "warning": 0, "info": 0}
        for f in findings:
            f["pass"] = label
            counts[f["severity"]] += 1
        report["targets"][label] = {"name": label, "counts": counts,
                                    "findings": findings,
                                    "report": ab_report}
        for sev, n in counts.items():
            report["totals"][sev] += n
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ab", action="append",
                    choices=(sorted(AB_TARGETS) + sorted(SERVING_TARGETS)
                             + sorted(CUSTOM_TARGETS)),
                    default=[], help="run one named A/B target "
                    "(repeatable)")
    ap.add_argument("--all", action="store_true",
                    help="run every named A/B target")
    ap.add_argument("--perturb-lr", type=float, default=None,
                    dest="perturb_lr", metavar="F",
                    help="negative control: candidate lr scaled by F "
                         "under zero tolerance — MUST diverge (exit 1 "
                         "naming the step/stat); proves the gate can "
                         "fail")
    ap.add_argument("--steps", type=int, default=4,
                    help="lockstep steps per side (default 4)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the graph_lint-schema machine report")
    args = ap.parse_args(argv)

    targets = (sorted(AB_TARGETS) + sorted(SERVING_TARGETS)
               + sorted(CUSTOM_TARGETS)) if args.all else list(args.ab)
    if not targets and args.perturb_lr is None:
        ap.error("pick a target: --ab NAME, --all, or --perturb-lr F")

    report = build_report(targets, steps=args.steps,
                          perturb_lr=args.perturb_lr)
    if args.as_json:
        print(json.dumps(report, indent=1))
    else:
        for t in report["targets"].values():
            for f in t["findings"]:
                print(f"  [{f['severity']}] {f['pass']}: {f['message']}")
        t = report["totals"]
        print(f"total: {t['error']} divergence(s), {t['info']} A/B(s) "
              f"within tolerance")
    return 1 if report["totals"]["error"] else 0


if __name__ == "__main__":
    sys.exit(main())
