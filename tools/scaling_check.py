"""Weak-scaling structure check (BASELINE metric: "fleet allreduce scaling
eff 8→256 chips").

Wall-clock scaling needs a pod; what is checkable anywhere is the PROGRAM
STRUCTURE that determines it: with a fixed per-device batch, the compiled
per-device train step must keep (a) per-device FLOPs, (b) grad all-reduce
count, and (c) all-reduce payload bytes CONSTANT as dp grows — collectives
whose cost rides the ring (per-device bytes ~2x payload, independent of N)
instead of multiplying with world size. A design that gathered params or
scaled payload with dp would fail here long before a pod run could.

Usage: XLA_FLAGS=--xla_force_host_platform_device_count=32 \
       python tools/scaling_check.py [--dp 2 8 32]
Prints one JSON line per dp plus a "scaling_ok" verdict.
"""
import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure(dp, per_device_batch=4, seq=64):
    import jax

    jax.config.update("jax_platforms", "cpu")  # virtual host devices
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainLoss

    devices = jax.devices()[:dp]
    assert len(devices) == dp, f"need {dp} devices, have {len(jax.devices())}"
    mesh = build_mesh((dp,), ("dp",), devices=devices)
    paddle.seed(0)
    cfg = GPTConfig.tiny()
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    tr = SpmdTrainer(model, opt, loss_fn=GPTPretrainLoss(), mesh=mesh)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(
        0, cfg.vocab_size, (per_device_batch * dp, seq)).astype(np.int32))
    batch = [ids, ids]
    step = tr._build(batch)
    lr = jnp.asarray(1e-4, jnp.float32)
    compiled = step.lower(tr.params, tr.opt_state, tr.buffers, lr,
                          jax.random.key(0), *batch).compile()
    txt = compiled.as_text()
    # DEFINING all-reduce instructions only (use sites of %all-reduce.N must
    # not count): "%x = f32[64]{0} all-reduce(" or the tuple form
    # "%x = (f32[a]{0}, f32[b]{0}) all-reduce("
    elt_bytes = {"f32": 4, "bf16": 2, "f16": 2, "f64": 8, "s32": 4,
                 "u32": 4, "pred": 1}

    def shape_bytes(ty, shape):
        n = 1
        for d in shape.split(","):
            if d.strip():
                n *= int(d)
        return n * elt_bytes.get(ty, 4)

    count, payload = 0, 0
    for m in re.finditer(
            r"=\s*(\w+)\[([\d,]*)\](?:\{[^}]*\})?\s+all-reduce\(", txt):
        count += 1
        payload += shape_bytes(m.group(1), m.group(2))
    for m in re.finditer(r"=\s*\(([^)]*)\)\s+all-reduce\(", txt):
        count += 1
        for ty, shape in re.findall(r"(\w+)\[([\d,]*)\]", m.group(1)):
            payload += shape_bytes(ty, shape)
    cost = compiled.cost_analysis()
    cost = cost[0] if isinstance(cost, (list, tuple)) else cost
    flops = float(cost.get("flops", -1.0)) if cost else -1.0
    return {"dp": dp, "allreduce_count": count,
            "allreduce_payload_bytes": payload,
            "flops_per_device": flops}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, nargs="+", default=None)
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    n = len(jax.devices())
    dps = args.dp or [d for d in (2, 8, 32) if d <= n]
    if len(dps) < 2:
        print(json.dumps({"error": f"need >=2 dp points; only {n} devices "
                          "visible — set XLA_FLAGS="
                          "--xla_force_host_platform_device_count=32"}))
        sys.exit(1)
    rows = [measure(dp) for dp in dps]
    for r in rows:
        print(json.dumps(r))
    base = rows[0]
    ok = all(r["allreduce_count"] == base["allreduce_count"]
             and r["allreduce_payload_bytes"]
             == base["allreduce_payload_bytes"]
             and (base["flops_per_device"] < 0 or r["flops_per_device"] < 0
                  or abs(r["flops_per_device"] - base["flops_per_device"])
                  <= 0.01 * base["flops_per_device"])
             for r in rows[1:])
    print(json.dumps({"scaling_ok": bool(ok), "dps": dps}))


if __name__ == "__main__":
    main()
