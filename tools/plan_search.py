"""Auto-parallelism plan search CLI: rank dp/mp/pp/stage partitionings.

    python tools/plan_search.py --model gpt             # rank plans, human
    python tools/plan_search.py --model gpt --top 5     # only the top 5
    python tools/plan_search.py --model gpt --explain   # per-plan cost
                                                        # breakdown + every
                                                        # rejection with the
                                                        # analyzer pass that
                                                        # killed it
    python tools/plan_search.py --model gpt --model bert --json
    python tools/plan_search.py --model gpt --emit      # winning plan as a
                                                        # ready-to-run config
    python tools/plan_search.py --model gpt --calibrated table.json
                                                        # price plans with
                                                        # measured constants
                                                        # (perf_report
                                                        # --calibrate)
    python tools/plan_search.py --model gpt --hbm-gb 0.001   # shrink the
                                                        # budget: every plan
                                                        # rejected -> exit 1

The static cost model (analysis/cost_model.py) prices compute from the
cost registry's traced flops/bytes, communication from the sharding-flow
analyzer's measured collective bytes plus HANDOFF_SCHEMA-derived edge
wire bytes, and memory against per-device HBM and the Pallas VMEM
budgets; the enumerator (analysis/plan_search.py) rejects invalid plans
through the EXISTING analyzers — a rejection always names the failing
pass, it never crashes. Nothing executes on devices: trace-only.

Report format: the tools/graph_lint.py schema ({"tool", "passes",
"rules", "targets": {name: {"name","counts","findings"}}, "totals"}) —
``graph_lint --plan`` folds the same targets into its battery. Exit
code 1 when any error-severity finding exists, i.e. when a model's
search space contains ZERO valid plans (``plan-space-empty``).
"""
import argparse
import json
import os
import sys

# plan verification traces shard_map programs against the deployment
# mesh: give the CPU backend its virtual devices BEFORE jax initializes
# (the tests/conftest.py mesh). APPEND to any user-set XLA_FLAGS — a
# plain setdefault would silently collapse the search to 1 device
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8"
                               ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _explain_lines(result, top=None):
    """Human cost breakdown: every ranked plan's terms, then every
    rejected plan with the analyzer pass(es) that killed it."""
    lines = []
    ranked = result.ranked[:top] if top else result.ranked
    for i, (plan, score) in enumerate(ranked):
        t = score["terms"]
        lines.append(
            f"  #{i + 1} {plan.describe()}: total "
            f"{score['total_s'] * 1e6:.2f}us")
        lines.append(
            f"      compute {score['compute_s'] * 1e6:.2f}us "
            f"(bubble x{score['bubble']:.2f}), comm "
            f"{score['comm_s'] * 1e6:.2f}us "
            f"({score['comm_bytes'] / 1024:.1f} KiB over "
            f"{score['messages']} message(s), "
            f"{'measured' if t.get('measured') else 'analytic'})")
        lines.append(
            f"      bytes: dp_sync {t['dp_sync_bytes'] / 1024:.1f} KiB, "
            f"mp_sync {t['mp_sync_bytes'] / 1024:.1f} KiB, "
            f"edge_wire {t['edge_wire_bytes'] / 1024:.1f} KiB; "
            f"hbm/device {score['mem_bytes_per_device'] / (1 << 20):.2f} "
            f"MiB (state {t['state_bytes'] / (1 << 20):.2f}, act "
            f"{t['activation_bytes'] / (1 << 20):.2f})")
    for plan, errs in result.rejected:
        passes = sorted({e.pass_name for e in errs})
        lines.append(f"  -- {plan.describe()}: REJECTED by {passes}")
        for e in errs:
            lines.append(f"      [{e.pass_name}] {e.message}")
    return lines


def build_report(models, devices=None, hbm_bytes=None, top=None,
                 calibrated=None):
    """Run the search per model; returns (graph_lint-schema report,
    {model: SearchResult}). ``calibrated`` is a calibration-table path
    (tools/perf_report.py --calibrate): its measured constants replace
    the nominal peak-flops/HBM/interconnect rates in the cost model —
    ranking only; validity checks are constant-free."""
    from paddle_tpu.analysis import registered_passes
    from paddle_tpu.analysis import cost_model, plan_search

    cm = None
    calibration = None
    if calibrated:
        from paddle_tpu.analysis import calibrate

        table = calibrate.load_table(calibrated)
        constants = calibrate.constants_for_cost_model(table)
        cm = cost_model.CostModel(
            hbm_bytes=hbm_bytes or cost_model.DEFAULT_HBM_BYTES,
            constants=constants)
        calibration = {"path": calibrated, "rows": table.get("rows"),
                       "env": table.get("env"), "constants": constants}
    results, targets = {}, {}
    for model in models:
        res = plan_search.search(model, devices=devices,
                                 hbm_bytes=hbm_bytes, cm=cm)
        results[model] = res
        targets[f"plan_{model}"] = res.to_report(top=top)
    totals = {"error": 0, "warning": 0, "info": 0}
    for rep in targets.values():
        for sev, n in rep.counts().items():
            totals[sev] = totals.get(sev, 0) + n
    rules = dict(cost_model.RULES)
    rules.update(plan_search.RULES)
    report = {
        "tool": "plan_search",
        "passes": registered_passes(),
        "rules": sorted(rules),
        "targets": {n: r.to_dict() for n, r in targets.items()},
        "totals": totals,
    }
    if calibration is not None:
        report["calibration"] = calibration
    return report, results


def main(argv=None):
    from paddle_tpu.analysis.plan_search import PLAN_MODELS

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", choices=PLAN_MODELS, action="append",
                    default=[],
                    help="bundled model to plan for (repeatable; "
                         "default gpt)")
    ap.add_argument("--top", type=int, default=None, metavar="K",
                    help="report only the K best-ranked plans")
    ap.add_argument("--devices", type=int, default=None, metavar="N",
                    help="plan for an N-device pool (default: the "
                         "visible jax device count)")
    ap.add_argument("--hbm-gb", type=float, default=None, dest="hbm_gb",
                    metavar="GB",
                    help="per-device HBM budget in GiB (default 16)")
    ap.add_argument("--calibrated", default=None, metavar="TABLE",
                    help="price plans with the measured constants from a "
                         "calibration table (tools/perf_report.py "
                         "--calibrate) instead of the nominal "
                         "peak-flops/HBM/interconnect rates")
    ap.add_argument("--explain", action="store_true",
                    help="per-plan cost-term breakdown + every rejected "
                         "plan with the analyzer pass that rejected it")
    ap.add_argument("--emit", action="store_true",
                    help="print each model's winning plan as the "
                         "ready-to-run trainer config JSON")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine-readable report (adds a "
                         "'search' key with ranked scores + rejections)")
    args = ap.parse_args(argv)

    models = list(args.model) or ["gpt"]
    hbm_bytes = int(args.hbm_gb * (1 << 30)) if args.hbm_gb else None
    report, results = build_report(models, devices=args.devices,
                                   hbm_bytes=hbm_bytes, top=args.top,
                                   calibrated=args.calibrated)

    if args.as_json:
        report["search"] = {m: r.to_dict(top=args.top)
                            for m, r in results.items()}
        if args.emit:
            from paddle_tpu.analysis.plan_search import emit

            report["configs"] = {
                m: emit(r.best[0], r.profile)
                for m, r in results.items() if r.best}
        print(json.dumps(report, indent=1))
    else:
        for model, res in results.items():
            print(f"plan_{model}: {len(res.ranked)} valid plan(s), "
                  f"{len(res.rejected)} rejected")
            if args.explain:
                for line in _explain_lines(res, top=args.top):
                    print(line)
            else:
                rep = report["targets"][f"plan_{model}"]
                for f in rep["findings"]:
                    print(f"  [{f['severity']}] {f['pass']}: "
                          f"{f['message']}")
            if args.emit and res.best:
                from paddle_tpu.analysis.plan_search import emit

                print(f"  config: "
                      f"{json.dumps(emit(res.best[0], res.profile))}")
        t = report["totals"]
        print(f"total: {t['error']} error(s), {t['warning']} warning(s), "
              f"{t['info']} info across {len(report['targets'])} "
              "target(s)")
    return 1 if report["totals"]["error"] else 0


if __name__ == "__main__":
    sys.exit(main())
