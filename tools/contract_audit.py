"""Contract auditor CLI: the flag/lazy-import/observability/thread/
handoff/kernel invariants, machine-checked (ISSUE 12 + 13;
docs/ANALYSIS.md "Contract auditor").

    python tools/contract_audit.py                    # all six passes
    python tools/contract_audit.py --flags --imports  # a subset
    python tools/contract_audit.py --handoff          # transfer edges only
    python tools/contract_audit.py --pallas           # kernel budgets only
    python tools/contract_audit.py --json             # machine-readable
    python tools/contract_audit.py --record           # regen BOTH baselines
    python tools/contract_audit.py --list-rules       # rules + markers

Targets:

  flags         : analysis/flag_audit.py — orphan/undocumented flags,
                  conflicting defaults, structural flags missing from
                  _exec_key/AOT extra_key, hot-path flag re-reads
  imports       : analysis/import_graph.py — manifest-lazy modules must
                  be unreachable from the plain trainer/engine closure
  observability : analysis/obs_audit.py — metric/span inventory vs the
                  docs/OBSERVABILITY.md reference tables and the
                  tools/metrics_dump.py required-families lists
  threads       : source_lint unlocked-thread-shared-write over the
                  daemon-thread modules (THREAD_SHARED_MODULES). The
                  rule ALSO rides lint_source, so graph_lint --source
                  reports the same findings under its source_lint
                  target — deliberate overlap (each CLI is complete on
                  its own); exit codes key off "any error", so the
                  double view never flips a verdict
  handoff       : analysis/handoff_schema.py — every declared transfer
                  edge (disagg KV, pipeline stage, federated adapter,
                  checkpoint tree) extracted from source, producer/
                  consumer sites verified, fingerprints pinned against
                  tests/handoff_baseline.json (drift = error)
  pallas        : analysis/pallas_audit.py — every registered kernel's
                  grid/block divisibility, MXU/VPU alignment, static
                  VMEM budget, fp32-accumulator checks

Report format: the tools/graph_lint.py schema ({"tool", "passes",
"targets": {name: {"name","counts","findings"}}, "totals"}), so CI reads
every audit tool through one loader. Exit code 1 when any
error-severity finding exists. Warning counts are pinned by the tier-1
gate (tests/test_contract_gate.py) against tests/contract_baseline.json;
``--record`` regenerates it (AND tests/handoff_baseline.json) after an
INTENTIONAL change — errors are never baselined, they are fixed (the
one exception is handoff drift, where --record IS the act of moving
both sides of the edge together).
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TARGETS = ("flags", "imports", "observability", "threads", "handoff",
           "pallas")
BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "contract_baseline.json")


def build_report(targets=TARGETS, handoff_baseline=None):
    """Run the requested contract passes; graph_lint-schema dict."""
    from paddle_tpu.analysis import contract_reports, contract_rules

    picked = contract_reports(targets=[n for n in TARGETS
                                       if n in targets],
                              handoff_baseline=handoff_baseline)
    totals = {"error": 0, "warning": 0, "info": 0}
    for rep in picked.values():
        for sev, n in rep.counts().items():
            totals[sev] = totals.get(sev, 0) + n
    return {
        "tool": "contract_audit",
        "passes": sorted(contract_rules()),
        "targets": {n: r.to_dict() for n, r in picked.items()},
        "totals": totals,
    }


def record_baseline(report, path=BASELINE_PATH):
    """Persist per-target warning/info counts (NEVER errors — those are
    fixed, not acknowledged)."""
    base = {"targets": {n: {"warning": r["counts"]["warning"],
                            "info": r["counts"]["info"]}
                        for n, r in report["targets"].items()}}
    with open(path, "w") as f:
        json.dump(base, f, indent=1)
        f.write("\n")
    return base


def list_rules():
    from paddle_tpu.analysis import rule_table

    print(rule_table())


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--flags", action="store_true",
                    help="run the flag-contract pass only")
    ap.add_argument("--imports", action="store_true",
                    help="run the lazy-import closure pass only")
    ap.add_argument("--obs", "--observability", action="store_true",
                    dest="obs", help="run the observability-drift pass "
                    "only")
    ap.add_argument("--threads", action="store_true",
                    help="run the thread-discipline lint only")
    ap.add_argument("--handoff", action="store_true",
                    help="run the transfer-edge schema audit only")
    ap.add_argument("--pallas", action="store_true",
                    help="run the Pallas kernel budget audit only")
    ap.add_argument("--handoff-baseline", default=None,
                    dest="handoff_baseline", metavar="PATH",
                    help="override the handoff baseline path (the gate's "
                         "planted-drift smoke uses this)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine-readable report")
    ap.add_argument("--record", action="store_true",
                    help="regenerate tests/contract_baseline.json AND "
                         "tests/handoff_baseline.json (warning/info "
                         "counts + edge fingerprints; errors never "
                         "baseline)")
    ap.add_argument("--list-rules", action="store_true", dest="list_rules",
                    help="print every rule, severity and allow-marker "
                         "spelling")
    args = ap.parse_args(argv)

    if args.list_rules:
        list_rules()
        return 0

    picked = [n for n, on in (("flags", args.flags),
                              ("imports", args.imports),
                              ("observability", args.obs),
                              ("threads", args.threads),
                              ("handoff", args.handoff),
                              ("pallas", args.pallas)) if on] or TARGETS
    if args.record and tuple(picked) != TARGETS:
        # a partial baseline would KeyError the tier-1 gate on the
        # missing targets — recording is always the full battery
        picked = TARGETS
    if args.record:
        # stamp the edge fingerprints FIRST so the drift pass in the
        # battery below sees (and reports against) the fresh baseline
        from paddle_tpu.analysis import handoff_schema

        hb = handoff_schema.record_baseline(path=args.handoff_baseline)
        print(f"recorded -> "
              f"{args.handoff_baseline or handoff_schema.BASELINE_PATH} "
              f"({len(hb['edges'])} transfer edge(s))")
    report = build_report(picked, handoff_baseline=args.handoff_baseline)
    if args.record:
        base = record_baseline(report)
        print(f"recorded -> {BASELINE_PATH}")
        print(json.dumps(base, indent=1))
    if args.as_json:
        print(json.dumps(report, indent=1))
    elif not args.record:
        for name, rep in report["targets"].items():
            c = rep["counts"]
            print(f"{name}: {c['error']} error(s), {c['warning']} "
                  f"warning(s), {c['info']} info")
            for f in rep["findings"]:
                loc = f" @ {f['where']}" if f["where"] else ""
                print(f"  [{f['severity']}] {f['pass']}: "
                      f"{f['message']}{loc}")
        t = report["totals"]
        print(f"total: {t['error']} error(s), {t['warning']} warning(s), "
              f"{t['info']} info across {len(report['targets'])} "
              "target(s)")
    return 1 if report["totals"]["error"] else 0


if __name__ == "__main__":
    sys.exit(main())
