"""Perf report CLI: record, check, calibrate and explain the perf ledger.

    python tools/perf_report.py --record --path perf.jsonl    # append one
                                                        # measured tiny-GPT
                                                        # window of rows
    python tools/perf_report.py --check --path perf.jsonl     # fresh
                                                        # measurement vs the
                                                        # ledger baselines;
                                                        # exit 1 names every
                                                        # regressed
                                                        # site+metric
    python tools/perf_report.py --check --path perf.jsonl --inject \
            trainer/batch=delay:400                     # sentinel self-test:
                                                        # plant a slowdown in
                                                        # the fresh run
    python tools/perf_report.py --calibrate --path perf.jsonl \
            --out table.json                            # least-squares the
                                                        # measured constants
                                                        # (plan_search
                                                        # --calibrated eats
                                                        # the table)
    python tools/perf_report.py --explain --path perf.jsonl   # what the
                                                        # ledger knows
    python tools/perf_report.py --goodput --path perf.jsonl   # the last
                                                        # run/goodput row's
                                                        # bucket table
                                                        # (FLAGS_goodput
                                                        # runs append them)
    python tools/perf_report.py --check --path perf.jsonl --json

The ledger (monitor/perfledger.py, FLAGS_perf_ledger) is the persistent
record; this CLI is the loop around it. --record measures this machine
(a CPU-shrunk tiny-GPT train window, the tools/metrics_dump.py
convention) and appends rows. --check re-measures into a THROWAWAY
ledger — a regressed check must never contaminate the baselines — and
compares every sentinel-directed (site, metric) mean against the stored
rows' EMA baselines (cold compile-resolving rows excluded); each
breach is one error finding naming the site and metric. --calibrate
fits effective peak-flops / HBM / interconnect constants
(analysis/calibrate.py) into the table ``plan_search --calibrated``
consumes.

Report format: the tools/graph_lint.py schema ({"tool", "passes",
"rules", "targets": {name: {"name", "counts", "findings"}}, "totals"})
so CI reads every audit tool through one loader. Exit 1 when any
error-severity finding exists.
"""
import argparse
import json
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: this tool's own finding rules (calibration adds analysis/calibrate.py
#: RULES; both tables ride in the report's "rules" list)
RULES = {
    # a fresh measurement breached its stored baseline — names site+metric
    "perf-regression": "error",
    # a measured sentinel-direction metric has no (or too short) baseline
    "perf-no-baseline": "warning",
    # the ledger holds no usable rows for the requested operation
    "perf-ledger-empty": "error",
    # --record appended nothing (armed run produced no rows)
    "perf-record-empty": "error",
}

_DIMS = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
             dropout=0.0)


def _finding(pass_name, severity, message, where=""):
    return {"pass": pass_name, "severity": severity, "message": message,
            "where": where}


def _measure(path, steps=8, inject=None):
    """One armed tiny-GPT train window appending rows to ``path``:
    the measurement both --record and --check share (--check points it
    at a throwaway file). Returns the trainer's stats()."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainLoss
    from paddle_tpu.monitor import perfledger
    from paddle_tpu.testing import failpoints

    from paddle_tpu import flags

    old = {k: flags.get_flag(k)
           for k in ("perf_ledger", "perf_ledger_path",
                     "perf_ledger_interval")}
    paddle.set_flags({"perf_ledger": True, "perf_ledger_path": path,
                      "perf_ledger_interval": 1})
    perfledger.reset_ledger()
    try:
        paddle.seed(0)
        rng = np.random.RandomState(0)
        model = GPTForCausalLM(GPTConfig(max_seq_len=64, **_DIMS))
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        trainer = SpmdTrainer(model, opt, loss_fn=GPTPretrainLoss(),
                              mesh=mesh)
        batch = [paddle.to_tensor(
            rng.randint(0, 256, (2, 16)).astype(np.int32))
            for _ in range(2)]
        if inject:
            with failpoints.scoped(inject):
                for _ in range(steps):
                    trainer.train_step(*batch)
        else:
            for _ in range(steps):
                trainer.train_step(*batch)
        return trainer.stats()
    finally:
        paddle.set_flags(old)
        perfledger.reset_ledger()


def _fresh_means(rows):
    """Per-(site, metric) mean over a fresh run's warm (non-cold) rows,
    sentinel-directed metrics only."""
    from paddle_tpu.monitor import perfledger as pl

    acc = {}
    for r in rows:
        m = r.get("metrics") or {}
        if m.get("cold"):
            continue
        site = r.get("site")
        for name, v in m.items():
            if name not in pl.HIGH_IS_BAD and name not in pl.LOW_IS_BAD:
                continue
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            s, n = acc.get((site, name), (0.0, 0))
            acc[(site, name)] = (s + float(v), n + 1)
    return {k: s / n for k, (s, n) in acc.items() if n}


def run_record(path, steps=8):
    from paddle_tpu.monitor import perfledger as pl

    before = len(pl.load_rows(path))
    _measure(path, steps=steps)
    after = len(pl.load_rows(path))
    findings = []
    if after <= before:
        findings.append(_finding(
            "perf-record-empty", "error",
            f"armed measurement appended no rows to {path}", where=path))
    else:
        findings.append(_finding(
            "record", "info",
            f"appended {after - before} row(s) ({after} total) to {path}",
            where=path))
    return findings


def run_check(path, steps=8, sigma=None, inject=None):
    """Fresh measurement vs the ledger's EMA baselines; one error
    finding per breached (site, metric)."""
    import tempfile

    from paddle_tpu import flags
    from paddle_tpu.monitor import perfledger as pl

    if sigma is None:
        sigma = float(flags.get_flag("perf_ledger_sigma", 4.0))
    rows = pl.load_rows(path)
    if not rows:
        return [_finding(
            "perf-ledger-empty", "error",
            f"no usable rows in {path!r} — run --record first",
            where=path)]
    base = pl.baselines(rows)
    warmup = max(2, int(flags.get_flag("perf_ledger_warmup", 5)))
    fd, tmp = tempfile.mkstemp(suffix=".jsonl", prefix="perf_check_")
    os.close(fd)
    try:
        _measure(tmp, steps=steps, inject=inject)
        fresh = _fresh_means(pl.load_rows(tmp))
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    findings = []
    if not fresh:
        return [_finding(
            "perf-ledger-empty", "error",
            "fresh measurement produced no warm rows to check",
            where=path)]
    for (site, metric) in sorted(fresh):
        value = fresh[(site, metric)]
        ema = base.get((site, metric))
        if ema is None or ema.n < warmup:
            findings.append(_finding(
                "perf-no-baseline", "warning",
                f"{site}/{metric}: {0 if ema is None else ema.n} stored "
                f"observation(s) (need {warmup}) — measured {value:.4g}, "
                "not checked", where=f"{site}/{metric}"))
            continue
        regressed, excess = pl.check_value(ema, metric, value, sigma)
        if regressed:
            findings.append(_finding(
                "perf-regression", "error",
                f"{site}/{metric} regressed: measured {value:.4g} vs "
                f"baseline {ema.mean:.4g} ± {ema.std():.4g} "
                f"({excess:.1f} floored sigmas, threshold {sigma:g})",
                where=f"{site}/{metric}"))
        else:
            findings.append(_finding(
                "check", "info",
                f"{site}/{metric}: {value:.4g} within baseline "
                f"{ema.mean:.4g} ± {ema.std():.4g} ({excess:+.1f}σ)",
                where=f"{site}/{metric}"))
    return findings


def run_calibrate(path, out=None):
    from paddle_tpu.analysis import calibrate
    from paddle_tpu.monitor import perfledger as pl

    rows = pl.load_rows(path)
    if not rows:
        return [_finding(
            "perf-ledger-empty", "error",
            f"no usable rows in {path!r} — run --record first",
            where=path)], None
    table, calib_findings = calibrate.calibrate(rows)
    findings = [f.to_dict() for f in calib_findings]
    findings.append(_finding(
        "calibrate", "info",
        f"fit {sorted(table['constants'])} from {table['rows']} row(s) "
        f"(of {table['rows_total']} total) for env "
        f"{pl.fingerprint_key(table['env'])}", where=path))
    if out:
        calibrate.save_table(table, out)
        findings.append(_finding(
            "calibrate", "info", f"table written to {out}", where=out))
    return findings, table


def run_explain(path):
    """What the ledger knows: row counts per site/env, the baselines a
    --check would enforce, and the recent regressions rows recorded."""
    from paddle_tpu.monitor import perfledger as pl

    rows = pl.load_rows(path)
    if not rows:
        return [_finding(
            "perf-ledger-empty", "error",
            f"no usable rows in {path!r} — run --record first",
            where=path)]
    findings = []
    sites, envs = {}, {}
    for r in rows:
        sites[r.get("site")] = sites.get(r.get("site"), 0) + 1
        key = pl.fingerprint_key(r.get("env") or {})
        envs[key] = envs.get(key, 0) + 1
    findings.append(_finding(
        "explain", "info",
        f"{len(rows)} row(s): " +
        ", ".join(f"{s}={n}" for s, n in sorted(sites.items())),
        where=path))
    for key, n in sorted(envs.items()):
        findings.append(_finding(
            "explain", "info", f"env [{key}]: {n} row(s)", where=path))
    for (site, metric), ema in sorted(pl.baselines(rows).items()):
        findings.append(_finding(
            "explain", "info",
            f"baseline {site}/{metric}: {ema.mean:.4g} ± {ema.std():.4g} "
            f"over {ema.n} obs", where=f"{site}/{metric}"))
    return findings


def run_goodput(path):
    """The last ``site=run/goodput`` row's bucket table: where every
    wall-second of the most recent FLAGS_goodput-accounted run went
    (monitor/goodput.py appends one row per finalized run)."""
    from paddle_tpu.monitor import perfledger as pl

    rows = [r for r in pl.load_rows(path)
            if r.get("site") == "run/goodput"]
    if not rows:
        return [_finding(
            "perf-ledger-empty", "error",
            f"no run/goodput rows in {path!r} — finalize a FLAGS_goodput "
            "run (or tools/metrics_dump.py --goodput) first",
            where=path)]
    row = rows[-1]
    m = row.get("metrics") or {}
    buckets = m.get("buckets") or {}
    wall = float(m.get("wall_s", 0.0)) or 1.0
    findings = [_finding(
        "goodput", "info",
        f"run {row.get('sig')}: goodput {float(m.get('goodput', 0.0)):.3f}"
        f" over {float(m.get('wall_s', 0.0)):.3f}s wall "
        f"({int(m.get('n_resumes', 0))} resume(s), "
        f"{int(m.get('n_reshards', 0))} reshard(s); "
        f"{len(rows)} run/goodput row(s) total)", where=path)]
    for b, secs in sorted(buckets.items(), key=lambda kv: -kv[1]):
        findings.append(_finding(
            "goodput", "info",
            f"{b:<14} {float(secs):8.3f}s  {100.0 * float(secs) / wall:5.1f}%",
            where=f"run/goodput/{b}"))
    return findings


def build_report(ops, path, steps=8, sigma=None, inject=None, out=None):
    """graph_lint-schema report over the requested operations."""
    from paddle_tpu.analysis import calibrate

    rules = dict(RULES)
    rules.update(calibrate.RULES)
    report = {"tool": "perf_report", "passes": sorted(ops),
              "rules": sorted(rules), "targets": {},
              "totals": {"error": 0, "warning": 0, "info": 0}}
    for op in ops:
        if op == "record":
            findings = run_record(path, steps=steps)
        elif op == "check":
            findings = run_check(path, steps=steps, sigma=sigma,
                                 inject=inject)
        elif op == "calibrate":
            findings, table = run_calibrate(path, out=out)
            if table is not None:
                report["calibration"] = table
        elif op == "goodput":
            findings = run_goodput(path)
        else:
            findings = run_explain(path)
        counts = {"error": 0, "warning": 0, "info": 0}
        for f in findings:
            counts[f["severity"]] += 1
        report["targets"][op] = {"name": op, "counts": counts,
                                 "findings": findings}
        for sev, n in counts.items():
            report["totals"][sev] += n
    return report


def main(argv=None):
    from paddle_tpu import flags

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--path", default=None, metavar="LEDGER",
                    help="the ledger JSONL (default: "
                         "FLAGS_perf_ledger_path)")
    ap.add_argument("--record", action="store_true",
                    help="measure this machine and append rows")
    ap.add_argument("--check", action="store_true",
                    help="fresh measurement vs the stored baselines; "
                         "exit 1 names every regressed site+metric")
    ap.add_argument("--calibrate", action="store_true",
                    help="least-squares the measured cost-model "
                         "constants from the rows (see --out)")
    ap.add_argument("--explain", action="store_true",
                    help="row counts, env groups and the baselines a "
                         "--check would enforce")
    ap.add_argument("--goodput", action="store_true",
                    help="print the last run/goodput row's bucket table "
                         "(where every wall-second of the most recent "
                         "accounted run went)")
    ap.add_argument("--out", default=None, metavar="TABLE",
                    help="where --calibrate writes the constants table "
                         "(plan_search --calibrated reads it)")
    ap.add_argument("--steps", type=int, default=8,
                    help="train steps per measurement window (default 8)")
    ap.add_argument("--sigma", type=float, default=None,
                    help="regression threshold in floored EMA sigmas "
                         "(default FLAGS_perf_ledger_sigma)")
    ap.add_argument("--inject", default=None, metavar="SPEC",
                    help="failpoint spec planted during the --check "
                         "measurement (sentinel self-test, e.g. "
                         "trainer/batch=delay:400)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine-readable report")
    args = ap.parse_args(argv)

    ops = [op for op, on in (("record", args.record), ("check", args.check),
                             ("calibrate", args.calibrate),
                             ("explain", args.explain),
                             ("goodput", args.goodput)) if on]
    if not ops:
        ap.error("pick an operation: --record, --check, --calibrate, "
                 "--explain and/or --goodput")
    path = args.path or flags.get_flag("perf_ledger_path", "")
    if not path:
        ap.error("no ledger path: pass --path or set "
                 "FLAGS_perf_ledger_path")

    report = build_report(ops, path, steps=args.steps, sigma=args.sigma,
                          inject=args.inject, out=args.out)
    if args.as_json:
        print(json.dumps(report, indent=1))
    else:
        for name, t in report["targets"].items():
            print(f"{name}:")
            for f in t["findings"]:
                print(f"  [{f['severity']}] {f['pass']}: {f['message']}")
        t = report["totals"]
        print(f"total: {t['error']} error(s), {t['warning']} warning(s), "
              f"{t['info']} info across {len(report['targets'])} "
              "target(s)")
    return 1 if report["totals"]["error"] else 0


if __name__ == "__main__":
    sys.exit(main())
