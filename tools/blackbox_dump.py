"""Blackbox dump CLI: trigger, read, and validate flight-recorder bundles.

    python tools/blackbox_dump.py --trigger 12345       # SIGUSR1 a live pid
    python tools/blackbox_dump.py --read BUNDLE.json    # pretty-printer
    python tools/blackbox_dump.py --read BUNDLE.json --json
    python tools/blackbox_dump.py --latest [--dir D]    # newest bundle

``--trigger PID`` sends SIGUSR1 to a live process running with
``FLAGS_blackbox=1`` — its installed handler writes a dump bundle to its
``FLAGS_blackbox_dir`` (default <tmp>/paddle_tpu_blackbox) without
stopping it. ``--read`` loads a bundle, validates the required keys
(reason, beacon table, ring, all-thread stacks, metrics snapshot,
in-flight request tables, context) and prints the wedge-reading view:
which site stalled, what every thread was doing, the last ring events,
and which requests were mid-flight. A missing or malformed bundle is an
error-severity finding and **exit code 1** — the CI contract.

``--json`` emits the tools/graph_lint.py report schema ({"tool",
"passes", "targets": {name: {"name", "counts", "findings"}}, "totals"},
plus the parsed "bundle" per target) so CI reads graph_lint /
metrics_dump / trace_dump / chaos_check / blackbox_dump through one
loader. See docs/OBSERVABILITY.md "Flight recorder & stall diagnostics".
"""
import argparse
import json
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PASSES = ["bundle-valid", "bundle-content"]


def _finding(name, severity, message, where=""):
    return {"pass": name, "severity": severity, "message": message,
            "where": where}


def audit_bundle(path):
    """Load + validate one bundle; returns (bundle | None, findings)."""
    from paddle_tpu.monitor import blackbox

    try:
        bundle = blackbox.load_bundle(path)
    except ValueError as e:
        return None, [_finding("bundle-valid", "error", str(e), where=path)]
    findings = [_finding("bundle-valid", "info",
                         f"bundle well-formed (reason={bundle['reason']!r}, "
                         f"site={bundle.get('site')!r})", where=path)]
    if not bundle.get("stacks"):
        findings.append(_finding(
            "bundle-content", "error",
            "bundle has no thread stacks — the dump writer captured "
            "nothing attributable", where=path))
    if bundle["reason"] not in ("stall", "signal", "crash"):
        findings.append(_finding(
            "bundle-content", "warning",
            f"unknown dump reason {bundle['reason']!r} (expected "
            "stall|signal|crash)", where=path))
    if bundle["reason"] == "stall" and not bundle.get("site"):
        findings.append(_finding(
            "bundle-content", "error",
            "a stall bundle must name the stalled beacon site",
            where=path))
    return bundle, findings


def summarize(bundle, out=sys.stdout):
    """The human wedge-reading view of one bundle."""
    w = out.write
    w(f"# blackbox bundle: reason={bundle['reason']} "
      f"site={bundle.get('site')} pid={bundle['pid']}\n")
    ctx = bundle.get("context") or {}
    if ctx:
        w(f"  context: {json.dumps(ctx, sort_keys=True)}\n")
    w("  beacons:\n")
    for site, b in sorted((bundle.get("beacons") or {}).items()):
        flag = " <-- stalled" if site == bundle.get("site") else ""
        w(f"    {site:<20} count={b['count']:<8} age={b['age_s']}s "
          f"active={b['active']}{flag}\n")
    reqs = bundle.get("requests") or []
    for entry in reqs:
        if "error" in entry:
            w(f"  {entry['kind']}: provider error {entry['error']}\n")
            continue
        w(f"  {entry['kind']}: "
          f"{json.dumps(entry['table'], sort_keys=True)}\n")
    ring = bundle.get("ring") or []
    w(f"  ring ({len(ring)} events, newest last):\n")
    for rec in ring[-10:]:
        w(f"    {json.dumps(rec, sort_keys=True)}\n")
    w(f"  threads ({len(bundle.get('stacks') or [])}):\n")
    for th in bundle.get("stacks") or []:
        w(f"    -- {th['name']} (tid {th['thread_id']})\n")
        for line in th["stack"][-4:]:
            for ln in line.rstrip().splitlines():
                w(f"       {ln}\n")


def build_report(paths):
    report = {"tool": "blackbox_dump", "passes": PASSES, "targets": {},
              "totals": {"error": 0, "warning": 0, "info": 0}}
    for path in paths:
        bundle, findings = audit_bundle(path)
        counts = {"error": 0, "warning": 0, "info": 0}
        for f in findings:
            counts[f["severity"]] += 1
        name = os.path.basename(path)
        report["targets"][name] = {"name": name, "counts": counts,
                                   "findings": findings}
        if bundle is not None:
            report["targets"][name]["bundle"] = bundle
        for sev, n in counts.items():
            report["totals"][sev] += n
    return report


def _latest(d):
    from paddle_tpu.monitor import blackbox

    d = d or blackbox.default_dir()
    def mtime(p):
        # a live recorder may prune a bundle between the listing and the
        # stat: score vanished entries oldest instead of crashing
        try:
            return os.path.getmtime(p)
        except OSError:
            return 0.0

    try:
        names = [os.path.join(d, n) for n in os.listdir(d)
                 if n.startswith("blackbox-") and n.endswith(".json")]
    except OSError:
        return None
    return max(names, key=mtime) if names else None


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trigger", metavar="PID", type=int,
                    help="SIGUSR1 a live FLAGS_blackbox=1 process: it "
                         "writes a dump bundle and keeps running")
    ap.add_argument("--read", metavar="BUNDLE", action="append",
                    default=[],
                    help="load + validate a bundle (repeatable); exit 1 "
                         "on a missing/malformed one")
    ap.add_argument("--latest", action="store_true",
                    help="read the newest bundle in --dir (default: the "
                         "default blackbox dir)")
    ap.add_argument("--dir", default=None,
                    help="bundle directory for --latest")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the graph_lint-schema machine report")
    args = ap.parse_args(argv)

    if args.trigger is not None:
        if not hasattr(signal, "SIGUSR1"):
            print("SIGUSR1 unavailable on this platform", file=sys.stderr)
            return 1
        try:
            os.kill(args.trigger, signal.SIGUSR1)
        except OSError as e:
            print(f"cannot signal pid {args.trigger}: {e}",
                  file=sys.stderr)
            return 1
        print(f"SIGUSR1 sent to {args.trigger}; the bundle lands in its "
              "FLAGS_blackbox_dir")
        return 0

    paths = list(args.read)
    if args.latest:
        p = _latest(args.dir)
        if p is None:
            print("no bundles found", file=sys.stderr)
            return 1
        paths.append(p)
    if not paths:
        ap.error("pick an action: --trigger PID, --read BUNDLE, "
                 "or --latest")

    report = build_report(paths)
    if args.as_json:
        print(json.dumps(report, indent=1))
    else:
        for name, t in report["targets"].items():
            for f in t["findings"]:
                if f["severity"] != "info":
                    print(f"  [{f['severity']}] {f['pass']}: "
                          f"{f['message']}")
            if "bundle" in t:
                summarize(t["bundle"])
    return 1 if report["totals"]["error"] else 0


if __name__ == "__main__":
    sys.exit(main())
