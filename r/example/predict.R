# paddle_tpu R inference example (reference r/example parity).
#
# Like the reference's R client, this drives the Python inference API through
# reticulate — the TPU-native predictor is XLA reached via Python, so R (and
# any reticulate-capable host) gets the full predictor surface:
#
#   install.packages("reticulate")
#
# Expects a model saved with paddle.jit.save(net, prefix, input_spec=[...])
# (the durable jax.export artifact loads without the original Python class).

library(reticulate)

# point reticulate at the environment that has paddle_tpu on PYTHONPATH
# use_python("/opt/venv/bin/python")

paddle <- import("paddle_tpu")
np <- import("numpy")

args <- commandArgs(trailingOnly = TRUE)
prefix <- if (length(args) >= 1) args[[1]] else "./model"

predictor <- paddle$jit$load(prefix)

x <- np$ones(c(2L, 4L), dtype = "float32")
out <- predictor(paddle$to_tensor(x))
print(out$numpy())
