// Go inference client for paddle_tpu — cgo wrapper over the C API.
//
// Reference parity: go/paddle/predictor.go (cgo over inference/capi).
// Build: the shared library must be built first (see
// paddle_tpu/native/paddle_tpu_capi.h), then:
//
//	CGO_CFLAGS="-I<repo>/paddle_tpu/native" \
//	CGO_LDFLAGS="-L<path> -lpaddle_tpu_capi $(python3-config --embed --ldflags)" go build
//
// NOTE: no Go toolchain ships in the framework CI image, so this client is
// compiled and exercised by downstream users; the C ABI itself is tested in
// tests/test_capi.py.
package paddle_tpu

/*
#cgo LDFLAGS: -lpaddle_tpu_capi
#include <stdint.h>
#include <stdlib.h>
#include "paddle_tpu_capi.h"
*/
import "C"

import (
	"errors"
	"unsafe"
)

// Predictor wraps a jit.save'd paddle_tpu model. Not safe for concurrent Run
// calls on the same instance (outBuf is reused).
type Predictor struct {
	handle unsafe.Pointer
	outBuf []float32
}

// Init initializes the runtime (embeds CPython when standalone).
func Init() error {
	if C.PD_Init() != 0 {
		return errors.New("paddle_tpu: runtime init failed")
	}
	return nil
}

// NewPredictor loads a model saved with paddle.jit.save(prefix).
func NewPredictor(modelPrefix string) (*Predictor, error) {
	cs := C.CString(modelPrefix)
	defer C.free(unsafe.Pointer(cs))
	h := C.PD_CreatePredictor(cs)
	if h == nil {
		return nil, errors.New("paddle_tpu: " + C.GoString(C.PD_GetLastError()))
	}
	return &Predictor{handle: h}, nil
}

// Run executes the model on one float32 tensor and returns (data, shape).
// The output buffer grows on "too small" errors and is reused across calls.
func (p *Predictor) Run(data []float32, shape []int64) ([]float32, []int64, error) {
	if len(data) == 0 || len(shape) == 0 {
		return nil, nil, errors.New("paddle_tpu: empty input data or shape")
	}
	if p.outBuf == nil {
		p.outBuf = make([]float32, 1<<16)
	}
	outShape := make([]int64, 16)
	for {
		var outNdim C.int
		n := C.PD_PredictorRunFloat(
			p.handle,
			(*C.float)(unsafe.Pointer(&data[0])),
			(*C.int64_t)(unsafe.Pointer(&shape[0])),
			C.int(len(shape)),
			(*C.float)(unsafe.Pointer(&p.outBuf[0])),
			C.int64_t(len(p.outBuf)),
			(*C.int64_t)(unsafe.Pointer(&outShape[0])),
			C.int(len(outShape)),
			&outNdim,
		)
		if n >= 0 {
			out := make([]float32, n)
			copy(out, p.outBuf[:n])
			return out, outShape[:outNdim], nil
		}
		msg := C.GoString(C.PD_GetLastError())
		if msg == "output buffer too small" && len(p.outBuf) < 1<<28 {
			p.outBuf = make([]float32, len(p.outBuf)*4)
			continue
		}
		return nil, nil, errors.New("paddle_tpu: " + msg)
	}
}

// Destroy releases the predictor.
func (p *Predictor) Destroy() {
	if p.handle != nil {
		C.PD_DestroyPredictor(p.handle)
		p.handle = nil
	}
}
