"""Test harness: force an 8-device virtual CPU mesh (SURVEY.md §4 template —
the TPU analog of the reference's localhost multi-process NCCL tests).

The axon sitecustomize pins jax_platforms to the TPU tunnel; tests override it to CPU
*before* any jax computation so the suite is hermetic and multi-device.
"""
import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_tape():
    """Isolate the global autograd tape between tests."""
    from paddle_tpu.core.tape import global_tape

    global_tape().clear()
    yield
    global_tape().clear()


@pytest.fixture
def seed():
    import numpy as np

    import paddle_tpu as paddle

    np.random.seed(0)
    paddle.seed(0)
    return 0


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process/subprocess tests (seconds-scale)")
