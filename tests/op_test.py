"""OpTest — declarative numpy-reference operator test harness.

Reference parity: python/paddle/fluid/tests/unittests/op_test.py (OpTest:255 —
subclasses declare `self.op`, `self.inputs`, `self.attrs`, `self.outputs`;
check_output_with_place:1054 compares against the numpy reference;
check_grad:1362 compares analytic grads with get_numeric_gradient:110's central
differences).

TPU-native design: `self.op` is any callable over paddle_tpu Tensors (a
paddle.tensor fn, nn.functional fn, or lambda). check_output runs it eagerly AND
under jax.jit (the dygraph/static dual-path check collapses to eager-vs-jit
parity); check_grad compares tape autograd against central differences of the
same callable — jax.grad is the oracle-free analytic side.
"""
import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor


class OpTest:
    """Subclass contract:

        def setUp(self):
            self.op = paddle.tensor.add            # callable
            self.inputs = {"x": np_arr, "y": np_arr}  # positional by order
            self.attrs = {}                        # keyword args
            self.outputs = {"out": np_expected}    # or list for multi-output

    then call self.check_output() / self.check_grad(["x"], "out").
    """

    op = None
    inputs = None
    attrs = None
    outputs = None
    atol = 1e-5
    rtol = 1e-5
    grad_atol = 1e-3
    grad_rtol = 1e-2

    # pytest runs setUp via the autouse fixture in subclass modules; call
    # explicitly for plain invocation
    def setUp(self):  # pragma: no cover - overridden
        raise NotImplementedError

    def _ensure(self):
        if self.op is None:
            self.setUp()
        self.attrs = self.attrs or {}

    def _run_op(self, raw_inputs):
        tensors = [Tensor(jnp.asarray(v)) for v in raw_inputs.values()]
        for t in tensors:
            t.stop_gradient = False
        out = self.op(*tensors, **self.attrs)
        return out, tensors

    @staticmethod
    def _flatten(out):
        if isinstance(out, (tuple, list)):
            return list(out)
        return [out]

    # ---- output check --------------------------------------------------------
    def check_output(self, atol=None, rtol=None, jit=True):
        """jit=False for dynamic-output-shape ops (masked_select, unique, nms)
        that are host-eager by design — the reference's CPU-only kernels."""
        self._ensure()
        atol = atol or self.atol
        rtol = rtol or self.rtol
        out, _ = self._run_op(self.inputs)
        got = [np.asarray(o._data) for o in self._flatten(out)]
        want = (list(self.outputs.values())
                if isinstance(self.outputs, dict) else list(self.outputs))
        assert len(got) == len(want), f"{len(got)} outputs vs {len(want)} expected"
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, np.asarray(w), atol=atol, rtol=rtol)

        if not jit:
            return
        # eager-vs-jit parity (the dygraph/to_static dual-path check)
        def pure(*vals):
            ts = [Tensor(v) for v in vals]
            return [o._data for o in
                    self._flatten(self.op(*ts, **self.attrs))]

        jit_out = jax.jit(pure)(*[jnp.asarray(v)
                                  for v in self.inputs.values()])
        for g, j in zip(got, jit_out):
            np.testing.assert_allclose(g, np.asarray(j), atol=atol, rtol=rtol,
                                       err_msg="eager vs jit mismatch")

    # ---- gradient check ------------------------------------------------------
    def _numeric_grad(self, wrt_idx, out_idx, delta):
        """Central differences of sum(output[out_idx]) w.r.t. input wrt_idx."""
        vals = [np.asarray(v, np.float64) for v in self.inputs.values()]
        x = vals[wrt_idx]
        grad = np.zeros_like(x, np.float64)

        def f(xv):
            call = [jnp.asarray(v, jnp.float32) for v in vals]
            call[wrt_idx] = jnp.asarray(xv, jnp.float32)
            ts = [Tensor(c) for c in call]
            for t in ts:
                t.stop_gradient = True
            out = self._flatten(self.op(*ts, **self.attrs))[out_idx]
            return float(jnp.sum(out._data.astype(jnp.float64)))

        flat = x.reshape(-1)
        g = grad.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + delta
            hi = f(x)
            flat[i] = orig - delta
            lo = f(x)
            flat[i] = orig
            g[i] = (hi - lo) / (2 * delta)
        return grad

    def check_grad(self, inputs_to_check, output_name=None, delta=1e-3,
                   atol=None, rtol=None, max_elems=64):
        """Analytic (tape) grads vs central differences.

        max_elems guards runtime: inputs larger than this are rejected — keep
        op-test shapes small like the reference does.
        """
        self._ensure()
        atol = atol or self.grad_atol
        rtol = rtol or self.grad_rtol
        names = list(self.inputs.keys())
        out_idx = 0
        if output_name is not None and isinstance(self.outputs, dict):
            out_idx = list(self.outputs.keys()).index(output_name)

        out, tensors = self._run_op(self.inputs)
        target = self._flatten(out)[out_idx]
        target.sum().backward()

        for name in inputs_to_check:
            i = names.index(name)
            x = np.asarray(self.inputs[name])
            assert x.size <= max_elems, (
                f"input {name} has {x.size} elems; keep op-test shapes small")
            analytic = tensors[i].grad
            assert analytic is not None, f"no gradient reached input {name!r}"
            numeric = self._numeric_grad(i, out_idx, delta)
            np.testing.assert_allclose(
                np.asarray(analytic._data), numeric, atol=atol, rtol=rtol,
                err_msg=f"grad mismatch for input {name!r}")
