"""Black-box flight recorder (ISSUE 7): ring bounds/thread-safety, the
beacon registry contract, sentinel fire/no-fire semantics, dump-bundle
round-trips (stacks + ring + metrics + request tables), the SIGUSR1 and
excepthook dump paths, and the engine/router errors that name the bundle
they just wrote."""
import glob
import json
import os
import signal
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import flags, trace
from paddle_tpu.monitor import blackbox


@pytest.fixture(autouse=True)
def _clean():
    blackbox.stop_sentinel()
    blackbox.disable()
    blackbox.reset()
    yield
    blackbox.stop_sentinel()
    blackbox.disable()
    blackbox.reset()


@pytest.fixture
def enabled(tmp_path):
    """Recorder on, bundles into tmp_path, flag restored afterwards."""
    old = flags.get_flag("blackbox_dir", "")
    flags.set_flags({"blackbox_dir": str(tmp_path)})
    blackbox.enable(install=False)
    yield str(tmp_path)
    flags.set_flags({"blackbox_dir": old})


def _bundles(d):
    return sorted(glob.glob(os.path.join(d, "blackbox-*.json")))


def _tiny_model():
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=2, max_seq_len=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


class TestRing:
    def test_bounded_oldest_dropped(self, enabled):
        blackbox.set_capacity(8)
        try:
            for i in range(20):
                blackbox.note("e", i=i)
            ring = blackbox.ring()
            assert len(ring) == 8
            assert [r["i"] for r in ring] == list(range(12, 20))
        finally:
            blackbox.set_capacity(512)

    def test_thread_safety(self, enabled):
        blackbox.set_capacity(10_000)
        try:
            def worker(k):
                for i in range(500):
                    blackbox.note("t", k=k, i=i)
                    blackbox.beacon(f"thread{k}")
            threads = [threading.Thread(target=worker, args=(k,))
                       for k in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(blackbox.ring()) == 2000
            for k in range(4):
                assert blackbox.beacons()[f"thread{k}"]["count"] == 500
        finally:
            blackbox.set_capacity(512)

    def test_ring_summary(self, enabled):
        for i in range(7):
            blackbox.note("e", i=i)
        s = blackbox.ring_summary(3)
        assert s["events"] == 7
        assert [r["i"] for r in s["tail"]] == [4, 5, 6]


class TestBeacons:
    def test_registry_contract(self, enabled):
        blackbox.beacon("site_a")
        blackbox.beacon("site_a")
        blackbox.beacon("site_b")
        b = blackbox.beacons()
        assert b["site_a"]["count"] == 2
        assert b["site_b"]["count"] == 1
        assert b["site_a"]["active"] and b["site_b"]["active"]
        assert b["site_a"]["age_s"] < 1.0
        blackbox.quiesce("site_a")
        assert not blackbox.beacons()["site_a"]["active"]
        blackbox.beacon("site_a")   # a beat re-activates
        assert blackbox.beacons()["site_a"]["active"]
        blackbox.quiesce()          # all-sites form
        assert not any(v["active"] for v in blackbox.beacons().values())

    def test_progress_window(self, enabled):
        with blackbox.progress("win"):
            assert blackbox.beacons()["win"]["active"]
        assert not blackbox.beacons()["win"]["active"]

    def test_reset_clears(self, enabled):
        blackbox.beacon("x")
        blackbox.note("e")
        blackbox.set_context("k", "v")
        blackbox.reset()
        assert blackbox.beacons() == {}
        assert blackbox.ring() == []
        assert blackbox.context() == {}


class TestSentinel:
    def test_fires_on_frozen_beacon(self, enabled):
        blackbox.beacon("frozen")
        blackbox.start_sentinel(timeout_s=0.15, poll_s=0.05)
        deadline = time.time() + 3.0
        while time.time() < deadline and not _bundles(enabled):
            time.sleep(0.05)
        bundles = _bundles(enabled)
        assert len(bundles) == 1, "sentinel did not fire on a frozen site"
        bundle = blackbox.load_bundle(bundles[0])
        assert bundle["reason"] == "stall"
        assert bundle["site"] == "frozen"
        # one bundle per episode: the frozen site must not dump again
        time.sleep(0.4)
        assert len(_bundles(enabled)) == 1

    def test_does_not_fire_on_slow_but_advancing(self, enabled):
        stop = threading.Event()

        def beat():
            while not stop.wait(0.05):
                blackbox.beacon("slow")

        t = threading.Thread(target=beat, daemon=True)
        t.start()
        try:
            blackbox.start_sentinel(timeout_s=0.3, poll_s=0.05)
            time.sleep(0.9)
            assert _bundles(enabled) == [], \
                "a slow-but-advancing beacon must never read as a stall"
        finally:
            stop.set()
            t.join()

    def test_does_not_fire_on_quiesced_site(self, enabled):
        blackbox.beacon("done")
        blackbox.quiesce("done")
        blackbox.start_sentinel(timeout_s=0.1, poll_s=0.05)
        time.sleep(0.4)
        assert _bundles(enabled) == []

    def test_names_most_recently_advancing_site(self, enabled):
        """Two stalled sites: the bundle names the one that was advancing
        last — the wedged loop, not a long-idle leftover."""
        blackbox.beacon("old_idle")
        time.sleep(0.25)
        blackbox.beacon("wedged_loop")
        # BOTH sites are already past the timeout at the first poll, so
        # one bundle covers the episode and must name the fresher site
        time.sleep(0.2)
        blackbox.start_sentinel(timeout_s=0.15, poll_s=0.05)
        deadline = time.time() + 3.0
        while time.time() < deadline and not _bundles(enabled):
            time.sleep(0.05)
        bundle = blackbox.load_bundle(_bundles(enabled)[0])
        assert bundle["site"] == "wedged_loop"
        stalled = {s["site"] for s in bundle["extra"]["stalled"]}
        assert stalled == {"old_idle", "wedged_loop"}

    def test_re_arms_after_progress(self, enabled):
        blackbox.beacon("flappy")
        blackbox.start_sentinel(timeout_s=0.12, poll_s=0.04)
        deadline = time.time() + 3.0
        while time.time() < deadline and len(_bundles(enabled)) < 1:
            time.sleep(0.04)
        assert len(_bundles(enabled)) == 1
        blackbox.beacon("flappy")   # progress re-arms the episode
        deadline = time.time() + 3.0
        while time.time() < deadline and len(_bundles(enabled)) < 2:
            time.sleep(0.04)
        assert len(_bundles(enabled)) == 2

    def test_thread_name_and_stop(self, enabled):
        blackbox.start_sentinel(timeout_s=5.0)
        assert blackbox.sentinel_running()
        assert any(t.name == blackbox.SENTINEL_THREAD_NAME
                   for t in threading.enumerate())
        blackbox.stop_sentinel()
        assert not blackbox.sentinel_running()


class TestDumpBundle:
    def test_round_trip_completeness(self, enabled):
        from paddle_tpu import monitor

        blackbox.beacon("rt_site")
        blackbox.note("evidence", n=1)
        blackbox.set_context("phase", "testing")
        monitor.counter("rt_probe_total").inc()
        path = blackbox.dump("signal", site="rt_site",
                             extra={"k": "v"})
        assert path is not None and os.path.exists(path)
        bundle = blackbox.load_bundle(path)
        assert blackbox.validate_bundle(bundle) == []
        # stacks: this thread must appear, mid-dump
        stacks = bundle["stacks"]
        assert any("dump" in "".join(th["stack"]) for th in stacks)
        # ring + beacons + context round-trip
        assert any(r["kind"] == "evidence" for r in bundle["ring"])
        assert bundle["beacons"]["rt_site"]["count"] == 1
        assert bundle["context"]["phase"] == "testing"
        assert bundle["extra"] == {"k": "v"}
        # full metrics snapshot rides along
        names = {m["name"] for m in bundle["metrics"]["metrics"]}
        assert "rt_probe_total" in names
        assert "faulthandler" in bundle

    def test_dump_counts_metric_and_ring(self, enabled):
        from paddle_tpu import monitor

        path = blackbox.dump("signal")
        assert path is not None
        metric = monitor.default_registry().get("blackbox_dump_total")
        series = {tuple(sorted(s.labels.items())): s.value
                  for s in metric.series()}
        assert series[(("reason", "signal"),)] >= 1
        assert any(r["kind"] == "dump" for r in blackbox.ring())

    def test_dump_emits_span_when_tracing(self, enabled):
        trace.clear()
        trace.enable()
        try:
            blackbox.dump("signal")
        finally:
            trace.disable()
        names = [s.name for s in trace.spans()]
        assert "blackbox_dump" in names
        trace.clear()

    def test_open_span_tree_captured(self, enabled):
        trace.clear()
        trace.enable()
        try:
            sp = trace.start_span("wedged_request", subsystem="serving")
            path = blackbox.dump("signal")
            bundle = blackbox.load_bundle(path)
            open_names = {s["name"] for s in bundle["open_spans"]}
            assert "wedged_request" in open_names
            sp.end()
            path2 = blackbox.dump("signal")
            bundle2 = blackbox.load_bundle(path2)
            assert "wedged_request" not in {
                s["name"] for s in bundle2["open_spans"]}
        finally:
            trace.disable()
            trace.clear()

    def test_span_close_digest_lands_in_ring(self, enabled):
        trace.clear()
        trace.enable()
        try:
            with trace.span("digested", subsystem="t"):
                pass
        finally:
            trace.disable()
            trace.clear()
        assert any(r["kind"] == "span" and r["name"] == "digested"
                   for r in blackbox.ring())

    def test_request_table_provider(self, enabled):
        from paddle_tpu.inference.serving import ServingEngine

        m = _tiny_model()
        eng = ServingEngine(m, max_batch=1)
        rng = np.random.RandomState(0)
        r0 = eng.submit(rng.randint(0, 64, (4,)).astype(np.int32),
                        max_new_tokens=8)
        r1 = eng.submit(rng.randint(0, 64, (6,)).astype(np.int32),
                        max_new_tokens=8)
        eng.step()   # r0 running in the slot, r1 queued
        path = blackbox.dump("signal")
        bundle = blackbox.load_bundle(path)
        tables = [t["table"] for t in bundle["requests"]
                  if t["kind"] == "serving_engine"]
        assert tables, "engine never registered its provider"
        t = tables[-1]
        assert set(t["in_flight"]) == {r0, r1}
        assert r1 in t["queued"]
        assert any(row["rid"] == r0 for row in t["running"])
        eng.run_until_complete()

    def test_bundle_dir_pruned_to_cap(self, enabled):
        old = flags.get_flag("blackbox_max_bundles", 32)
        flags.set_flags({"blackbox_max_bundles": 3})
        try:
            paths = [blackbox.dump("signal") for _ in range(5)]
            kept = _bundles(enabled)
            assert len(kept) == 3
            # newest survive: the last three written paths remain
            assert set(kept) == set(paths[-3:])
        finally:
            flags.set_flags({"blackbox_max_bundles": old})

    def test_dump_never_raises(self, tmp_path):
        # unwritable dir: dump returns None instead of crashing the host
        blackbox.enable(install=False)
        bad = tmp_path / "not_a_dir"
        bad.write_text("file, not dir")
        assert blackbox.dump("signal",
                             dir_=str(bad / "sub")) is None


class TestCrashAndSignalPaths:
    def test_sigusr1_dump(self, enabled):
        if not hasattr(signal, "SIGUSR1"):
            pytest.skip("no SIGUSR1 on this platform")
        old = signal.getsignal(signal.SIGUSR1)
        blackbox.install_hooks()
        # install_hooks latches; re-assert the handler for this test
        signal.signal(signal.SIGUSR1, blackbox._on_signal)
        try:
            os.kill(os.getpid(), signal.SIGUSR1)
            deadline = time.time() + 2.0
            while time.time() < deadline and not _bundles(enabled):
                time.sleep(0.02)
            bundles = _bundles(enabled)
            assert bundles, "SIGUSR1 did not produce a bundle"
            bundle = blackbox.load_bundle(bundles[0])
            assert bundle["reason"] == "signal"
        finally:
            signal.signal(signal.SIGUSR1, old)

    def test_excepthook_dump(self, enabled):
        try:
            raise ValueError("boom for the recorder")
        except ValueError as e:
            blackbox._on_excepthook(ValueError, e, e.__traceback__)
        bundles = _bundles(enabled)
        assert bundles
        bundle = blackbox.load_bundle(bundles[-1])
        assert bundle["reason"] == "crash"
        assert "boom for the recorder" in bundle["extra"]["exception"]

    def test_engine_stalled_error_names_dump_path(self, enabled):
        from paddle_tpu.inference.serving import ServingEngine

        m = _tiny_model()
        eng = ServingEngine(m, max_batch=1)
        rng = np.random.RandomState(1)
        rid = eng.submit(rng.randint(0, 64, (4,)).astype(np.int32),
                         max_new_tokens=30)
        with pytest.raises(RuntimeError) as exc:
            eng.run_until_complete(max_steps=2)
        msg = str(exc.value)
        assert "blackbox dump bundle:" in msg
        path = msg.rsplit("blackbox dump bundle: ", 1)[1]
        bundle = blackbox.load_bundle(path)
        assert bundle["reason"] == "stall"
        assert bundle["site"] == "serving/step"
        # the dump ran BEFORE the finishes: the rid is still in-flight
        tables = [t["table"] for t in bundle["requests"]
                  if t["kind"] == "serving_engine"]
        assert any(rid in t["in_flight"] for t in tables)
        assert eng.get_request(rid).finish_reason == "engine_stalled"

    def test_router_all_dead_error_names_dump_path(self, enabled):
        from paddle_tpu.inference.serving import ServingEngine
        from paddle_tpu.serving.router import NoLiveEngineError, Router

        m = _tiny_model()
        router = Router({"a": ServingEngine(m, max_batch=1)})
        router._alive.discard("a")   # every engine dead
        rng = np.random.RandomState(2)
        with pytest.raises(NoLiveEngineError) as exc:
            router.submit(rng.randint(0, 64, (4,)).astype(np.int32),
                          max_new_tokens=2)
        msg = str(exc.value)
        assert "blackbox dump bundle:" in msg
        path = msg.rsplit("blackbox dump bundle: ", 1)[1]
        bundle = blackbox.load_bundle(path)
        assert bundle["reason"] == "crash"
        assert bundle["site"] == "router/no_live_engine"
        assert bundle["extra"]["dead" if "dead" in bundle["extra"]
                               else "engines"] is not None

    def test_engine_stall_without_recorder_keeps_old_error(self):
        """Flag off: the engine_stalled error reads exactly as before —
        no dump, no path in the message."""
        from paddle_tpu.inference.serving import ServingEngine

        m = _tiny_model()
        eng = ServingEngine(m, max_batch=1)
        rng = np.random.RandomState(1)
        eng.submit(rng.randint(0, 64, (4,)).astype(np.int32),
                   max_new_tokens=30)
        with pytest.raises(RuntimeError) as exc:
            eng.run_until_complete(max_steps=2)
        assert "blackbox" not in str(exc.value)


class TestWorkloadBeacons:
    def test_serving_and_trainer_sites_register(self, enabled):
        from paddle_tpu.inference.serving import ServingEngine

        m = _tiny_model()
        eng = ServingEngine(m, max_batch=1)
        rng = np.random.RandomState(0)
        eng.submit(rng.randint(0, 64, (4,)).astype(np.int32),
                   max_new_tokens=3)
        eng.run_until_complete()
        sites = blackbox.beacons()
        assert sites["serving/step"]["count"] >= 2
        # the step window closed with the last step: a finished drain
        # never reads as a stall
        assert not sites["serving/step"]["active"]
        assert "serving/admit" in sites
        assert not sites["serving/admit"]["active"]  # window closed

        import jax

        from paddle_tpu.distributed.mesh import build_mesh
        from paddle_tpu.distributed.spmd import SpmdTrainer

        model = paddle.nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        tr = SpmdTrainer(model, opt, loss_fn=paddle.nn.MSELoss(),
                         mesh=mesh)
        tr.train_step(np.ones((2, 4), np.float32),
                      np.zeros((2, 1), np.float32))
        assert blackbox.beacons()["trainer/step"]["count"] == 1

    def test_router_and_disagg_sites_register(self, enabled):
        from paddle_tpu.inference.serving import ServingEngine
        from paddle_tpu.serving.disagg import DisaggregatedPool
        from paddle_tpu.serving.router import Router

        m = _tiny_model()
        rng = np.random.RandomState(0)
        router = Router({"a": ServingEngine(m, max_batch=1)})
        router.submit(rng.randint(0, 64, (4,)).astype(np.int32),
                      max_new_tokens=2)
        router.run_until_complete()
        pool = DisaggregatedPool(m, prefill_workers=1, decode_engines=1,
                                 max_batch=1)
        pool.submit(rng.randint(0, 64, (5,)).astype(np.int32),
                    max_new_tokens=2)
        pool.run_until_complete()
        sites = blackbox.beacons()
        for site in ("router/step", "disagg/handoff", "disagg/prefill"):
            assert sites[site]["count"] >= 1, site
            assert not sites[site]["active"], site

    def test_collective_and_checkpoint_tags(self, enabled, tmp_path):
        from paddle_tpu.distributed import collective

        collective.all_reduce(paddle.to_tensor(np.ones(2, np.float32)))
        p = str(tmp_path / "ckpt.pdparams")
        paddle.save({"w": paddle.to_tensor(np.ones(3))}, p)
        kinds = [r["kind"] for r in blackbox.ring()]
        assert "collective" in kinds
        assert "checkpoint" in kinds
