"""Tier-1 contract-auditor gate (ISSUE 12): the repo's flag-gating,
lazy-import, observability-inventory, and thread-discipline invariants
are machine-checked every run.

Contract (the acceptance criteria, in executable form):

 - `tools/contract_audit.py` reports ZERO error-severity findings on all
   four targets (flags / imports / observability / threads) — errors are
   contract violations and are FIXED, never baselined;
 - warning/info counts are pinned to tests/contract_baseline.json (a new
   warning fails until acknowledged by re-recording) and the recorded
   baseline itself is empty or comment-justified;
 - `python tools/contract_audit.py --json` exits 0 (the CLI form);
 - conflicting-default `define_flag` re-definition raises; the
   idempotent same-default path and the set_flags-before-define
   (provisional) path keep working;
 - every flag in the runtime registry carries a non-empty help string;
 - each pass demonstrably fails on a planted violation (the full pos/neg
   matrix lives in tests/test_analysis_passes.py);
 - the ten subprocess no-import pins stay as belt-and-braces: one plain
   trainer+engine subprocess asserts EVERY manifest-lazy module is
   absent from sys.modules — the dynamic twin of the static closure
   check (and the pin for the newly-lazy monitor/blackbox.py).

Regenerate the baseline after an INTENTIONAL change:
    python tools/contract_audit.py --record
"""
import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "contract_baseline.json")
TARGETS = ("flags", "imports", "observability", "threads", "handoff",
           "pallas")   # handoff/pallas joined in ISSUE 13


def _load_tool():
    spec = importlib.util.spec_from_file_location(
        "contract_audit", os.path.join(REPO, "tools", "contract_audit.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def report():
    return _load_tool().build_report()


@pytest.fixture(scope="module")
def baseline():
    if not os.path.exists(BASELINE_PATH):
        pytest.fail("tests/contract_baseline.json missing — run "
                    "`python tools/contract_audit.py --record`")
    return json.load(open(BASELINE_PATH))


# ---------------------------------------------------------------------------
# the repo is clean
# ---------------------------------------------------------------------------


def test_all_targets_present(report):
    assert set(report["targets"]) == set(TARGETS)
    assert len(report["passes"]) >= 12   # the consolidated rule table


@pytest.mark.parametrize("target", TARGETS)
def test_zero_error_findings(report, target):
    rep = report["targets"][target]
    errors = [f for f in rep["findings"] if f["severity"] == "error"]
    assert errors == [], (
        f"{target}: contract violations (fix them — errors never go "
        "into the baseline):\n" + "\n".join(
            f"  [{f['pass']}] {f['message']} @ {f['where']}"
            for f in errors))


@pytest.mark.parametrize("target", TARGETS)
def test_warning_baseline(report, baseline, target):
    got = report["targets"][target]["counts"]["warning"]
    want = baseline["targets"][target]["warning"]
    assert got <= want, (
        f"{target}: {got} warning(s) vs recorded {want} — fix it or "
        "acknowledge via `python tools/contract_audit.py --record`")


def test_baseline_never_carries_errors(baseline):
    for name, counts in baseline["targets"].items():
        assert set(counts) <= {"warning", "info"}, (
            f"{name}: the baseline may only pin warning/info counts — "
            "error findings are fixed, not recorded")


def test_record_writes_counts_only(report, tmp_path):
    tool = _load_tool()
    path = tmp_path / "baseline.json"
    base = tool.record_baseline(report, path=str(path))
    on_disk = json.load(open(path))
    assert on_disk == base
    for counts in on_disk["targets"].values():
        assert set(counts) <= {"warning", "info"}


# ---------------------------------------------------------------------------
# rule-table consolidation (--list-rules)
# ---------------------------------------------------------------------------


def test_rule_table_is_consolidated():
    from paddle_tpu.analysis import (contract_rules, flag_audit,
                                     handoff_schema, import_graph,
                                     obs_audit, pallas_audit,
                                     sharding_flow, source_lint)
    from paddle_tpu.analysis.allowlist import spellings

    merged = contract_rules()
    for mod in (source_lint, flag_audit, import_graph, obs_audit,
                sharding_flow, handoff_schema, pallas_audit):
        for rule, sev in mod.RULES.items():
            assert merged[rule] == sev
    # every rule resolves to at least its own spelling; the documented
    # shorthands stay registered
    for rule in merged:
        assert spellings(rule)[0] == rule
    assert "client_output" in spellings("nonreduced-client-output")
    assert "thread-shared-write" in spellings(
        "unlocked-thread-shared-write")
    assert "lazy-import" in spellings("lazy-module-leak")
    assert "orphan-flag" in spellings("orphan-flag-unread")


def test_graph_lint_contracts_umbrella():
    """tools/graph_lint.py --contracts folds the auditor into the shared
    report (and --all includes it)."""
    spec = importlib.util.spec_from_file_location(
        "graph_lint", os.path.join(REPO, "tools", "graph_lint.py"))
    gl = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gl)
    rep = gl.build_report(contracts=True)
    for t in TARGETS:
        assert f"contract_{t}" in rep["targets"]
        assert rep["targets"][f"contract_{t}"]["counts"]["error"] == 0
    assert rep["totals"]["error"] == 0


# ---------------------------------------------------------------------------
# define_flag conflicting-default contract (ISSUE 12 satellite)
# ---------------------------------------------------------------------------


class TestDefineFlagConflicts:
    def test_conflicting_default_raises(self):
        from paddle_tpu import flags

        probe = "contract_gate_conflict_probe"
        try:
            flags.define_flag(probe, 1, "first")
            with pytest.raises(ValueError, match="conflicting defaults"):
                flags.define_flag(probe, 2, "second")
            # the registry keeps the FIRST (authoritative) definition
            assert flags.get_flag(probe) == 1
            assert flags._REGISTRY[probe]["default"] == 1
        finally:
            flags._REGISTRY.pop(probe, None)

    def test_type_change_is_a_conflict(self):
        from paddle_tpu import flags

        probe = "contract_gate_type_probe"
        try:
            flags.define_flag(probe, False, "bool flag")
            with pytest.raises(ValueError, match="conflicting defaults"):
                flags.define_flag(probe, 0, "int flag")   # False != 0 here
        finally:
            flags._REGISTRY.pop(probe, None)

    def test_same_default_redefine_is_idempotent(self):
        from paddle_tpu import flags

        probe = "contract_gate_idem_probe"
        try:
            flags.define_flag(probe, 5, "h")
            flags.set_flags({probe: 9})
            assert flags.define_flag(probe, 5, "h") == 9   # value kept
        finally:
            flags._REGISTRY.pop(probe, None)

    def test_set_flags_before_define_still_adopts(self):
        """The lazy-module pattern (tests/test_numerics_gate.py pins the
        original form): a user value set before the defining module
        loads survives, and the later real definition owns the default
        WITHOUT tripping the conflict check."""
        from paddle_tpu import flags

        probe = "contract_gate_provisional_probe"
        try:
            flags.set_flags({probe: 17})
            assert flags.define_flag(probe, 3, "late definer") == 17
            assert flags._REGISTRY[probe]["default"] == 3
            assert not flags._REGISTRY[probe].get("provisional")
        finally:
            flags._REGISTRY.pop(probe, None)


def test_every_registered_flag_has_help():
    """Acceptance criterion: no flag in the runtime registry without a
    help string — including the ones lazy modules define."""
    import paddle_tpu  # noqa: F401
    from paddle_tpu import flags
    # pull in every lazy flag-defining module
    import paddle_tpu.framework.aot  # noqa: F401
    import paddle_tpu.monitor.blackbox  # noqa: F401
    import paddle_tpu.monitor.numerics  # noqa: F401
    import paddle_tpu.testing.failpoints  # noqa: F401
    import paddle_tpu.trace  # noqa: F401
    import paddle_tpu.trace.costs  # noqa: F401

    missing = [n for n, e in flags._REGISTRY.items()
               if not e.get("provisional") and not e["help"]]
    assert missing == [], f"flags without help strings: {missing}"


# ---------------------------------------------------------------------------
# planted-violation smoke (full matrix in test_analysis_passes.py)
# ---------------------------------------------------------------------------


def test_each_pass_fails_on_a_planted_violation():
    from paddle_tpu.analysis import flag_audit, import_graph, obs_audit
    from paddle_tpu.analysis.source_lint import lint_thread_discipline

    fs = flag_audit.audit_inventory(
        flag_audit.collect({"m.py": 'define_flag("orphan_x", 0, "h")\n'}),
        hot_paths={}, lazy_modules=())
    assert any(f.pass_name == "orphan-flag-unread" for f in fs)

    g = import_graph.build_graph(sources={
        "p": "", "p.core": "from . import lazy_mod\n", "p.lazy_mod": ""})
    fs = import_graph.audit_graph(g, manifest=("p.lazy_mod",),
                                  roots=("p.core",))
    assert any(f.pass_name == "lazy-module-leak" for f in fs)

    doc = ("## Metric family reference\n\n| family |\n|---|\n"
           "## Span name reference\n\n| span |\n|---|\n")
    fs = obs_audit.audit_inventory(
        {"m.py": '_C = _monitor.counter("undoc_total", "h")\n'}, doc)
    assert any(f.pass_name == "metric-undocumented" for f in fs)

    src = ("import threading\n_LOCK = threading.Lock()\n_S = {}\n"
           "def w():\n    _S['k'] = 1\n"
           "threading.Thread(target=w).start()\n")
    fs = lint_thread_discipline(src, "m.py", "_LOCK")
    assert any(f.pass_name == "unlocked-thread-shared-write" for f in fs)


# ---------------------------------------------------------------------------
# end-to-end CLI + dynamic no-import pin (subprocesses)
# ---------------------------------------------------------------------------


def test_cli_json_exits_zero():
    """THE acceptance invocation: zero error findings, empty baseline."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "contract_audit.py"),
         "--json"],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    rep = json.loads(out.stdout)
    assert rep["tool"] == "contract_audit"
    assert set(rep["targets"]) == set(TARGETS)
    assert rep["totals"]["error"] == 0


def test_plain_process_imports_no_manifest_lazy_module():
    """Belt-and-braces for the static closure check: a plain trainer AND
    a plain engine in one subprocess, then every LAZY_MODULES name (and
    its subtree) must be absent from sys.modules. This is the dynamic
    pin for monitor/blackbox.py going manifest-lazy in ISSUE 12."""
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "import paddle_tpu as paddle\n"
        "from paddle_tpu import nn\n"
        "from paddle_tpu.distributed.mesh import build_mesh\n"
        "from paddle_tpu.distributed.spmd import SpmdTrainer\n"
        "from paddle_tpu.inference.serving import ServingEngine\n"
        "from paddle_tpu.models import GPTConfig, GPTForCausalLM\n"
        "paddle.seed(0)\n"
        "net = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 4))\n"
        "opt = paddle.optimizer.AdamW(learning_rate=1e-3,\n"
        "    parameters=net.parameters())\n"
        "mesh = build_mesh((1,), ('dp',), devices=jax.devices()[:1])\n"
        "tr = SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)\n"
        "x = paddle.to_tensor(np.ones((4, 8), np.float32))\n"
        "y = paddle.to_tensor(np.ones((4, 4), np.float32))\n"
        "tr.train_step(x, y)\n"
        "m = GPTForCausalLM(GPTConfig(vocab_size=64, hidden_size=32,\n"
        "    num_layers=1, num_heads=2, max_seq_len=32))\n"
        "m.eval()\n"
        "eng = ServingEngine(m, max_batch=1)\n"
        "eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=2)\n"
        "eng.run_until_complete()\n"
        "import sys\n"
        "from paddle_tpu.analysis.import_graph import LAZY_MODULES\n"
        "bad = [m for m in sys.modules\n"
        "       for entry in LAZY_MODULES\n"
        "       if m == entry or m.startswith(entry + '.')]\n"
        "assert not bad, f'manifest-lazy modules imported: {bad}'\n"
        "print('CLEAN')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, timeout=560,
                         env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "CLEAN" in out.stdout


if __name__ == "__main__":
    print(__doc__)
