"""Tier-1 acceptance gates for the paged KV + multi-LoRA mode (ISSUE 18).

Three gates, all tier-1 (deliberately NOT marked ``slow``):

1. **Import pinning** (subprocess): with ``FLAGS_paged_kv`` unset, the
   plain engine path never imports ``paddle_tpu.serving.paging`` — the
   dense hot path carries zero paging code, and its outputs are
   byte-identical to the same binary with the module importable.
2. **Scale parity**: ONE pooled engine holding 8 adapters serves 16
   concurrent sessions (2 per adapter) bit-exactly vs 8 dedicated
   single-adapter engines.
3. **Memory**: with prefix + adapter sharing, measured KV bytes per
   session is >= 2x lower than the dense per-slot cost — asserted from
   the pool's own accounting AND from the perf-ledger row the engine
   emits at site ``serving/paged_step``.
"""
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import flags
from paddle_tpu.models import GPTConfig, GPTForCausalLM

REPO = Path(__file__).resolve().parent.parent

CFG = dict(vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
           max_seq_len=64, dropout=0.0)


@pytest.fixture
def paged():
    old = flags.get_flag("paged_kv", False)
    paddle.set_flags({"paged_kv": True})
    yield
    paddle.set_flags({"paged_kv": old})


def _model():
    paddle.seed(0)
    m = GPTForCausalLM(GPTConfig(**CFG))
    m.eval()
    return m


def _export_adapter(model, seed):
    from paddle_tpu.incubate.lora import apply_lora, export_lora

    m2 = GPTForCausalLM(GPTConfig(**CFG))
    m2.load_dict(model.state_dict())
    apply_lora(m2, r=4, alpha=8)
    rng = np.random.RandomState(seed)
    for n_, p_ in m2.named_parameters():
        if "lora_B" in n_:
            p_.set_value(paddle.to_tensor(
                rng.normal(0, 0.3, p_.shape).astype(np.float32)))
    return export_lora(m2)


_GATE_CODE = r"""
import sys
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.inference.serving import ServingEngine

paddle.seed(0)
m = GPTForCausalLM(GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                             num_heads=2, max_seq_len=64, dropout=0.0))
m.eval()
eng = ServingEngine(m, max_batch=2)
rids = [eng.submit([3, 14, 15, 9], max_new_tokens=4),
        eng.submit([7, 1], max_new_tokens=4)]
res = eng.run_until_complete()
toks = [[int(t) for t in res[r].output_ids] for r in rids]
assert "paging" not in eng.stats(), "plain engine leaked paging stats"
assert "paddle_tpu.serving.paging" not in sys.modules, \
    "plain engine imported serving.paging"
print("TOKENS", toks)
print("GATE_OK")
"""


def test_plain_engine_never_imports_paging():
    """The dense path is structurally untouched: no paging import, no
    paging stats, and the flag default leaves behavior byte-identical
    (the printed token transcript is asserted stable across two runs)."""
    outs = []
    for _ in range(2):
        r = subprocess.run([sys.executable, "-c", _GATE_CODE], cwd=REPO,
                           capture_output=True, text=True, timeout=560)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "GATE_OK" in r.stdout
        outs.append([l for l in r.stdout.splitlines()
                     if l.startswith("TOKENS")])
    assert outs[0] == outs[1]


def test_pool_serves_8_adapters_16_sessions_bit_exact(paged):
    from paddle_tpu.inference.serving import ServingEngine

    m = _model()
    exports = {f"ad{i}": _export_adapter(m, seed=10 + i) for i in range(8)}
    prompts = [[3 + i, 14, 15 - i % 4] for i in range(16)]

    pooled = ServingEngine(m, max_batch=8, max_adapters=8)
    for name, exp in exports.items():
        pooled.load_adapter(name, exp)
    rids = [pooled.submit(list(prompts[i]), max_new_tokens=3,
                          adapter=f"ad{i % 8}") for i in range(16)]
    res = pooled.run_until_complete()
    pooled_out = [[int(t) for t in res[r].output_ids] for r in rids]

    dedicated_out = [None] * 16
    for a in range(8):
        eng = ServingEngine(m, max_batch=8, max_adapters=1)
        eng.load_adapter(f"ad{a}", exports[f"ad{a}"])
        mine = [i for i in range(16) if i % 8 == a]
        rs = [eng.submit(list(prompts[i]), max_new_tokens=3,
                         adapter=f"ad{a}") for i in mine]
        rr = eng.run_until_complete()
        for i, r in zip(mine, rs):
            dedicated_out[i] = [int(t) for t in rr[r].output_ids]

    assert pooled_out == dedicated_out
    st = pooled.stats()["paging"]
    assert st["adapters"]["loaded"] == 8


def test_kv_bytes_per_session_2x_below_dense(paged, tmp_path):
    """16 sessions sharing one registered 32-token prefix: the pool's
    measured bytes/session must be >= 2x below the dense per-slot cost,
    and the perf-ledger row at serving/paged_step must carry the same
    gate metric."""
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.monitor import perfledger as pl

    m = _model()
    eng = ServingEngine(m, max_batch=16)
    pid = eng.register_prefix(list(range(2, 34)))
    rids = [eng.submit([40 + i], prefix_id=pid, max_new_tokens=8)
            for i in range(16)]
    for _ in range(3):                      # all 16 admitted and decoding
        eng.step()

    st = eng.stats()["paging"]              # measured while sessions live
    assert st["live_sessions"] >= 16
    ratio = st["dense_bytes_per_session"] / st["kv_bytes_per_session"]
    assert ratio >= 2.0, f"sharing ratio {ratio:.2f} < 2x"

    led = pl.PerfLedger(path=str(tmp_path / "ledger.jsonl"))
    pl.record_engine(eng, ledger=led, site="serving")

    res = eng.run_until_complete()
    assert all(res[r].finish_reason == "length" for r in rids)

    rows = pl.load_rows(str(tmp_path / "ledger.jsonl"))
    paged_rows = [r for r in rows if r["site"] == "serving/paged_step"]
    assert paged_rows, "no serving/paged_step ledger row"
    mrow = paged_rows[-1]["metrics"]
    assert "kv_bytes_per_session" in mrow
    assert mrow["dense_bytes_per_session"] / \
        mrow["kv_bytes_per_session"] >= 2.0
