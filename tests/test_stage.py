"""MPMD stage-program runtime (ISSUE 15): typed backpressured edges, the
int8 row codec, schedule equivalence on StageGraph, unequal per-stage
meshes, per-stage AOT cache keys, the shared _pvary helper, stage span
lineage, and the disagg pool's hand-off-over-edge parity + metering."""
import os
import sys

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import flags, monitor, trace
from paddle_tpu.analysis.handoff_schema import HandoffMismatch
from paddle_tpu.distributed import compress as C
from paddle_tpu.distributed import stage as stage_mod
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.pipeline import PipelineTrainer
from paddle_tpu.distributed.stage import (EdgeEmptyError, EdgeFullError,
                                          StageEdge)
from paddle_tpu.models import GPTConfig, GPTForCausalLM

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


@pytest.fixture
def mpmd():
    old = flags.get_flag("mpmd", False)
    paddle.set_flags({"mpmd": True})
    yield
    paddle.set_flags({"mpmd": old})


def _pipeline(schedule="1F1B", n_pp=2, hidden=32, heads=2, **kw):
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=hidden, num_layers=n_pp,
                    num_heads=heads, max_seq_len=32, dropout=0.0)
    model = GPTForCausalLM(cfg)
    pre, stages, post = model.pipeline_split(n_pp)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    mesh = build_mesh((n_pp,), ("pp",), devices=jax.devices()[:n_pp])
    return PipelineTrainer(pre, stages, post, opt, mesh=mesh,
                           n_micro=n_pp, schedule_mode=schedule, **kw)


def _losses(tr, steps=3, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(steps):
        x = rng.randint(0, 64, (4, 16)).astype(np.int32)
        y = rng.randint(0, 64, (4, 16)).astype(np.int32)
        out.append(float(np.asarray(tr.train_step(x, y)._data)))
    return out


class TestStageEdge:
    def test_validate_rejects_shape_and_key_mismatch(self):
        edge = StageEdge("e", stage_mod.HANDOFF_SCHEMA, capacity=2)
        with pytest.raises(HandoffMismatch):
            edge.put({"activation": np.ones((2, 3), np.float32)})  # rank 2
        with pytest.raises(HandoffMismatch):
            edge.put({"wrong_key": np.ones((1, 2, 4), np.float32)})
        assert len(edge) == 0  # a rejected payload is never enqueued

    def test_backpressure_counts_and_drains_fifo(self):
        edge = StageEdge("e", stage_mod.HANDOFF_SCHEMA, capacity=2)
        rows = [np.full((1, 2, 4), float(i + 1), np.float32)
                for i in range(3)]
        edge.put({"activation": rows[0]})
        edge.put({"activation": rows[1]})
        assert edge.full()
        with pytest.raises(EdgeFullError):
            edge.put({"activation": rows[2]})
        assert edge.stats["backpressured"] == 1
        assert edge.stats["puts"] == 2  # the rejected put did no work
        got = [np.asarray(edge.get()["activation"]) for _ in range(2)]
        assert all(np.array_equal(g, r) for g, r in zip(got, rows))
        with pytest.raises(EdgeEmptyError):
            edge.get()

    def test_dense_edge_meters_wire_eq_logical(self):
        monitor.reset()
        edge = StageEdge("e", stage_mod.HANDOFF_SCHEMA, capacity=1)
        row = np.ones((2, 4, 8), np.float32)
        wire = edge.put({"activation": row})
        assert wire == row.nbytes
        assert edge.stats["wire_bytes"] == edge.stats["logical_bytes"]
        flat = monitor.flatten(monitor.snapshot())
        assert flat["kv_handoff_bytes_total"] == row.nbytes

    def test_quantized_edge_hits_wire_ratio_and_meters_savings(self):
        """The acceptance bar: a compress=8 activation edge moves >=3.5x
        fewer wire bytes than logical at feature dim 256 (per-row int8:
        ratio = 4/(1 + 4/D) -> 3.94x), and the savings land on the
        collective chokepoint as {op=stage_edge}."""
        monitor.reset()
        edge = StageEdge("q", stage_mod.HANDOFF_SCHEMA, capacity=4,
                         compress=8)
        rng = np.random.RandomState(0)
        for _ in range(3):
            edge.put({"activation":
                      rng.randn(2, 4, 256).astype(np.float32)})
        st = edge.stats
        ratio = st["logical_bytes"] / st["wire_bytes"]
        assert ratio >= 3.5, f"wire ratio {ratio:.2f} < 3.5"
        flat = monitor.flatten(monitor.snapshot())
        assert flat["kv_handoff_bytes_total"] == st["wire_bytes"]
        assert flat["collective_bytes_total{op=stage_edge}"] == \
            st["wire_bytes"]
        assert flat["collective_bytes_saved_total{op=stage_edge}"] == \
            st["logical_bytes"] - st["wire_bytes"]

    def test_quantized_roundtrip_stays_close(self):
        edge = StageEdge("q", stage_mod.HANDOFF_SCHEMA, capacity=1,
                         compress=8)
        rng = np.random.RandomState(1)
        row = rng.randn(1, 3, 64).astype(np.float32)
        edge.put({"activation": row})
        out = np.asarray(edge.get()["activation"])
        assert out.dtype == np.float32
        # per-row int8: error bounded by half a quantization step
        bound = np.abs(row).max(axis=-1, keepdims=True) / 127.0
        assert np.all(np.abs(out - row) <= bound * 0.51 + 1e-8)


class TestRowCodec:
    def test_roundtrip_deterministic(self):
        rng = np.random.RandomState(2)
        x = rng.randn(5, 32).astype(np.float32)
        q1, s1 = C.quantize_rows(x)
        q2, s2 = C.quantize_rows(x)
        assert np.array_equal(np.asarray(q1), np.asarray(q2))
        assert np.array_equal(np.asarray(s1), np.asarray(s2))
        assert np.asarray(q1).dtype == np.int8
        assert np.asarray(s1).shape == (5, 1)
        back = np.asarray(C.dequantize_rows(q1, s1))
        step = np.abs(x).max(axis=-1, keepdims=True) / 127.0
        assert np.all(np.abs(back - x) <= step * 0.51 + 1e-8)

    def test_zero_row_is_exact(self):
        q, s = C.quantize_rows(np.zeros((2, 8), np.float32))
        assert np.array_equal(np.asarray(C.dequantize_rows(q, s)),
                              np.zeros((2, 8), np.float32))

    def test_nan_poisons_only_its_row(self):
        x = np.ones((2, 4), np.float32)
        x[0, 1] = np.nan
        back = np.asarray(C.dequantize_rows(*C.quantize_rows(x)))
        assert not np.all(np.isfinite(back[0]))
        assert np.allclose(back[1], x[1], atol=1e-2)


class TestPvaryDedupe:
    def test_single_definition_shared_by_both_consumers(self):
        """Satellite 1: one _pvary, owned by spmd — the pipeline and
        long-context modules alias it instead of carrying copies."""
        from paddle_tpu.distributed import long_context, pipeline, spmd

        assert pipeline._vary is spmd._pvary
        assert long_context._vary is spmd._pvary

    def test_identity_fallback_without_pcast_or_pvary(self, monkeypatch):
        """On jax builds with NEITHER pcast nor pvary the helper is the
        identity (shard_map cotangents are already rank-local there)."""
        from paddle_tpu.distributed import spmd

        monkeypatch.delattr(jax.lax, "pcast", raising=False)
        monkeypatch.delattr(jax.lax, "pvary", raising=False)
        x = object()
        assert spmd._pvary(x, "dp") is x


class TestSchedulesAndMeshes:
    def test_armed_1f1b_matches_disarmed_loss_exactly(self, mpmd):
        paddle.set_flags({"mpmd": False})
        ref = _losses(_pipeline())
        paddle.set_flags({"mpmd": True})
        assert _losses(_pipeline()) == ref

    def test_all_schedules_bit_equal(self, mpmd):
        ref = _losses(_pipeline("1F1B"))
        assert _losses(_pipeline("F-then-B")) == ref
        assert _losses(_pipeline("interleaved")) == ref

    def test_unequal_stage_meshes_train_to_same_loss(self, mpmd):
        """Satellite 5: a 2-stage graph with DIFFERENT per-stage device
        counts (1 vs 3) trains to the same loss as the equal-mesh run —
        stage programs replicate within their own mesh, so mesh width
        is a placement choice, not a numerics choice."""
        ref = _losses(_pipeline())
        meshes = [build_mesh((1,), ("stage",), devices=jax.devices()[:1]),
                  build_mesh((3,), ("stage",),
                             devices=jax.devices()[1:4])]
        tr = _pipeline(stage_meshes=meshes)
        assert [len(m.devices.ravel())
                for m in tr._mpmd_runner.stage_meshes] == [1, 3]
        got = _losses(tr)
        np.testing.assert_allclose(got, ref, rtol=0, atol=1e-6)

    def test_quantized_edge_trains_close_and_meters(self, mpmd):
        monitor.reset()
        ref = _losses(_pipeline(hidden=64, heads=4))
        got = _losses(_pipeline(hidden=64, heads=4, compress=8))
        np.testing.assert_allclose(got, ref, rtol=0, atol=5e-2)
        flat = monitor.flatten(monitor.snapshot())
        saved = flat["collective_bytes_saved_total{op=stage_edge}"]
        wire = flat["collective_bytes_total{op=stage_edge}"]
        assert (saved + wire) / wire >= 3.5  # logical/wire at d=64


class TestPerStageAotCache:
    def test_disk_entries_keyed_by_each_stages_mesh_fingerprint(
            self, mpmd, tmp_path):
        """Satellite 5: each stage program compiles through the PR 3 AOT
        cache with ITS OWN mesh fingerprint in the key — a rebuilt
        trainer replays every stage program from disk (hit/disk), and
        the two stages' fingerprints genuinely differ."""
        from paddle_tpu.framework import aot

        old = flags.get_flag("jit_cache_dir", "")
        paddle.set_flags({"jit_cache_dir": str(tmp_path)})
        try:
            _losses(_pipeline(), steps=1)
            monitor.reset()
            tr = _pipeline()
            _losses(tr, steps=1)
            flat = monitor.flatten(monitor.snapshot())
            disk_hits = {k: v for k, v in flat.items()
                         if k.startswith("compile_cache_total")
                         and "site=stage" in k and "source=disk" in k}
            assert disk_hits, f"no stage disk hits: {sorted(flat)}"
            # every stage program (fwd0/bwd0/last1 + optimizer) replays
            sigs = {k.split("sig=")[1].split(",")[0].rstrip("}")
                    for k in disk_hits}
            assert {"fwd0", "bwd0", "last1", "optimizer"} <= sigs
            # each program's cache key carries ITS stage's fingerprint
            runner = tr._mpmd_runner
            for k, prog_name in ((0, "fwd0"), (1, "last1")):
                fp = aot.mesh_fingerprint(runner.stage_meshes[k])
                assert fp in runner.programs[prog_name]._jit._extra_key
            # the fingerprint is a topology identity: same-width stage
            # meshes share it (executables are offerable across them),
            # different widths never alias
            wide = build_mesh((3,), ("stage",), devices=jax.devices()[:3])
            assert aot.mesh_fingerprint(wide) != \
                aot.mesh_fingerprint(runner.stage_meshes[0])
        finally:
            paddle.set_flags({"jit_cache_dir": old})


class TestStageSpans:
    def test_stage_step_spans_share_one_trace_id(self, mpmd):
        tr = _pipeline()
        _losses(tr, steps=1)
        trace.clear()
        trace.enable()
        try:
            _losses(tr, steps=1, seed=1)
        finally:
            trace.disable()
        roots = [s for s in trace.spans() if s.name == "stage_graph"]
        ticks = [s for s in trace.spans() if s.name == "stage_step"]
        assert len(roots) == 1
        assert ticks and all(s.trace_id == roots[0].trace_id
                             for s in ticks)
        assert all(s.subsystem == "stage" for s in roots + ticks)


class TestDisaggOverEdge:
    def _pool(self, m, **kw):
        from paddle_tpu.serving.disagg import DisaggregatedPool

        return DisaggregatedPool(m, prefill_workers=1, decode_engines=1,
                                 max_batch=2, **kw)

    def test_armed_pool_byte_identical_and_edge_metered(self, mpmd):
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_heads=2, max_seq_len=64, dropout=0.0)
        m = GPTForCausalLM(cfg)
        m.eval()
        rng = np.random.RandomState(0)
        prompts = [rng.randint(0, 64, (n,)).astype(np.int32)
                   for n in (5, 8, 4)]
        paddle.set_flags({"mpmd": False})
        ref_pool = self._pool(m)
        ref_ids = [ref_pool.submit(p, max_new_tokens=5) for p in prompts]
        ref = ref_pool.run_until_complete()
        paddle.set_flags({"mpmd": True})
        monitor.reset()
        pool = self._pool(m)
        rids = [pool.submit(p, max_new_tokens=5) for p in prompts]
        res = pool.run_until_complete()
        for a, b in zip(ref_ids, rids):
            np.testing.assert_array_equal(ref[a].tokens, res[b].tokens)
        st = pool.stats()["edge"]
        assert st["puts"] == st["gets"] == len(prompts)
        assert st["wire_bytes"] == st["logical_bytes"]  # dense hand-off
        flat = monitor.flatten(monitor.snapshot())
        assert flat["kv_handoff_bytes_total"] == st["wire_bytes"]
