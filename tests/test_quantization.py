"""Quantization tests (slim parity: QAT + PTQ + fake-quant ops)."""
import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.quantization import (
    ImperativeQuantAware, PostTrainingQuantization, QuantConfig,
    fake_quantize_abs_max, fake_quantize_channel_wise_abs_max,
    fake_quantize_moving_average_abs_max, quantize_to_int8,
)
from paddle_tpu.quantization.layers import Int8Linear, QuantedConv2D, QuantedLinear


class TestFakeQuantOps:
    def test_abs_max_error_bound_and_ste(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(64, 32).astype(np.float32))
        q, scale = fake_quantize_abs_max(x)
        assert float(scale) == float(jnp.max(jnp.abs(x)))
        # max quantization error <= scale/127/2 (round-to-nearest)
        assert float(jnp.max(jnp.abs(q - x))) <= float(scale) / 127 / 2 + 1e-6
        # straight-through: gradient of sum(q) w.r.t. x is all-ones
        g = jax.grad(lambda v: jnp.sum(fake_quantize_abs_max(v)[0]))(x)
        np.testing.assert_allclose(np.asarray(g), np.ones_like(np.asarray(g)))

    def test_channel_wise_scales(self):
        x = jnp.stack([jnp.ones((8,)) * 1.0, jnp.ones((8,)) * 4.0], axis=1)  # [8,2]
        q, scales = fake_quantize_channel_wise_abs_max(x, axis=-1)
        np.testing.assert_allclose(np.asarray(scales), [1.0, 4.0])
        np.testing.assert_allclose(np.asarray(q), np.asarray(x), atol=1e-6)

    def test_moving_average_updates(self):
        x1 = jnp.ones((4,)) * 2.0
        s0 = jnp.zeros([])
        _, s1 = fake_quantize_moving_average_abs_max(x1, s0, training=True)
        assert float(s1) == 2.0  # first step adopts current max
        x2 = jnp.ones((4,)) * 4.0
        _, s2 = fake_quantize_moving_average_abs_max(x2, s1, rate=0.9, training=True)
        np.testing.assert_allclose(float(s2), 0.9 * 2.0 + 0.1 * 4.0, rtol=1e-6)
        # eval mode keeps the stored scale
        _, s3 = fake_quantize_moving_average_abs_max(x2, s2, training=False)
        np.testing.assert_allclose(float(s3), float(s2), rtol=1e-6)

    def test_int8_roundtrip(self):
        rng = np.random.RandomState(1)
        w = jnp.asarray(rng.randn(16, 8).astype(np.float32))
        q, s = quantize_to_int8(w, axis=-1)
        assert q.dtype == jnp.int8
        back = np.asarray(q, np.float32) / 127.0 * np.asarray(s)
        np.testing.assert_allclose(back, np.asarray(w), atol=float(s.max()) / 127)


class TestQAT:
    def _mlp(self):
        class MLP(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(16, 32)
                self.fc2 = nn.Linear(32, 4)

            def forward(self, x):
                return self.fc2(paddle.nn.functional.relu(self.fc1(x)))

        return MLP()

    def test_quantize_replaces_layers_and_trains(self):
        paddle.seed(0)
        model = self._mlp()
        n = ImperativeQuantAware().quantize(model)
        assert n == 2
        assert isinstance(model.fc1, QuantedLinear)
        assert isinstance(model.fc2, QuantedLinear)

        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=model.parameters())
        x = paddle.randn([8, 16])
        y = paddle.to_tensor(np.random.RandomState(0).randint(0, 4, (8,)))
        losses = []
        for _ in range(5):
            loss = paddle.nn.functional.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss._data)))
        assert losses[-1] < losses[0]
        # observer ran: activation scale is positive
        assert float(np.asarray(model.fc1.act_scale._data)) > 0

    def test_conv_quantization_on_lenet(self):
        from paddle_tpu.vision.models import LeNet

        paddle.seed(0)
        model = LeNet()
        n = ImperativeQuantAware(config=QuantConfig()).quantize(model)
        assert n >= 3  # 2 convs + linears
        x = paddle.randn([2, 1, 28, 28])
        out = model(x)
        assert tuple(out.shape)[0] == 2
        quanted = [l for l in model.sublayers()
                   if isinstance(l, (QuantedConv2D, QuantedLinear))]
        assert len(quanted) == n

    def test_skip_layers(self):
        model = self._mlp()
        n = ImperativeQuantAware(skip_layers=("fc2",)).quantize(model)
        assert n == 1
        assert isinstance(model.fc1, QuantedLinear)
        assert isinstance(model.fc2, nn.Linear)


class TestPTQ:
    def test_calibrate_convert_accuracy(self):
        paddle.seed(0)

        class MLP(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(16, 64)
                self.fc2 = nn.Linear(64, 8)

            def forward(self, x):
                return self.fc2(paddle.nn.functional.relu(self.fc1(x)))

        model = MLP()
        model.eval()
        rng = np.random.RandomState(0)
        calib = [paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
                 for _ in range(4)]
        ref = np.asarray(model(calib[0])._data)

        ptq = PostTrainingQuantization(model, algo="abs_max")
        for b in calib:
            ptq.collect(model, b)
        n = ptq.convert(model)
        assert n == 2
        assert isinstance(model.fc1, Int8Linear)

        out = np.asarray(model(calib[0])._data)
        # int8 sim should stay close to float (scale-bounded error)
        rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
        assert rel < 0.05, f"int8 rel err {rel}"

    def test_hist_algo_percentile_scale(self):
        from paddle_tpu.quantization.ptq import _Observer

        obs = _Observer(algo="hist", percentile=0.5)
        obs.collect(np.linspace(-1, 1, 1001))
        assert 0.4 < obs.scale() < 0.6  # median of |x| ~ 0.5
        assert obs.abs_max == 1.0
