"""Quantization tests (slim parity: QAT + PTQ + fake-quant ops)."""
import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.quantization import (
    ImperativeQuantAware, PostTrainingQuantization, QuantConfig,
    fake_quantize_abs_max, fake_quantize_channel_wise_abs_max,
    fake_quantize_moving_average_abs_max, quantize_to_int8,
)
from paddle_tpu.quantization.layers import Int8Linear, QuantedConv2D, QuantedLinear


class TestFakeQuantOps:
    def test_abs_max_error_bound_and_ste(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(64, 32).astype(np.float32))
        q, scale = fake_quantize_abs_max(x)
        assert float(scale) == float(jnp.max(jnp.abs(x)))
        # max quantization error <= scale/127/2 (round-to-nearest)
        assert float(jnp.max(jnp.abs(q - x))) <= float(scale) / 127 / 2 + 1e-6
        # straight-through: gradient of sum(q) w.r.t. x is all-ones
        g = jax.grad(lambda v: jnp.sum(fake_quantize_abs_max(v)[0]))(x)
        np.testing.assert_allclose(np.asarray(g), np.ones_like(np.asarray(g)))

    def test_channel_wise_scales(self):
        x = jnp.stack([jnp.ones((8,)) * 1.0, jnp.ones((8,)) * 4.0], axis=1)  # [8,2]
        q, scales = fake_quantize_channel_wise_abs_max(x, axis=-1)
        np.testing.assert_allclose(np.asarray(scales), [1.0, 4.0])
        np.testing.assert_allclose(np.asarray(q), np.asarray(x), atol=1e-6)

    def test_moving_average_updates(self):
        x1 = jnp.ones((4,)) * 2.0
        s0 = jnp.zeros([])
        _, s1 = fake_quantize_moving_average_abs_max(x1, s0, training=True)
        assert float(s1) == 2.0  # first step adopts current max
        x2 = jnp.ones((4,)) * 4.0
        _, s2 = fake_quantize_moving_average_abs_max(x2, s1, rate=0.9, training=True)
        np.testing.assert_allclose(float(s2), 0.9 * 2.0 + 0.1 * 4.0, rtol=1e-6)
        # eval mode keeps the stored scale
        _, s3 = fake_quantize_moving_average_abs_max(x2, s2, training=False)
        np.testing.assert_allclose(float(s3), float(s2), rtol=1e-6)

    def test_range_abs_max_window(self):
        from paddle_tpu.quantization import fake_quantize_range_abs_max

        win = jnp.zeros((3,))
        it = jnp.asarray(0, jnp.int32)
        q, win, it, s1 = fake_quantize_range_abs_max(
            jnp.ones((4,)) * 2.0, win, it, window_size=3, training=True)
        assert float(s1) == 2.0 and int(it) == 1
        _, win, it, s2 = fake_quantize_range_abs_max(
            jnp.ones((4,)) * 8.0, win, it, window_size=3, training=True)
        assert float(s2) == 8.0
        # two more small steps evict the 8.0 entry from the 3-slot window
        for v in (1.0, 1.0, 1.0):
            _, win, it, s = fake_quantize_range_abs_max(
                jnp.ones((4,)) * v, win, it, window_size=3, training=True)
        np.testing.assert_allclose(float(s), 1.0, rtol=1e-6)
        # eval: quantize with the stored window max, no state update
        _, win2, it2, se = fake_quantize_range_abs_max(
            jnp.ones((4,)) * 99.0, win, it, window_size=3, training=False)
        assert float(se) == 1.0 and int(it2) == int(it)

    def test_int8_roundtrip(self):
        rng = np.random.RandomState(1)
        w = jnp.asarray(rng.randn(16, 8).astype(np.float32))
        q, s = quantize_to_int8(w, axis=-1)
        assert q.dtype == jnp.int8
        back = np.asarray(q, np.float32) / 127.0 * np.asarray(s)
        np.testing.assert_allclose(back, np.asarray(w), atol=float(s.max()) / 127)


class TestQAT:
    def _mlp(self):
        class MLP(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(16, 32)
                self.fc2 = nn.Linear(32, 4)

            def forward(self, x):
                return self.fc2(paddle.nn.functional.relu(self.fc1(x)))

        return MLP()

    def test_quantize_replaces_layers_and_trains(self):
        paddle.seed(0)
        model = self._mlp()
        n = ImperativeQuantAware().quantize(model)
        assert n == 2
        assert isinstance(model.fc1, QuantedLinear)
        assert isinstance(model.fc2, QuantedLinear)

        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=model.parameters())
        x = paddle.randn([8, 16])
        y = paddle.to_tensor(np.random.RandomState(0).randint(0, 4, (8,)))
        losses = []
        for _ in range(5):
            loss = paddle.nn.functional.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss._data)))
        assert losses[-1] < losses[0]
        # observer ran: activation scale is positive
        assert float(np.asarray(model.fc1.act_scale._data)) > 0

    def test_conv_quantization_on_lenet(self):
        from paddle_tpu.vision.models import LeNet

        paddle.seed(0)
        model = LeNet()
        n = ImperativeQuantAware(config=QuantConfig()).quantize(model)
        assert n >= 3  # 2 convs + linears
        x = paddle.randn([2, 1, 28, 28])
        out = model(x)
        assert tuple(out.shape)[0] == 2
        quanted = [l for l in model.sublayers()
                   if isinstance(l, (QuantedConv2D, QuantedLinear))]
        assert len(quanted) == n

    def test_skip_layers(self):
        model = self._mlp()
        n = ImperativeQuantAware(skip_layers=("fc2",)).quantize(model)
        assert n == 1
        assert isinstance(model.fc1, QuantedLinear)
        assert isinstance(model.fc2, nn.Linear)


class TestPTQ:
    def test_calibrate_convert_accuracy(self):
        paddle.seed(0)

        class MLP(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(16, 64)
                self.fc2 = nn.Linear(64, 8)

            def forward(self, x):
                return self.fc2(paddle.nn.functional.relu(self.fc1(x)))

        model = MLP()
        model.eval()
        rng = np.random.RandomState(0)
        calib = [paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
                 for _ in range(4)]
        ref = np.asarray(model(calib[0])._data)

        ptq = PostTrainingQuantization(model, algo="abs_max")
        for b in calib:
            ptq.collect(model, b)
        n = ptq.convert(model)
        assert n == 2
        assert isinstance(model.fc1, Int8Linear)

        out = np.asarray(model(calib[0])._data)
        # int8 sim should stay close to float (scale-bounded error)
        rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
        assert rel < 0.05, f"int8 rel err {rel}"

    def test_hist_algo_percentile_scale(self):
        from paddle_tpu.quantization.ptq import _Observer

        obs = _Observer(algo="hist", percentile=0.5)
        obs.collect(np.linspace(-1, 1, 1001))
        assert 0.4 < obs.scale() < 0.6  # median of |x| ~ 0.5
        assert obs.abs_max == 1.0


class TestInt8Deployment:
    """VERDICT r2 #6: PTQ -> saved int8 artifact -> Predictor serve
    round-trip with <1% accuracy drop on the LeNet/MNIST-style pipeline."""

    def _trained_lenet(self):
        from paddle_tpu.vision.models import LeNet

        paddle.seed(0)
        rng = np.random.RandomState(0)
        means = rng.randn(10, 1, 28, 28).astype(np.float32)
        ys = rng.randint(0, 10, 512)
        xs = (means[ys] + 0.15 * rng.randn(512, 1, 28, 28)).astype(np.float32)

        net = LeNet()
        opt = paddle.optimizer.Adam(learning_rate=2e-3,
                                    parameters=net.parameters())
        loss_fn = paddle.nn.CrossEntropyLoss()
        for i in range(12):
            sl = slice((i % 4) * 128, (i % 4) * 128 + 128)
            out = net(paddle.to_tensor(xs[sl]))
            loss = loss_fn(out, paddle.to_tensor(ys[sl].astype(np.int64)))
            loss.backward()
            opt.step()
            opt.clear_grad()
        net.eval()
        return net, xs, ys

    @staticmethod
    def _acc(logits, ys):
        return float((np.argmax(logits, -1) == ys).mean())

    def test_ptq_save_serve_roundtrip(self, tmp_path):
        from paddle_tpu.quantization import save_quantized_model
        from paddle_tpu.static.io import _load_params_npz, load_aot_predictor

        net, xs, ys = self._trained_lenet()
        fp_acc = self._acc(np.asarray(net(paddle.to_tensor(xs))._data), ys)
        assert fp_acc > 0.9, fp_acc  # the float pipeline must actually work

        ptq = PostTrainingQuantization(net, algo="abs_max")
        for i in range(4):
            ptq.collect(net, paddle.to_tensor(xs[i * 128:(i + 1) * 128]))
        assert ptq.convert(net) == 3  # all three fc Linears

        prefix = str(tmp_path / "lenet_int8")
        save_quantized_model(
            net, prefix,
            [paddle.jit.InputSpec([None, 1, 28, 28], "float32")])

        # the saved artifact really stores int8 weights
        params = _load_params_npz(prefix + ".pdiparams.npz")
        int8_keys = [k for k, v in params.items() if v.dtype == np.int8]
        assert len(int8_keys) == 3, sorted(params)

        predict = load_aot_predictor(prefix)
        out = predict(xs[:256])
        out = out[0] if isinstance(out, (tuple, list)) else out
        q_acc = self._acc(np.asarray(out._data), ys[:256])
        fp_acc_sub = self._acc(
            np.asarray(net(paddle.to_tensor(xs[:256]))._data), ys[:256])
        assert q_acc >= fp_acc_sub - 0.01, (q_acc, fp_acc_sub)

    def test_int8_artifact_serves_fresh_process(self, tmp_path):
        import os
        import subprocess
        import sys
        import textwrap

        from paddle_tpu.quantization import save_quantized_model

        net, xs, ys = self._trained_lenet()
        ptq = PostTrainingQuantization(net, algo="abs_max")
        ptq.collect(net, paddle.to_tensor(xs[:128]))
        ptq.convert(net)
        want = np.asarray(net(paddle.to_tensor(xs[:4]))._data)
        prefix = str(tmp_path / "fresh_int8")
        save_quantized_model(net, prefix, [paddle.to_tensor(xs[:4])])
        np.save(str(tmp_path / "x.npy"), xs[:4])
        np.save(str(tmp_path / "want.npy"), want)

        script = textwrap.dedent(f"""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import numpy as np
            from paddle_tpu.inference import Config, create_predictor

            pred = create_predictor(Config(model_path={prefix!r}))
            x = np.load({str(tmp_path / 'x.npy')!r})
            want = np.load({str(tmp_path / 'want.npy')!r})
            h = pred.get_input_handle("input_0")
            h.copy_from_cpu(x)
            (got,) = pred.run()
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
            print("INT8_SERVED_OK")
        """)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        r = subprocess.run([sys.executable, "-c", script], env=env,
                           capture_output=True, text=True, timeout=560)
        assert "INT8_SERVED_OK" in r.stdout, r.stdout + r.stderr
