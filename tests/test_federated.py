"""Federated MapReduce primitives + FedAvg loop (paddle_tpu.federated).

Covers the ISSUE 8 satellite checklist: forward/grad parity of
client_map+federated_sum against a hand-rolled sequential per-client
loop (bit-for-bit on the 8-virtual-device CPU harness; the clients axis
sharded over 1/2/8-device meshes), LoRA-adapter FedAvg convergence on a
toy task with the aggregation bytes verified through the metered
collective chokepoint, weighted-mean correctness with unequal client
example counts, and federated/round failpoint coverage (client dropout
mid-round -> the round completes with the surviving cohort).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, nn, trace
from paddle_tpu.distributed.mesh import client_mesh
from paddle_tpu.federated import (FederatedAverager, broadcast_to_clients,
                                  client_map, federated_mean, federated_sum,
                                  federated_weighted_mean, in_client_map,
                                  num_clients, partition_clients)
from paddle_tpu.incubate.lora import apply_lora, lora_parameters
from paddle_tpu.testing import failpoints

C, B, D = 8, 4, 3


def _local_loss(w, x, y):
    return jnp.mean((x @ w - y) ** 2)


@pytest.fixture
def data():
    rng = np.random.RandomState(0)
    return (rng.randn(C, B, D).astype(np.float32),
            rng.randn(C, B).astype(np.float32),
            rng.randn(D).astype(np.float32))


class TestClientMapParity:
    def test_forward_matches_sequential_loop_bitwise(self, data):
        xs, ys, w = data
        fed = client_map(lambda x, y: federated_sum(_local_loss(w, x, y)),
                         xs, ys)
        assert fed.shape == (C,)          # every client holds the total
        ref = jnp.stack([_local_loss(w, xs[i], ys[i])
                         for i in range(C)]).sum(0)
        np.testing.assert_array_equal(np.asarray(fed),
                                      np.broadcast_to(np.asarray(ref), (C,)))

    def test_grads_match_sequential_loop_bitwise(self, data):
        """The MapReduce gradient form — per-client grads aggregated by
        federated_sum — is BIT-FOR-BIT the sequential per-client
        reference on the 8-virtual-device CPU harness."""
        xs, ys, w = data
        g_fed = np.asarray(client_map(
            lambda x, y: federated_sum(jax.grad(_local_loss)(w, x, y)),
            xs, ys))[0]
        g_seq = np.asarray(jnp.stack(
            [jax.grad(_local_loss)(w, xs[i], ys[i])
             for i in range(C)]).sum(0))
        np.testing.assert_array_equal(g_fed, g_seq)

    def test_grad_through_psum_is_differentiable(self, data):
        """d/dw of a psum-reduced loss: the reduce itself differentiates
        (DrJAX's core claim); matches the sequential loop to float32
        accuracy (contraction order differs between batched and
        sequential lowering, so this one is allclose, not bitwise)."""
        xs, ys, w = data

        def fed_loss(w_):
            return client_map(
                lambda x, y: federated_sum(_local_loss(w_, x, y)),
                xs, ys)[0]

        def ref_loss(w_):
            return jnp.stack([_local_loss(w_, xs[i], ys[i])
                              for i in range(C)]).sum(0)

        np.testing.assert_allclose(np.asarray(jax.grad(fed_loss)(w)),
                                   np.asarray(jax.grad(ref_loss)(w)),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("n_devices", [1, 2, 8])
    def test_clients_axis_sharded_over_mesh(self, data, n_devices):
        """The same program with the clients dim sharded over a 1/2/8-
        device `clients` mesh axis: forward stays bit-identical; grads
        stay float32-close (a cross-DEVICE psum accumulates shard-major,
        a physically different fp add order)."""
        xs, ys, w = data
        mesh = client_mesh(n_devices)
        l_seq = np.asarray(jnp.stack([_local_loss(w, xs[i], ys[i])
                                      for i in range(C)]).sum(0))
        g_seq = np.asarray(jnp.stack(
            [jax.grad(_local_loss)(w, xs[i], ys[i])
             for i in range(C)]).sum(0))
        l = client_map(lambda x, y: federated_sum(_local_loss(w, x, y)),
                       xs, ys, mesh=mesh)
        g = client_map(
            lambda x, y: federated_sum(jax.grad(_local_loss)(w, x, y)),
            xs, ys, mesh=mesh)
        np.testing.assert_array_equal(np.asarray(l)[0], l_seq)
        if n_devices == 1:   # single shard: same add order as the loop
            np.testing.assert_array_equal(np.asarray(g)[0], g_seq)
        else:
            np.testing.assert_allclose(np.asarray(g)[0], g_seq,
                                       rtol=1e-5, atol=1e-6)

    def test_mesh_rejects_non_leading_in_axes(self, data):
        xs, _, _ = data
        with pytest.raises(ValueError, match="LEADING axis"):
            client_map(lambda x: federated_sum(x.sum()),
                       np.moveaxis(xs, 0, 1), mesh=client_mesh(2),
                       in_axes=1)

    def test_broadcast_and_axis_introspection(self):
        out = broadcast_to_clients(
            np.arange(6, dtype=np.float32).reshape(2, 3), 4)
        assert out.shape == (4, 2, 3)
        np.testing.assert_array_equal(np.asarray(out)[0],
                                      np.asarray(out)[3])
        assert num_clients(out) == 4
        assert not in_client_map()
        seen = client_map(lambda x: jnp.asarray(num_clients(), np.int32)
                          + 0 * x[0, 0], out)
        np.testing.assert_array_equal(np.asarray(seen),
                                      np.full((4,), 4, np.int32))

    def test_tensor_args_keep_autograd_with_mesh(self, data):
        """Tensor args ride the tape even when the clients dim is
        sharded over a mesh (the reshard is placement-only and must not
        detach the leaf)."""
        xs, _, _ = data
        t = paddle.to_tensor(xs)
        t.stop_gradient = False
        out = client_map(lambda x: federated_sum(jnp.sum(x * x)),
                         t, mesh=client_mesh(2))
        assert not out.stop_gradient
        out.backward(paddle.to_tensor(
            np.ones(out.shape, np.float32) / C))
        assert t.grad is not None
        np.testing.assert_allclose(np.asarray(t.grad._data), 2 * xs,
                                   rtol=1e-5)

    def test_broadcast_to_clients_differentiable(self):
        """The reverse of a broadcast is a cross-client sum; Tensor
        inputs keep their tape link."""
        w = paddle.to_tensor(np.arange(3, dtype=np.float32))
        w.stop_gradient = False
        y = broadcast_to_clients(w, 4)
        assert not y.stop_gradient
        (y * y).backward(paddle.to_tensor(np.ones((4, 3), np.float32)))
        np.testing.assert_allclose(np.asarray(w.grad._data),
                                   4 * 2 * np.arange(3, dtype=np.float32),
                                   rtol=1e-6)

    def test_federated_mean_inside_and_outside_map(self, data):
        xs, _, _ = data
        ref = np.asarray(xs.mean(0))
        outside = np.asarray(federated_mean(xs))
        inside = np.asarray(client_map(lambda x: federated_mean(x), xs))[0]
        np.testing.assert_allclose(outside, ref, rtol=1e-6)
        np.testing.assert_allclose(inside, ref, rtol=1e-6)


class TestWeightedMean:
    def test_unequal_client_example_counts(self):
        rng = np.random.RandomState(3)
        vals = rng.randn(5, 4, 2).astype(np.float32)
        counts = np.array([1.0, 7.0, 2.0, 5.0, 3.0], np.float32)
        got = np.asarray(federated_weighted_mean(vals, counts))
        ref = np.average(vals, axis=0, weights=counts)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_weighted_mean_inside_map_matches_outside(self):
        rng = np.random.RandomState(4)
        vals = rng.randn(6, 3).astype(np.float32)
        wts = np.array([1, 2, 3, 4, 5, 6], np.float32)
        outside = np.asarray(federated_weighted_mean(vals, wts))
        inside = np.asarray(client_map(
            lambda v, w: federated_weighted_mean(v, w), vals, wts))[0]
        np.testing.assert_allclose(inside, outside, rtol=1e-5, atol=1e-6)

    def test_metered_through_collective_chokepoint(self):
        """The reduce is byte-metered as op=federated_sum: numerator
        bytes == the stacked payload, denominator == the weight vector."""
        monitor.reset()
        vals = np.ones((4, 10), np.float32)
        wts = np.ones((4,), np.float32)
        federated_weighted_mean(vals, wts)
        flat = monitor.flatten(monitor.snapshot())
        assert flat["collective_bytes_total{op=federated_sum}"] == \
            vals.nbytes + wts.nbytes
        assert flat["collective_calls_total{op=federated_sum}"] == 2.0


def _lora_setup(n_clients=4, batch_size=16):
    paddle.seed(0)
    rng = np.random.RandomState(0)
    net = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 4))
    apply_lora(net, r=4, alpha=8)          # bases frozen, adapters train
    true_w = rng.randn(8, 4).astype(np.float32) * 0.5
    X = rng.randn(64, 8).astype(np.float32)
    Y = (X @ true_w).astype(np.float32)
    clients = partition_clients((X, Y), n_clients, batch_size=batch_size)
    return net, clients


class TestFedAvgLoRA:
    def test_lora_fedavg_converges_and_meters_adapter_bytes(self):
        """The acceptance run: >=4 clients, only LoRA adapters travel,
        pinned toy-task loss reached, and
        collective_bytes_total{op=federated_sum} equals EXACTLY the
        aggregated adapter payload (stacked adapter deltas + the weight
        vector, per round) — aggregation verifiably flows through the
        metered chokepoint."""
        monitor.reset()
        net, clients = _lora_setup(n_clients=4)
        fed = FederatedAverager(net, nn.MSELoss(), clients,
                                local_steps=6, local_lr=0.2, seed=0)
        # only adapters are trainable -> only adapters aggregate
        assert all("lora_" in n for n, _ in fed._trainable)
        loss0 = fed.evaluate()
        rounds = 6
        fed.run(rounds)
        loss = fed.evaluate()
        assert loss < 0.2, f"LoRA FedAvg stalled: {loss0} -> {loss}"
        n_adapter = sum(int(np.prod(p.shape))
                        for p in lora_parameters(net))
        expected = rounds * 4 * (n_adapter * 4 + 4)   # deltas + weights
        flat = monitor.flatten(monitor.snapshot())
        assert flat["collective_bytes_total{op=federated_sum}"] == expected
        assert flat["federated_round_total{algorithm=fedavg}"] == rounds
        ex = flat["federated_client_examples"]
        assert ex["count"] == rounds * 4 and ex["sum"] > 0

    def test_fedsgd_single_gradient_round(self):
        net, clients = _lora_setup(n_clients=4)
        fed = FederatedAverager(net, nn.MSELoss(), clients,
                                algorithm="fedsgd", seed=0,
                                server_optimizer=paddle.optimizer.SGD(
                                    learning_rate=0.2,
                                    parameters=[p for _, p in
                                                [(n, p) for n, p in
                                                 net.named_parameters()
                                                 if p.trainable]]))
        loss0 = fed.evaluate()
        fed.run(4)
        assert fed.evaluate() < loss0

    def test_client_sampling_subset(self):
        net, clients = _lora_setup(n_clients=4)
        fed = FederatedAverager(net, nn.MSELoss(), clients,
                                clients_per_round=2, local_steps=2,
                                local_lr=0.1, seed=7)
        s = fed.run_round()
        assert s["cohort"] == 2 and s["survivors"] == 2

    def test_round_spans_emitted(self):
        net, clients = _lora_setup(n_clients=4)
        fed = FederatedAverager(net, nn.MSELoss(), clients,
                                local_steps=1, local_lr=0.1, seed=0)
        trace.clear()
        trace.enable()
        try:
            fed.run_round()
        finally:
            trace.disable()
        names = [s.name for s in trace.spans()]
        assert "federated_round" in names
        assert names.count("client_update") == 4
        assert "federated_aggregate" in names
        root = [s for s in trace.spans() if s.name == "federated_round"][0]
        kids = [s for s in trace.spans() if s.parent_id == root.span_id]
        assert {"client_update", "federated_aggregate"} <= \
            {s.name for s in kids}


class TestFederatedFailpoint:
    def test_client_dropout_round_completes_with_survivors(self):
        """federated/round armed error:1 — the first sampled client's
        update dies, the round completes with the remaining cohort, and
        the drop is counted in federated_client_dropped_total."""
        monitor.reset()
        net, clients = _lora_setup(n_clients=4)
        fed = FederatedAverager(net, nn.MSELoss(), clients,
                                local_steps=2, local_lr=0.1, seed=0)
        with failpoints.scoped("federated/round=error:1"):
            s = fed.run_round()
        assert s["cohort"] == 4
        assert s["dropped"] == 1
        assert s["survivors"] == 3
        assert failpoints.hits("federated/round") == 1
        flat = monitor.flatten(monitor.snapshot())
        assert flat[
            "federated_client_dropped_total{reason=failpoint}"] == 1.0
        # the surviving cohort's aggregate actually applied
        assert s["update_norm"] > 0
        # and the next round is healthy again
        s2 = fed.run_round()
        assert s2["dropped"] == 0 and s2["survivors"] == 4

    def test_organic_client_error_also_drops(self):
        """Per-client isolation covers organic errors too (serving's
        per-slot discipline): a client with a broken batch is dropped
        with reason=error and the round completes with the survivors."""
        monitor.reset()
        net, clients = _lora_setup(n_clients=4)
        clients[1] = [(np.ones((4, 8), np.float32), None)]   # broken batch
        fed = FederatedAverager(net, nn.MSELoss(), clients,
                                local_steps=1, local_lr=0.1, seed=0)
        s = fed.run_round()
        assert s["dropped"] == 1 and s["survivors"] == 3
        flat = monitor.flatten(monitor.snapshot())
        assert flat["federated_client_dropped_total{reason=error}"] == 1.0
        # the dropped client's partial grads were cleared, not bled into
        # the cohort that followed it
        assert all(p.grad is None for _, p in fed._trainable)

    def test_all_clients_dropped_raises(self):
        net, clients = _lora_setup(n_clients=4)
        fed = FederatedAverager(net, nn.MSELoss(), clients,
                                local_steps=1, local_lr=0.1, seed=0)
        before = fed._snapshot()
        with failpoints.scoped("federated/round=error"):
            with pytest.raises(RuntimeError, match="every client"):
                fed.run_round()
        # global params untouched by the failed round
        for a, b in zip(before, fed._snapshot()):
            np.testing.assert_array_equal(a, b)


class TestPartitionClients:
    def test_contiguous_deterministic_unequal(self):
        X = np.arange(22, dtype=np.float32).reshape(11, 2)
        Y = np.arange(11, dtype=np.float32)
        parts = partition_clients((X, Y), 3, batch_size=2)
        sizes = [sum(len(b[0]) for b in p) for p in parts]
        assert sizes == [4, 4, 3]           # near-equal, first gets extra
        # contiguous and order-preserving
        np.testing.assert_array_equal(parts[0][0][0], X[:2])
        np.testing.assert_array_equal(parts[2][-1][1], Y[10:])
        parts2 = partition_clients((X, Y), 3, batch_size=2)
        np.testing.assert_array_equal(parts[1][0][0], parts2[1][0][0])

    def test_corpus_partition(self):
        corpus = paddle.dataset.tiny_corpus()
        parts = partition_clients(corpus, 4, batch_size=8, seq_len=16)
        assert len(parts) == 4
        assert all(p for p in parts)
        x, y = parts[0][0]
        assert x.dtype == np.int32 and x.shape[1] == 16
        # labels are the next-char shift of the inputs
        np.testing.assert_array_equal(x[0, 1:], y[0, :-1])

    def test_errors(self):
        with pytest.raises(ValueError, match="cannot shard"):
            partition_clients((np.zeros((2, 1)), np.zeros(2)), 3)
        with pytest.raises(TypeError, match="partition_clients"):
            partition_clients("not a corpus", 2)
