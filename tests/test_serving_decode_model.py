"""DecodeModel protocol + registry (serving/decode_model.py): the serving
tier's only doorway into model code. Contract: gpt resolves lazily, the
engine served THROUGH the registry is byte-identical to the pre-registry
engine (same decode helpers under the adapter), and unknown models fail
with an actionable error."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.serving import decode_model as dm


def _model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


class TestRegistry:
    def test_gpt_resolves_lazily_by_name(self):
        adapter = dm.get_decode_model("gpt")
        assert adapter.name == "gpt"
        assert "gpt" in dm.registered_decode_models()

    def test_resolve_by_instance_and_spec(self):
        m = _model()
        a = dm.resolve(m)                      # probe matches()
        assert a.name == "gpt"
        assert dm.resolve(m, "gpt") is a       # by name
        assert dm.resolve(m, a) is a           # pass-through instance

    def test_unknown_name_lists_known(self):
        with pytest.raises(KeyError, match="gpt"):
            dm.get_decode_model("nope")

    def test_unmatched_model_is_actionable(self):
        with pytest.raises(TypeError, match="DecodeModel adapter"):
            dm.resolve(object())

    def test_duplicate_registration_rejected(self):
        class Fake(dm.DecodeModel):
            name = "gpt"

        with pytest.raises(ValueError, match="already registered"):
            dm.register_decode_model(Fake())
        # clobber + restore (keeps the real adapter installed for the
        # rest of the suite)
        real = dm.get_decode_model("gpt")
        dm.register_decode_model(Fake(), clobber=True)
        try:
            assert isinstance(dm.get_decode_model("gpt"), Fake)
        finally:
            dm.register_decode_model(real, clobber=True)

    def test_nameless_adapter_rejected(self):
        with pytest.raises(ValueError, match="name"):
            dm.register_decode_model(dm.DecodeModel())


class TestGPTAdapter:
    def test_cache_spec_documents_layout(self):
        m = _model()
        spec = dm.resolve(m).cache_spec(m.cfg)
        assert spec["kind"] == "kv_pair"
        assert spec["layout"] == "[L, B, KVh, T, hd]"
        assert spec["axes"] == {"L": 2, "KVh": 2, "T": 64, "hd": 16}

    def test_decode_fns_cache_init_matches_spec(self):
        import jax.numpy as jnp

        m = _model()
        a = dm.resolve(m)
        params, aux = a.extract_params(m, "the model")
        fwd, logits_of, cache_init = a.decode_fns(m.cfg, aux)
        kc, vc = cache_init(3, 64, jnp.float32)
        assert kc.shape == vc.shape == (2, 3, 2, 64, 16)

    def test_cache_row_bytes(self):
        import jax.numpy as jnp

        m = _model()
        a = dm.resolve(m)
        _, aux = a.extract_params(m, "the model")
        cache_init = a.decode_fns(m.cfg, aux)[2]
        row = cache_init(1, 64, jnp.float32)
        # two sides x [L=2, 1, KVh=2, T=64, hd=16] f32
        assert dm.cache_row_bytes(row) == 2 * 2 * 2 * 64 * 16 * 4

    def test_compute_dtype_and_config_check_delegate(self):
        import jax.numpy as jnp

        m = _model()
        a = dm.resolve(m)
        assert a.compute_dtype(None) is None
        assert a.compute_dtype("bfloat16") == jnp.bfloat16
        moe = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=2, max_seq_len=32, num_experts=2)
        with pytest.raises(ValueError):
            a.check_config(moe)


class TestEngineThroughRegistry:
    def test_engine_outputs_identical_by_every_resolution_path(self):
        m = _model()
        rng = np.random.RandomState(0)
        prompts = [rng.randint(0, 128, (n,)).astype(np.int32)
                   for n in (5, 11)]

        def run(**kw):
            eng = ServingEngine(m, max_batch=2, **kw)
            rids = [eng.submit(p, max_new_tokens=6) for p in prompts]
            res = eng.run_until_complete()
            return [res[r].tokens for r in rids]

        base = run()                               # resolve by matches()
        by_name = run(decode_model="gpt")          # resolve by name
        by_inst = run(decode_model=dm.get_decode_model("gpt"))
        for a, b, c in zip(base, by_name, by_inst):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(a, c)
        # and exact solo-generate parity (the serving tier's parity bar)
        for p, toks in zip(prompts, base):
            ref = m.generate(paddle.to_tensor(p[None]), max_new_tokens=6,
                             temperature=0.0)
            np.testing.assert_array_equal(
                toks, np.asarray(ref._data)[0, len(p):])

    def test_dense_base_adapter_rejects_tp(self):
        a = dm.DecodeModel()
        a.name = "dense-only"
        with pytest.raises(NotImplementedError, match="tensor-parallel"):
            a.tp_setup(None, None, None)
