"""Unit coverage for the goodput ledger + weight-version lineage
(ISSUE 20): bucket exclusivity under nesting, run-row schema + ledger
append through the direction-aware sentinel, WeightVersion monotonicity
across checkpoint -> restore -> reshard -> hot_swap, pre-version
checkpoints loading as v0, the stale-session counter firing exactly
once per stale finish, and the exporters' histogram-percentile
round-trip regression (metrics_dump output must parse losslessly or
skip with a reason)."""
import os

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import time

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import flags, monitor
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.spmd import SpmdTrainer
from paddle_tpu.framework import lineage
from paddle_tpu.monitor import goodput


@pytest.fixture(autouse=True)
def _clean_goodput():
    goodput.reset()
    yield
    goodput.reset()


class TestBucketAccounting:
    def test_nesting_pauses_outer_and_buckets_sum_to_wall(self):
        """A compile resolving inside a step books `compile`, not
        `step` (exclusive attribution), and the bucket totals sum to
        the run's wall time by construction."""
        run = goodput.GoodputRun("t/nest", stall_threshold_s=10.0)
        with run.bucket("step"):
            time.sleep(0.03)
            with run.bucket("compile"):
                assert run.active() == "compile"
                time.sleep(0.05)
            assert run.active() == "step"
            time.sleep(0.02)
        row = run.finalize()
        assert run.buckets["compile"] >= 0.05
        assert run.buckets["step"] >= 0.04
        # the nested 0.05s must NOT also be in step (< outer + slack)
        assert run.buckets["step"] < 0.05 + 0.05
        assert sum(run.buckets.values()) == pytest.approx(
            row["wall_s"], rel=1e-6)

    def test_gap_books_stall_past_threshold_other_under(self):
        run = goodput.GoodputRun("t/gap", stall_threshold_s=0.04)
        time.sleep(0.06)              # idle gap >= threshold
        run.begin("step")
        run.end("step")
        time.sleep(0.01)              # idle gap < threshold
        run.finalize()
        assert run.buckets["stall"] >= 0.06
        assert run.buckets["other"] > 0.0
        assert run.buckets["other"] < 0.04

    def test_unbalanced_end_is_no_op_and_unknown_bucket_raises(self):
        run = goodput.GoodputRun("t/unbal", stall_threshold_s=10.0)
        run.end("step")               # no matching begin: no-op
        assert run.active() is None
        with pytest.raises(ValueError):
            run.begin("not_a_bucket")

    def test_finalize_idempotent_and_last_bucket_survives_unwind(self):
        """An exception unwinds the active bucket BEFORE a crash dump
        lands — `last_bucket` keeps the "what was it doing" answer."""
        run = goodput.GoodputRun("t/kill", stall_threshold_s=10.0)
        with pytest.raises(RuntimeError):
            with run.bucket("step"):
                time.sleep(0.01)
                raise RuntimeError("kill")
        snap = run.snapshot()
        assert snap["active_bucket"] is None
        assert snap["last_bucket"] == "step"
        r1 = run.finalize()
        r2 = run.finalize()
        assert r1["wall_s"] == r2["wall_s"]

    def test_module_helpers_are_noops_without_a_run(self):
        assert goodput.current_run() is None
        with goodput.bucket("step"):
            pass
        goodput.count("resume")
        assert goodput.end_run() is None


class TestRunRowAndLedger:
    def test_row_schema(self):
        run = goodput.start_run("t/schema")
        with goodput.bucket("step"):
            time.sleep(0.01)
        goodput.count("resume")
        goodput.count("reshard", 2)
        row = goodput.end_run()
        assert set(row) == {"run_id", "goodput", "wall_s", "n_resumes",
                            "n_reshards", "buckets"}
        assert row["run_id"] == "t/schema"
        assert row["n_resumes"] == 1 and row["n_reshards"] == 2
        assert set(row["buckets"]) == set(goodput.BUCKETS)
        assert 0.0 < row["goodput"] <= 1.0

    def test_end_run_appends_ledger_row_through_sentinel(self, tmp_path):
        """FLAGS_perf_ledger also armed: the finalized run lands one
        site=run/goodput row keyed by its run_id, and `goodput` is
        sentinel-directed LOW_IS_BAD."""
        from paddle_tpu.monitor import perfledger

        path = str(tmp_path / "perf.jsonl")
        old = {k: flags.get_flag(k)
               for k in ("perf_ledger", "perf_ledger_path",
                         "perf_ledger_interval")}
        paddle.set_flags({"perf_ledger": True, "perf_ledger_path": path,
                          "perf_ledger_interval": 1})
        perfledger.reset_ledger()
        try:
            goodput.start_run("t/ledger")
            with goodput.bucket("step"):
                time.sleep(0.01)
            row = goodput.end_run()
            rows = [r for r in perfledger.load_rows(path)
                    if r.get("site") == "run/goodput"]
            assert rows and rows[0]["sig"] == "t/ledger"
            m = rows[0]["metrics"]
            assert m["goodput"] == pytest.approx(row["goodput"])
            assert m["buckets"]["step"] > 0.0
            assert "goodput" in perfledger.LOW_IS_BAD
        finally:
            paddle.set_flags(old)
            perfledger.reset_ledger()

    def test_start_run_finalizes_unfinished_prior_leg(self):
        first = goodput.start_run("t/leg1")
        with goodput.bucket("step"):
            time.sleep(0.005)
        second = goodput.start_run("t/leg2")
        assert first.finalized
        assert goodput.current_run() is second
        assert goodput.ensure_run("t/other") is second   # no clobber
        goodput.end_run()


def _tiny_trainer(n_dev=1):
    from paddle_tpu import nn

    paddle.seed(0)
    net = nn.Linear(8, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    mesh = build_mesh((n_dev,), ("dp",), devices=jax.devices()[:n_dev])
    return SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)


class TestWeightVersionLineage:
    def test_bump_is_monotone_and_origin_checked(self):
        v = lineage.WeightVersion("r", 0, "init")
        seen = [v]
        for origin in ("step", "restore", "reshard", "hot_swap",
                       "adapter_load"):
            seen.append(seen[-1].bump(origin))
        counters = [x.counter for x in seen]
        assert counters == sorted(counters) and len(set(counters)) == 6
        with pytest.raises(ValueError):
            v.bump("teleport")

    def test_from_dict_malformed_is_v0(self):
        v = lineage.WeightVersion.from_dict(None, run_id="r")
        assert (v.counter, v.origin) == (0, "init")
        v = lineage.WeightVersion.from_dict({"counter": "junk"},
                                            run_id="r")
        assert (v.counter, v.origin) == (0, "init")

    def test_trainer_lineage_checkpoint_restore_reshard(self):
        """counter strictly increases across step -> save -> restore
        (origin `restore`) -> live resize (origin `reshard`); a restore
        rejoins at max(live, loaded) + 1 so two lineages never share a
        counter value."""
        rng = np.random.RandomState(0)
        x = rng.rand(4, 8).astype(np.float32)
        y = rng.rand(4, 4).astype(np.float32)
        old = {k: flags.get_flag(k)
               for k in ("elastic", "shard_weight_update")}
        # resize() is elastic-only and FLAGS_elastic is structural: it
        # must be armed at trainer construction
        paddle.set_flags({"elastic": True, "shard_weight_update": True})
        try:
            self._lineage_walk(x, y)
        finally:
            paddle.set_flags(old)

    def _lineage_walk(self, x, y):
        tr = _tiny_trainer(1)
        history = [tr.weight_version.counter]
        tr.train_step(x, y)
        tr.train_step(x, y)
        history.append(tr.weight_version.counter)
        state = tr.state_dict()
        saved = lineage.WeightVersion.from_dict(
            state["__weight_version__"], run_id=tr.weight_version.run_id)
        assert saved.counter == tr.weight_version.counter
        tr.train_step(x, y)                       # live moves past saved
        tr.set_state_dict(state)
        history.append(tr.weight_version.counter)
        assert tr.weight_version.origin == "restore"
        tr.resize(build_mesh((2,), ("dp",), devices=jax.devices()[:2]))
        history.append(tr.weight_version.counter)
        assert tr.weight_version.origin == "reshard"
        assert history == sorted(history)
        assert len(set(history)) == len(history)  # strictly monotone

    def test_pre_version_checkpoint_loads_as_v0(self):
        """A checkpoint written before this PR has no __weight_version__
        leaf: it loads as version 0 and the live trainer rejoins at
        live+1 (handoff baseline covers the schema side)."""
        tr = _tiny_trainer(1)
        state = tr.state_dict()
        state.pop("__weight_version__")
        before = tr.weight_version.counter
        tr.set_state_dict(state)
        assert tr.weight_version.counter == before + 1
        assert tr.weight_version.origin == "restore"


class TestServingStaleSessions:
    def _model(self):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_heads=2, max_seq_len=64, dropout=0.0)
        m = GPTForCausalLM(cfg)
        m.eval()
        return m

    def test_hot_swap_stamps_and_counts_stale_exactly_once(self):
        """A session submitted pre-swap finishes carrying its pre-swap
        stamp and counts ONE stale finish; a post-swap session carries
        the bumped version and counts nothing. Same weights both sides,
        so tokens are bit-exact across the swap."""
        from paddle_tpu.inference.serving import ServingEngine

        old = flags.get_flag("goodput")
        paddle.set_flags({"goodput": True})
        try:
            m = self._model()
            eng = ServingEngine(m, max_batch=2)
            rng = np.random.RandomState(0)
            prompt = rng.randint(0, 64, (6,)).astype(np.int32)

            def stale_total():
                flat = monitor.flatten(monitor.snapshot())
                return flat.get("serving_stale_sessions_total", 0)

            base = stale_total()
            rid0 = eng.submit(prompt, max_new_tokens=4)
            v1 = eng.hot_swap(m)      # same weights: outputs unchanged
            assert v1.counter == 1 and v1.origin == "hot_swap"
            res = eng.run_until_complete()
            tok0 = res[rid0].tokens.tolist()
            s0 = eng.get_request(rid0).stats()
            assert s0["weight_version"].split(":")[1] == "0"
            assert stale_total() == base + 1      # exactly once
            rid1 = eng.submit(prompt, max_new_tokens=4)
            res = eng.run_until_complete()
            s1 = eng.get_request(rid1).stats()
            assert s1["weight_version"].split(":")[1] == "1"
            assert stale_total() == base + 1      # fresh finish: no inc
            assert res[rid1].tokens.tolist() == tok0   # bit-exact
            assert eng.stats()["weight_version"].split(":")[2] \
                == "hot_swap"
        finally:
            paddle.set_flags({"goodput": old})

    def test_hot_swap_rejects_mismatched_architecture(self):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.inference.serving import ServingEngine

        eng = ServingEngine(self._model(), max_batch=2)
        paddle.seed(1)
        other = GPTForCausalLM(GPTConfig(
            vocab_size=64, hidden_size=16, num_layers=1, num_heads=2,
            max_seq_len=64, dropout=0.0))
        other.eval()
        with pytest.raises(ValueError):
            eng.hot_swap(other)


class TestExporterRoundtrip:
    def test_histogram_percentiles_roundtrip(self):
        """The regression this PR fixes: metrics_dump --prometheus now
        emits quantile-labelled samples for each histogram's digest,
        and parse_prometheus reads them back instead of dropping (or
        crashing on) percentile lines."""
        from paddle_tpu.monitor import exporters

        monitor.reset()
        h = monitor.histogram("rt_ms", "roundtrip test",
                              labelnames=("site",))
        for v in (1.0, 2.0, 3.0, 10.0):
            h.labels(site="a").observe(v)
        snap = monitor.snapshot()
        summ = {"rt_ms{site=a}": {"p50": 2.0, "p90": 3.0, "p99": 10.0}}
        text = exporters.to_prometheus(snap, summaries=summ)
        assert 'rt_ms{quantile="0.5",site="a"} 2' in text
        parsed = exporters.parse_prometheus(text)
        key = ("rt_ms", frozenset({("site", "a"),
                                   ("quantile", "0.99")}.__iter__()))
        assert parsed[key] == 10.0
        # default form stays byte-identical to the historical output
        assert exporters.to_prometheus(snap) == \
            exporters.to_prometheus(snap, summaries=None)

    def test_non_exposition_line_skips_with_reason(self):
        from paddle_tpu.monitor import exporters

        text = ('good_total 3\n'
                'rt_ms{site="a"}: {"p50": 2.0, "p90": 3.0}\n')
        skipped = []
        parsed = exporters.parse_prometheus(text, skipped=skipped)
        assert parsed[("good_total", frozenset())] == 3.0
        assert len(skipped) == 1
        line, reason = skipped[0]
        assert line.startswith("rt_ms") and "not a float" in reason
