"""Failpoint framework contract (paddle_tpu/testing/failpoints.py): spec
parsing, arming/disarming, error:N counting, delay, scoped restore, flag
arming, and the planted sites actually firing in their host modules."""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.testing import failpoints as fp
from paddle_tpu.testing.failpoints import FailpointError


@pytest.fixture(autouse=True)
def _clean():
    fp.reset()
    yield
    fp.reset()
    paddle.set_flags({"failpoints": ""})


class TestSpecParsing:
    def test_parse_multi_site_spec(self):
        acts = fp.parse("ckpt/write=error:2, serving/step=delay:5")
        assert set(acts) == {"ckpt/write", "serving/step"}
        assert acts["ckpt/write"].kind == "error"
        assert acts["ckpt/write"].remaining == 2
        assert acts["serving/step"].kind == "delay"
        assert acts["serving/step"].arg == 5.0

    def test_unknown_site_lists_known_ones(self):
        with pytest.raises(ValueError, match="known sites.*ckpt/write"):
            fp.parse("no/such/site=error")

    def test_bad_specs(self):
        with pytest.raises(ValueError, match="site=action"):
            fp.parse("ckpt/write")
        with pytest.raises(ValueError, match="unknown action"):
            fp.parse("ckpt/write=explode")
        with pytest.raises(ValueError, match="delay needs"):
            fp.parse("ckpt/write=delay")
        with pytest.raises(ValueError, match=">= 1"):
            fp.parse("ckpt/write=error:0")

    def test_empty_spec_parses_empty(self):
        assert fp.parse("") == {}
        assert fp.parse(" , ") == {}

    def test_parse_scale_action(self):
        acts = fp.parse("trainer/batch=scale:1e4")
        assert acts["trainer/batch"].kind == "scale"
        assert acts["trainer/batch"].arg == 1e4
        import math

        assert math.isnan(fp.parse("trainer/batch=scale:nan")
                          ["trainer/batch"].arg)
        with pytest.raises(ValueError, match="scale needs"):
            fp.parse("trainer/batch=scale")


class TestTransformSite:
    """transform(): the value-transforming failpoint form
    (docs/ROBUSTNESS.md scale:F) — floats scaled, ints untouched,
    disarmed = identity, non-scale actions fire as usual."""

    def test_disarmed_identity(self):
        val = [np.ones(3, np.float32)]
        assert fp.transform("trainer/batch", val) is val

    def test_scale_floats_only_and_counts_hits(self):
        with fp.scoped("trainer/batch=scale:2"):
            out = fp.transform("trainer/batch",
                               (np.full(3, 1.5, np.float32),
                                np.arange(3, dtype=np.int32)))
        assert isinstance(out, tuple)
        np.testing.assert_array_equal(out[0], np.full(3, 3.0))
        np.testing.assert_array_equal(out[1], np.arange(3))
        assert out[1].dtype == np.int32
        assert fp.hits("trainer/batch") == 1

    def test_scale_nan_poisons(self):
        with fp.scoped("trainer/batch=scale:nan"):
            (out,) = fp.transform("trainer/batch",
                                  [np.ones(4, np.float32)])
        assert np.isnan(out).all()

    def test_error_action_fires_through_transform(self):
        with fp.scoped("trainer/batch=error:1"):
            with pytest.raises(FailpointError):
                fp.transform("trainer/batch", [np.ones(2)])

    def test_plain_failpoint_ignores_scale_arming(self):
        with fp.scoped("trainer/batch=scale:3"):
            fp.failpoint("trainer/batch")   # no raise, no hit consumed
            assert fp.hits("trainer/batch") == 0

    def test_trainer_batch_site_registered(self):
        assert "trainer/batch" in fp.SITES


class TestArming:
    def test_arm_disarm_round_trip(self):
        assert not fp.is_enabled()
        fp.arm("ckpt/write", "error")
        assert fp.is_enabled()
        assert fp.armed() == {"ckpt/write": "error"}
        fp.disarm("ckpt/write")
        assert not fp.is_enabled()

    def test_error_n_auto_disarms_after_n_fires(self):
        fp.arm("ckpt/read", "error:2")
        for _ in range(2):
            with pytest.raises(FailpointError, match="ckpt/read"):
                fp.failpoint("ckpt/read")
        # third hit: site disarmed itself, nothing fires
        fp.failpoint("ckpt/read")
        assert fp.hits("ckpt/read") == 2
        assert not fp.is_enabled()

    def test_unarmed_site_is_inert_while_another_is_armed(self):
        fp.arm("ckpt/write", "error")
        fp.failpoint("serving/step")   # not armed: no-op
        assert fp.hits("serving/step") == 0

    def test_delay_sleeps(self):
        fp.arm("serving/step", "delay:30")
        t0 = time.perf_counter()
        fp.failpoint("serving/step")
        assert (time.perf_counter() - t0) * 1e3 >= 25

    def test_scoped_restores_previous_state(self):
        fp.arm("ckpt/write", "error:5")
        with fp.scoped("ckpt/read=error:1"):
            assert set(fp.armed()) == {"ckpt/write", "ckpt/read"}
            with pytest.raises(FailpointError):
                fp.failpoint("ckpt/read")
        assert set(fp.armed()) == {"ckpt/write"}
        assert fp.is_enabled()
        fp.reset()
        with fp.scoped("ckpt/read=error:1"):
            pass
        assert not fp.is_enabled()

    def test_exhausted_error_n_does_not_refire_after_scoped_restore(self):
        """scoped() restores the pre-scope arming dict by reference; an
        error:N exhausted INSIDE the scope must stay exhausted after exit —
        its budget is spent, not reset."""
        fp.arm("ckpt/write", "error:1")
        with fp.scoped("serving/step=delay:1"):
            with pytest.raises(FailpointError):
                fp.failpoint("ckpt/write")   # consumes the one shot
        fp.failpoint("ckpt/write")   # restored-but-spent: must NOT fire
        assert fp.hits("ckpt/write") == 1
        assert not fp.is_enabled()

    def test_arm_from_flag(self):
        paddle.set_flags({"failpoints": "exe/compile=error:1"})
        fp.arm_from_flag()
        assert fp.armed() == {"exe/compile": "error:1"}
        paddle.set_flags({"failpoints": ""})
        fp.arm_from_flag()
        assert not fp.is_enabled()

    def test_trigger_metric_series_appears_on_fire(self):
        from paddle_tpu import monitor

        fp.arm("serving/step", "delay:0")
        fp.failpoint("serving/step")
        metric = monitor.default_registry().get("failpoint_trigger_total")
        assert any(s.labels == {"site": "serving/step", "action": "delay"}
                   for s in metric.series())


class TestPlantedSites:
    """Each planted site fires in its host module when armed."""

    def test_ckpt_write_and_read_sites(self, tmp_path):
        p = str(tmp_path / "s.pdparams")
        with fp.scoped("ckpt/write=error:1"):
            with pytest.raises(FailpointError):
                paddle.save({"a": 1}, p)
        paddle.save({"a": 1}, p)
        with fp.scoped("ckpt/read=error:1"):
            with pytest.raises(FailpointError):
                paddle.load(p)
        assert paddle.load(p) == {"a": 1}

    def test_ckpt_commit_site(self, tmp_path):
        from paddle_tpu.incubate.checkpoint.auto_checkpoint import \
            CheckpointSaver

        saver = CheckpointSaver(str(tmp_path))
        with fp.scoped("ckpt/commit=error:1"):
            with pytest.raises(FailpointError):
                saver.save_checkpoint({"v": 1})
        assert saver.get_checkpoint_numbers() == []   # nothing committed

    def test_exe_compile_site(self):
        import paddle_tpu.static as st

        paddle.seed(0)
        main, startup = st.Program(), st.Program()
        st.enable_static()
        try:
            with st.program_guard(main, startup):
                x = st.data("x", [None, 4])
                w = paddle.create_parameter([4, 4])
                y = paddle.matmul(x, w)
        finally:
            st.disable_static()
        exe = st.Executor()
        exe.run(startup)
        feed = {"x": np.ones((2, 4), np.float32)}
        with fp.scoped("exe/compile=error:1"):
            with pytest.raises(FailpointError):
                exe.run(main, feed=feed, fetch_list=[y])
        (r,) = exe.run(main, feed=feed, fetch_list=[y])   # recovers
        assert np.isfinite(r).all()

    def test_collective_call_site(self):
        from paddle_tpu.distributed import collective

        t = paddle.to_tensor(np.ones(4, np.float32))
        with fp.scoped("collective/call=error:1"):
            with pytest.raises(FailpointError):
                collective.all_reduce(t)
        collective.all_reduce(t)   # disarmed: identity at world_size 1
