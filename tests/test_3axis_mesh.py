"""3-axis hybrid parallelism at 16 virtual devices (VERDICT r2 #9): one
combined pp x dp x mp pipeline train step runs finite, and ZeRO stage-2/3
HLO carries reduce-scatter/all-gather at that scale.

The suite's conftest pins 8 virtual devices, so these tests re-exec in a
subprocess with a 16-device CPU platform."""
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run16(body, timeout=560):
    script = textwrap.dedent("""
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import paddle_tpu as paddle
    """) + textwrap.dedent(body)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    env["JAX_PLATFORMS"] = ""
    env["PYTHONPATH"] = REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    r = subprocess.run([sys.executable, "-c", script], env=env,
                       capture_output=True, text=True, timeout=timeout)
    assert r.returncode == 0, r.stdout + r.stderr
    return r.stdout


def test_pp_dp_mp_combined_step_16dev():
    """pp=4 x dp=2 x mp=2: pipeline schedule + dp grad psum + tensor-parallel
    stage shardings in ONE jitted train step."""
    out = _run16("""
        from paddle_tpu.distributed.mesh import build_mesh
        from paddle_tpu.distributed.pipeline import PipelineTrainer
        from paddle_tpu.distributed.split import collect_spmd_specs
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu import optimizer as popt

        devices = jax.devices()
        assert len(devices) >= 16, devices
        mesh = build_mesh((4, 2, 2), ("pp", "dp", "mp"), devices=devices[:16])

        paddle.seed(0)
        cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=4,
                        num_heads=4, max_seq_len=32, dropout=0.0,
                        tensor_parallel=True)
        model = GPTForCausalLM(cfg)
        pre, stages, post = model.pipeline_split(4)
        specs = collect_spmd_specs(stages[0])
        assert specs, "tensor-parallel stages must expose spmd specs"
        opt = popt.AdamW(learning_rate=1e-4, parameters=model.parameters())
        trainer = PipelineTrainer(pre, stages, post, opt, mesh=mesh,
                                  n_micro=4, schedule_mode="1F1B",
                                  stage_param_specs=specs)
        rng = np.random.RandomState(0)
        x = rng.randint(0, 512, (8, 32)).astype(np.int32)
        y = rng.randint(0, 512, (8, 32)).astype(np.int32)
        loss = float(np.asarray(trainer.train_step(x, y)._data))
        assert np.isfinite(loss), loss
        # a stacked stage param really is sharded over pp AND mp
        name = next(k for k in trainer.params if k.startswith("stage::")
                    and trainer.stage_param_specs.get(
                        k.split("::", 1)[1]) is not None)
        spec = trainer.p_shardings[name].spec
        flat = [ax for d in spec if d for ax in
                (d if isinstance(d, tuple) else (d,))]
        assert "pp" in flat and "mp" in flat, spec
        print("PP_DP_MP_OK", loss)
    """)
    assert "PP_DP_MP_OK" in out


def test_zero_stage_hlo_collectives_16dev():
    """dp=8 x mp=2 ZeRO: stage-2 HLO must reduce-scatter grads and stage-3
    must all-gather params — asserted on the lowered step at 16 devices."""
    out = _run16("""
        from paddle_tpu.distributed.mesh import build_mesh
        from paddle_tpu.distributed.spmd import SpmdTrainer
        from paddle_tpu.distributed.split import collect_spmd_specs
        from paddle_tpu.models import GPTConfig, GPTForCausalLM, \
            GPTPretrainLoss

        devices = jax.devices()[:16]
        mesh = build_mesh((8, 2), ("dp", "mp"), devices=devices)

        def lowered(stage):
            paddle.seed(0)
            cfg = GPTConfig.tiny()
            cfg.tensor_parallel = True
            model = GPTForCausalLM(cfg)
            loss_layer = GPTPretrainLoss()
            opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                         parameters=model.parameters())
            tr = SpmdTrainer(model, opt, loss_fn=loss_layer, mesh=mesh,
                             sharding_stage=stage,
                             extra_param_specs=collect_spmd_specs(model))
            rng = np.random.RandomState(0)
            ids = jax.numpy.asarray(
                rng.randint(0, cfg.vocab_size, (16, 32)).astype(np.int32))
            batch = [ids, ids]
            step = tr._compiled or tr._build(batch)
            import jax.numpy as jnp
            lr = jnp.asarray(0.1, jnp.float32)
            r = jax.random.key(0)
            # post-SPMD-partitioning HLO: collectives only exist after
            # compilation (the lowered StableHLO carries sharding annotations)
            return step.lower(tr.params, tr.opt_state, tr.buffers, lr, r,
                              *batch).compile().as_text()

        t0 = lowered(0)
        t2 = lowered(2)
        t3 = lowered(3)
        # the CPU backend lowers reduce-scatter to all-reduce+slice, so the
        # robust cross-backend discriminator is the all-gather that sharded
        # optimizer state (stage 2) / sharded params (stage 3) require and
        # plain DP (stage 0) must NOT have, plus grad reduction being present
        c0, c2, c3 = (t.count("all-gather") for t in (t0, t2, t3))
        # mp=2 tensor parallel gathers activations at every stage, so the
        # ZeRO evidence is the GROWTH in all-gathers: sharded opt-state
        # (stage 2) and sharded params (stage 3) add param-reassembly
        # gathers plain DP does not have
        assert c2 > c0, f"stage-2 adds no param/state gathers ({c2} vs {c0})"
        assert c3 > c0, f"stage-3 adds no param gathers ({c3} vs {c0})"
        for name, t in (("stage-2", t2), ("stage-3", t3)):
            assert ("reduce-scatter" in t) or ("all-reduce" in t), \
                f"{name} HLO lacks grad reduction"
        print("ZERO_HLO_OK", c0, c2, c3)
    """)
    assert "ZERO_HLO_OK" in out


def test_weak_scaling_structure_32dev():
    """BASELINE's 'allreduce scaling eff' in compile-checkable form: with a
    fixed per-device batch, per-device FLOPs and grad all-reduce
    count/payload must be IDENTICAL at dp=2/8/32 — collective cost rides the
    ring, independent of world size (tools/scaling_check.py)."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "scaling_check.py")],
        env={**os.environ,
             "XLA_FLAGS": "--xla_force_host_platform_device_count=32",
             "JAX_PLATFORMS": "",
             "PYTHONPATH": REPO + (
                 os.pathsep + os.environ["PYTHONPATH"]
                 if os.environ.get("PYTHONPATH") else "")},
        capture_output=True, text=True, timeout=560)
    assert out.returncode == 0, out.stdout + out.stderr
    import json

    lines = [json.loads(ln) for ln in out.stdout.strip().splitlines()]
    verdict = lines[-1]
    assert verdict["scaling_ok"] is True, lines
    assert verdict["dps"] == [2, 8, 32]
