"""hapi observability surfaces: the reference-style progress bar
(hapi/progressbar.py) and the TF-events scalar writer behind the VisualDL
callback (utils/tb_writer.py — standard wire format, crc-checked)."""
import glob
import io
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.hapi.progressbar import ProgressBar
from paddle_tpu.utils import tb_writer


class TestProgressBar:
    def _render(self, num, updates, verbose=1, elapsed=2.0):
        buf = io.StringIO()
        buf.isatty = lambda: True
        pb = ProgressBar(num=num, verbose=verbose, file=buf)
        pb._start = time.time() - elapsed
        for step, values in updates:
            pb.update(step, values)
        return buf.getvalue()

    def test_bar_eta_rate_and_values(self):
        out = self._render(10, [(3, [("loss", 0.1234), ("acc", 5e-4)])])
        assert "step  3/10 [" in out          # digit-padded counter
        assert "==>" in out and "....." in out
        assert "loss: 0.1234" in out
        assert "acc: 5.0000e-04" in out       # small values in sci form
        assert "ETA:" in out and "ms/step" in out

    def test_completion_fills_bar_and_newlines(self):
        out = self._render(4, [(4, [("loss", 1.0)])])
        assert "[" + "=" * 30 + "]" in out
        assert "ETA" not in out and out.endswith("\n")

    def test_unknown_total_verbose2(self):
        buf = io.StringIO()
        pb = ProgressBar(num=None, verbose=2, file=buf)
        pb.update(7, [("loss", 1.5)])
        assert "step   7" in buf.getvalue()
        assert "loss: 1.5000" in buf.getvalue()

    def test_verbose_zero_silent(self):
        out = self._render(10, [(5, [("loss", 1.0)])], verbose=0)
        assert out == ""

    def test_rejects_nonpositive_num(self):
        import pytest

        with pytest.raises(TypeError):
            ProgressBar(num=0)

    def test_non_tty_verbose1_no_leading_blank_line(self):
        # non-tty at verbose=1 prints one line per update; the first line
        # must not be preceded by a spurious blank line
        buf = io.StringIO()
        buf.isatty = lambda: False
        pb = ProgressBar(num=4, verbose=1, file=buf)
        pb._start = time.time() - 1.0
        pb.update(1, [("loss", 1.0)])
        pb.update(2, [("loss", 0.5)])
        out = buf.getvalue()
        assert not out.startswith("\n")
        assert out.count("\n") == 1  # exactly one separator between 2 lines


class TestTBWriter:
    def test_crc32c_known_vector(self):
        # the standard Castagnoli check value
        assert tb_writer.crc32c(b"123456789") == 0xE3069283

    def test_roundtrip_scalars(self, tmp_path):
        w = tb_writer.EventFileWriter(str(tmp_path))
        w.add_scalar("train/loss", 0.5, 1)
        w.add_scalar("train/loss", 0.25, 2)
        w.add_scalar("eval/acc", 0.9, 2)
        w.close()
        (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
        scalars = tb_writer.read_scalars(path)
        assert (1, "train/loss", np.float32(0.5)) in scalars
        assert (2, "train/loss", np.float32(0.25)) in scalars
        assert (2, "eval/acc", np.float32(0.9)) in scalars

    def test_torn_tail_returns_prefix(self, tmp_path):
        w = tb_writer.EventFileWriter(str(tmp_path))
        w.add_scalar("a", 1.0, 1)
        w.add_scalar("b", 2.0, 2)
        w.close()
        (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
        raw = open(path, "rb").read()
        open(path, "wb").write(raw[:-7])     # kill mid-final-record
        scalars = tb_writer.read_scalars(path)
        assert (1, "a", np.float32(1.0)) in scalars
        assert all(tag != "b" for _, tag, _ in scalars)

    def test_two_writers_same_second_distinct_files(self, tmp_path):
        w1 = tb_writer.EventFileWriter(str(tmp_path))
        w2 = tb_writer.EventFileWriter(str(tmp_path))
        w1.close(); w2.close()
        assert len(glob.glob(str(tmp_path / "events.out.tfevents.*"))) == 2

    def test_corruption_detected(self, tmp_path):
        import pytest

        w = tb_writer.EventFileWriter(str(tmp_path))
        w.add_scalar("t", 1.0, 1)
        w.close()
        (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
        raw = bytearray(open(path, "rb").read())
        raw[-6] ^= 0xFF                      # flip a payload byte
        open(path, "wb").write(bytes(raw))
        with pytest.raises(ValueError, match="crc"):
            tb_writer.read_scalars(path)


class TestVisualDLCallback:
    def test_fit_writes_events_and_tsv(self, tmp_path):
        from paddle_tpu import nn
        from paddle_tpu.hapi.callbacks import VisualDL

        paddle.seed(0)
        model = paddle.Model(nn.Sequential(nn.Flatten(), nn.Linear(4, 2)))
        model.prepare(paddle.optimizer.SGD(
            learning_rate=0.1, parameters=model.network.parameters()),
            nn.CrossEntropyLoss())
        x = np.random.RandomState(0).rand(16, 4).astype(np.float32)
        y = np.random.RandomState(1).randint(0, 2, (16, 1)).astype(np.int64)

        class DS:
            def __len__(self):
                return 16

            def __getitem__(self, i):
                return x[i], y[i]

        model.fit(DS(), epochs=1, batch_size=8, verbose=0,
                  callbacks=[VisualDL(str(tmp_path))])
        assert (tmp_path / "scalars.tsv").exists()
        (path,) = glob.glob(str(tmp_path / "train" / "events.out.*"))
        scalars = tb_writer.read_scalars(path)
        assert any(tag == "train/loss" for _, tag, _ in scalars)
