"""SpmdTrainer non-finite step guard (FLAGS_check_nan_inf,
docs/ROBUSTNESS.md): a NaN/Inf loss or gradient SKIPS the optimizer update
on-device — params, optimizer moments, and step counters stay bit-identical
— for up to FLAGS_max_skip_steps consecutive steps before train_step raises
FloatingPointError. With the flag off (default) behavior is exactly
pre-guard.

Since ISSUE 11 the HOST learns about a skip DEFERRED (docs/PERF.md): the
verdict is fetched at the next train_step entry (window 1), at a
FLAGS_benchmark sync, at stats(), or on guard_sync() — never by a blocking
per-step sync inside the step itself. Tests force the fetch with
guard_sync() where they assert host-visible skip state; the device-side
bit-identical contract needs no sync at all."""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.spmd import SpmdTrainer


@pytest.fixture(autouse=True)
def _restore_flags():
    yield
    paddle.set_flags({"check_nan_inf": False, "max_skip_steps": 3})


def _trainer(**kw):
    paddle.seed(0)
    model = paddle.nn.Linear(4, 1)
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=model.parameters())
    mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
    return SpmdTrainer(model, opt, loss_fn=paddle.nn.MSELoss(), mesh=mesh,
                       **kw), opt


def _snapshot(tr):
    snap = {f"p/{k}": np.asarray(v).copy() for k, v in tr.params.items()}
    for pname, st in tr.opt_state.items():
        if pname == "__step__":
            snap["__step__"] = np.asarray(st).copy()
        else:
            for k, v in st.items():
                snap[f"s/{pname}/{k}"] = np.asarray(v).copy()
    return snap


def _assert_bit_identical(tr, snap):
    now = _snapshot(tr)
    assert set(now) == set(snap)
    for k in snap:
        assert now[k].tobytes() == snap[k].tobytes(), k


X = np.ones((2, 4), np.float32)
Y = np.zeros((2, 1), np.float32)
XNAN = X.copy()
XNAN[0, 0] = np.nan


class TestGuard:
    def test_nonfinite_step_skips_update_bit_identical(self):
        paddle.set_flags({"check_nan_inf": True})
        tr, opt = _trainer()
        tr.train_step(X, Y)                    # one clean step
        snap = _snapshot(tr)
        count_before = opt._step_count
        loss = tr.train_step(XNAN, Y)          # poisoned batch
        assert np.isnan(float(np.asarray(loss._data)))
        _assert_bit_identical(tr, snap)        # params AND Adam moments
        tr.guard_sync()                        # deferred verdict fetch
        assert opt._step_count == count_before  # LR schedule did not move
        assert tr._nonfinite_streak == 1

    def test_skip_metric_counts(self):
        monitor.reset()
        paddle.set_flags({"check_nan_inf": True})
        tr, _ = _trainer()
        tr.train_step(XNAN, Y)
        tr.guard_sync()
        skipped = monitor.counter("train_step_skipped_total",
                                  labelnames=("reason",))
        assert skipped.labels(reason="nonfinite").value == 1

    def test_finite_step_resets_the_streak(self):
        paddle.set_flags({"check_nan_inf": True, "max_skip_steps": 2})
        tr, _ = _trainer()
        tr.train_step(XNAN, Y)
        tr.train_step(XNAN, Y)
        tr.guard_sync()
        assert tr._nonfinite_streak == 2
        tr.train_step(X, Y)                    # recovery
        tr.guard_sync()
        assert tr._nonfinite_streak == 0
        tr.train_step(XNAN, Y)                 # a fresh streak may restart
        tr.guard_sync()
        assert tr._nonfinite_streak == 1

    def test_raises_after_max_consecutive_skips(self):
        paddle.set_flags({"check_nan_inf": True, "max_skip_steps": 2})
        tr, _ = _trainer()
        snap = _snapshot(tr)
        tr.train_step(XNAN, Y)
        tr.train_step(XNAN, Y)
        tr.train_step(XNAN, Y)
        with pytest.raises(FloatingPointError, match="max_skip_steps"):
            tr.guard_sync()                    # the deferred raise site
        _assert_bit_identical(tr, snap)        # nothing ever applied

    def test_raise_also_fires_from_the_next_step_entry(self):
        """Without an explicit guard_sync, the window-1 entry drain of
        the NEXT train_step call surfaces the deferred raise — the run
        cannot silently train past the streak limit."""
        paddle.set_flags({"check_nan_inf": True, "max_skip_steps": 1})
        tr, _ = _trainer()
        tr.train_step(XNAN, Y)
        tr.train_step(XNAN, Y)   # entry drain books skip 1 (<= max)
        with pytest.raises(FloatingPointError, match="max_skip_steps"):
            tr.train_step(X, Y)  # entry drain books skip 2 -> raise

    def test_inf_gradient_also_skips(self):
        paddle.set_flags({"check_nan_inf": True})
        tr, _ = _trainer()
        snap = _snapshot(tr)
        xinf = X.copy()
        xinf[0, 0] = np.inf
        tr.train_step(xinf, Y)
        _assert_bit_identical(tr, snap)

    def test_flag_off_is_pre_guard_behavior(self):
        tr, opt = _trainer()
        loss = tr.train_step(XNAN, Y)          # default flag: no guard
        assert np.isnan(float(np.asarray(loss._data)))
        # the update DID apply (NaN propagates into params) and counters moved
        assert opt._step_count == 1
        assert any(np.isnan(np.asarray(v)).any()
                   for v in tr.params.values())

    def test_toggling_flag_recompiles_not_misunpacks(self):
        tr, opt = _trainer()
        tr.train_step(X, Y)                    # unguarded executable cached
        paddle.set_flags({"check_nan_inf": True})
        snap = _snapshot(tr)
        tr.train_step(XNAN, Y)                 # guarded executable, same sig
        _assert_bit_identical(tr, snap)
        paddle.set_flags({"check_nan_inf": False})
        tr.train_step(X, Y)                    # back to the unguarded one
        assert opt._step_count == 2

    def test_guarded_clean_training_still_converges(self):
        paddle.set_flags({"check_nan_inf": True})
        tr, _ = _trainer()
        losses = [float(np.asarray(tr.train_step(X, Y)._data))
                  for _ in range(5)]
        assert losses[-1] < losses[0]
        assert tr._nonfinite_streak == 0
