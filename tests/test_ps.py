"""Parameter-server mode tests.

Modeled on the reference's PS test strategy (SURVEY.md §4):
- table semantics unit tests = paddle/fluid/distributed/test/sparse_table_test.cc
- in-process server+client on localhost ports = brpc_service_dense_sgd_test.cc
- multi-worker convergence = test_dist_base.py (threads stand in for processes;
  the RPC path is identical).
"""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import (
    Communicator,
    DenseTable,
    GeoSparseTable,
    PsClient,
    PsEmbedding,
    PsServer,
    SparseTable,
    TheOnePs,
)
from paddle_tpu.distributed.fleet.meta_optimizers import PsDenseOptimizer


# ---------- table semantics (no RPC) -----------------------------------------
class TestTables:
    def test_dense_sgd(self):
        t = DenseTable((4,), optimizer="sgd", lr=0.5, init=np.ones(4, np.float32))
        t.push(np.full(4, 2.0, np.float32))
        np.testing.assert_allclose(t.pull(), np.zeros(4))

    def test_dense_adam_moves_toward_minimum(self):
        t = DenseTable((2,), optimizer="adam", lr=0.1, init=np.array([1.0, -1.0], np.float32))
        for _ in range(50):
            t.push(t.pull())  # grad = x for loss x^2/2
        assert np.abs(t.pull()).max() < 0.5

    def test_sparse_autoinit_and_update(self):
        t = SparseTable(3, optimizer="sgd", lr=1.0, initializer="zeros")
        rows = t.pull([5, 9, 5])
        assert rows.shape == (3, 3)
        np.testing.assert_allclose(rows, 0)
        # duplicate ids in one push accumulate
        t.push([5, 5, 9], np.ones((3, 3), np.float32))
        np.testing.assert_allclose(t.pull([5])[0], [-2, -2, -2])
        np.testing.assert_allclose(t.pull([9])[0], [-1, -1, -1])
        assert t.size() == 2

    def test_sparse_adagrad(self):
        t = SparseTable(2, optimizer="adagrad", lr=0.1, initializer="zeros")
        t.push([1], np.ones((1, 2), np.float32))
        # g2sum=1 -> delta = 0.1/1
        np.testing.assert_allclose(t.pull([1])[0], [-0.1, -0.1], atol=1e-5)

    def test_geo_delta_exchange(self):
        t = GeoSparseTable(2, trainers=2, initializer="zeros")
        t.push_delta(0, [7], np.full((1, 2), 0.5, np.float32))
        ids, deltas = t.pull_geo(1)  # trainer 1 sees trainer 0's delta
        np.testing.assert_array_equal(ids, [7])
        np.testing.assert_allclose(deltas, 0.5)
        ids2, _ = t.pull_geo(1)  # drained
        assert len(ids2) == 0
        ids0, _ = t.pull_geo(0)  # own pushes not echoed back
        assert len(ids0) == 0


# ---------- RPC server/client ------------------------------------------------
@pytest.fixture()
def two_servers():
    servers = [PsServer(port=0, worker_num=2).start() for _ in range(2)]
    yield servers
    for s in servers:
        s.shutdown()


class TestRpcPath:
    def test_dense_roundtrip_server_side_sgd(self, two_servers):
        client = PsClient([s.endpoint for s in two_servers])
        client.create_dense_table(0, (3,), optimizer="sgd", lr=0.5,
                                  init=np.ones(3, np.float32))
        client.push_dense(0, np.full(3, 2.0, np.float32))
        np.testing.assert_allclose(client.pull_dense(0), np.zeros(3))
        client.close()

    def test_sparse_sharded_across_servers(self, two_servers):
        client = PsClient([s.endpoint for s in two_servers])
        client.create_sparse_table(1, 4, optimizer="sgd", lr=1.0, initializer="zeros")
        ids = np.array([0, 1, 2, 3, 10, 11], np.int64)  # both parities -> both shards
        rows = client.pull_sparse(1, ids)
        assert rows.shape == (6, 4)
        client.push_sparse(1, ids, np.ones((6, 4), np.float32))
        np.testing.assert_allclose(client.pull_sparse(1, ids), -1)
        # each shard only holds its own rows
        sizes = [s._tables[1].size() for s in two_servers]
        assert sorted(sizes) == [3, 3]
        client.close()

    def test_barrier_two_workers(self, two_servers):
        eps = [s.endpoint for s in two_servers]
        results = []

        def worker(tid):
            c = PsClient(eps, trainer_id=tid)
            results.append(c.barrier())
            c.close()

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10)
        assert results == [True, True]

    def test_heartbeat_monitor(self, two_servers):
        client = PsClient([s.endpoint for s in two_servers], trainer_id=0)
        alive = client._conns[0].call("heartbeat", 0)
        assert alive == 1
        assert two_servers[0]._monitor.dead_workers() == []
        client.close()

    def test_stop(self):
        server = PsServer(port=0, worker_num=1)
        run_t = threading.Thread(target=server.run, daemon=True)
        run_t.start()
        import time

        time.sleep(0.2)
        client = PsClient([server.endpoint])
        client.stop_server()
        run_t.join(timeout=10)
        assert not run_t.is_alive()
        client.close()


# ---------- async communicator ------------------------------------------------
class TestCommunicator:
    def test_async_merge_and_apply(self, two_servers):
        client = PsClient([s.endpoint for s in two_servers])
        client.create_dense_table(0, (2,), optimizer="sum", lr=1.0,
                                  init=np.zeros(2, np.float32))
        comm = Communicator(client, mode="async", max_merge_var_num=4)
        for _ in range(8):
            comm.push_dense_async(0, np.ones(2, np.float32))
        comm.flush()
        comm.stop()
        np.testing.assert_allclose(client.pull_dense(0), -8)
        client.close()


# ---------- end-to-end: PS-backed training ------------------------------------
class TestPsTraining:
    def test_ps_embedding_regression_single_worker(self, two_servers):
        """Sparse embedding pulled from PS, trained via server-side sgd."""
        paddle.seed(0)
        client = PsClient([s.endpoint for s in two_servers])
        emb = PsEmbedding(table_id=3, embedding_dim=4, client=client,
                          optimizer="sgd", lr=1.0)
        ids = paddle.to_tensor(np.array([1, 2, 3, 4], np.int64))
        target = paddle.to_tensor(np.random.RandomState(0).randn(4, 4).astype(np.float32))
        losses = []
        for _ in range(30):
            out = emb(ids)
            loss = paddle.mean((out - target) ** 2)
            loss.backward()
            emb.push_step()
            losses.append(float(np.asarray(loss._data)))
        assert losses[-1] < 0.1 * losses[0]
        client.close()

    def test_dense_ps_optimizer_two_workers(self, two_servers):
        """Two workers hogwild-train shared dense params through the PS."""
        eps = [s.endpoint for s in two_servers]
        w_true = np.array([[2.0], [-1.0]], np.float32)
        rng = np.random.RandomState(0)
        X = rng.randn(64, 2).astype(np.float32)
        Y = X @ w_true

        def worker(tid, losses):
            lin = paddle.nn.Linear(2, 1)
            client = PsClient(eps, trainer_id=tid)
            opt = PsDenseOptimizer(lin.parameters(), client, optimizer="sgd", lr=0.1)
            if tid == 0:  # worker 0's init wins (create is idempotent)
                pass
            for i in range(40):
                xb = paddle.to_tensor(X[(tid * 8 + i) % 56:(tid * 8 + i) % 56 + 8])
                yb = paddle.to_tensor(Y[(tid * 8 + i) % 56:(tid * 8 + i) % 56 + 8])
                loss = paddle.mean((lin(xb) - yb) ** 2)
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(np.asarray(loss._data)))
            client.close()

        l0, l1 = [], []
        ts = [threading.Thread(target=worker, args=(0, l0)),
              threading.Thread(target=worker, args=(1, l1))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert min(l0[-5:]) < 0.1 * l0[0]
        assert min(l1[-5:]) < 0.1 * l1[0]


# ---------- fleet integration --------------------------------------------------
class TestFleetPsIntegration:
    def test_runtime_roles_via_env(self, monkeypatch):
        server = PsServer(port=0, worker_num=1).start()
        monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST", server.endpoint)
        monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
        from paddle_tpu.distributed.fleet.role_maker import PaddleCloudRoleMaker
        from paddle_tpu.distributed.fleet.distributed_strategy import DistributedStrategy

        strategy = DistributedStrategy()
        strategy.a_sync = True
        rt = TheOnePs(role_maker=PaddleCloudRoleMaker(is_collective=False),
                      strategy=strategy)
        client = rt.init_worker()
        assert rt.mode == "async" and rt.communicator is not None
        client.create_dense_table(0, (2,), optimizer="sgd", lr=1.0,
                                  init=np.zeros(2, np.float32))
        assert client.pull_dense(0).shape == (2,)
        rt.stop_worker()
        server.shutdown()

    def test_geo_mode_selected_by_k_steps(self):
        from paddle_tpu.distributed.fleet.distributed_strategy import DistributedStrategy

        server = PsServer(port=0, worker_num=1).start()
        strategy = DistributedStrategy()
        strategy.a_sync = True
        strategy.a_sync_configs.k_steps = 2
        rt = TheOnePs(strategy=strategy, endpoints=[server.endpoint], worker_num=1)
        rt.init_worker()
        assert rt.mode == "geo"
        rt.stop_worker()
        server.shutdown()

    def test_meta_optimizer_selection(self):
        from paddle_tpu.distributed.fleet.distributed_strategy import DistributedStrategy
        from paddle_tpu.distributed.fleet.meta_optimizers import apply_meta_optimizers

        strategy = DistributedStrategy()
        strategy.a_sync = True
        kw, _ = apply_meta_optimizers({}, None, strategy)
        assert kw.get("ps_mode") is True


def test_sparse_entry_admission():
    from paddle_tpu.distributed.ps.tables import (CountFilterEntry,
                                                  ProbabilityEntry,
                                                  SparseTable)

    t = SparseTable(4, entry=CountFilterEntry(3))
    import numpy as np

    # pushes before admission are dropped, pulls read zeros
    t.push([7], np.ones((1, 4), np.float32))
    assert t.size() == 0
    v1 = t.pull([7])          # seen 2x now (push + pull)
    np.testing.assert_allclose(v1, 0.0)
    v2 = t.pull([7])          # 3rd sighting -> admitted
    assert t.size() == 1
    # ProbabilityEntry(1.0) admits immediately; (0.0) never does
    t2 = SparseTable(4, entry=ProbabilityEntry(0.0))
    t2.pull([1])
    assert t2.size() == 0
    t3 = SparseTable(4, entry=ProbabilityEntry(1.0))
    t3.pull([1])
    assert t3.size() == 1
