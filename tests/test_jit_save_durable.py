"""Durable jit.save/load (VERDICT r1 #7): the saved artifact must run
without the original class definition — jax.export program + params
(reference: fluid/dygraph/jit.py:160 save + dygraph/io.py TranslatedLayer).
"""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def _make_unpicklable_net():
    """A Layer class created in a throwaway namespace: pickle cannot find it,
    so only the durable artifact can serve jit.load."""
    ns = {}
    exec(textwrap.dedent("""
        import paddle_tpu as paddle
        from paddle_tpu import nn

        class Throwaway(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.fc2 = nn.Linear(8, 3)

            def forward(self, x):
                return self.fc2(nn.functional.relu(self.fc1(x)))
    """), ns)
    return ns["Throwaway"]()


class TestDurableJitSave:
    def test_load_without_class(self, tmp_path):
        paddle.seed(0)
        net = _make_unpicklable_net()
        net.eval()
        x = paddle.to_tensor(np.random.RandomState(0).rand(2, 4).astype(np.float32))
        want = net(x).numpy()

        prefix = str(tmp_path / "durable")
        paddle.jit.save(net, prefix,
                        input_spec=[paddle.jit.InputSpec([2, 4], "float32")])
        assert os.path.exists(prefix + ".pdmodel.jaxexport")

        loaded = paddle.jit.load(prefix)
        from paddle_tpu.jit import TranslatedLayer

        assert isinstance(loaded, TranslatedLayer)
        got = loaded(x)
        np.testing.assert_allclose(np.asarray(got._data), want, rtol=1e-5)

    def test_fresh_process_load(self, tmp_path):
        """Save here; load + predict in a NEW python process that never sees
        the class definition."""
        paddle.seed(1)
        net = _make_unpicklable_net()
        net.eval()
        x = np.random.RandomState(1).rand(2, 4).astype(np.float32)
        want = net(paddle.to_tensor(x)).numpy()
        prefix = str(tmp_path / "fresh")
        paddle.jit.save(net, prefix,
                        input_spec=[paddle.jit.InputSpec([2, 4], "float32")])
        np.save(str(tmp_path / "x.npy"), x)
        np.save(str(tmp_path / "want.npy"), want)

        script = textwrap.dedent(f"""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import numpy as np
            import paddle_tpu as paddle

            x = np.load({str(tmp_path / 'x.npy')!r})
            want = np.load({str(tmp_path / 'want.npy')!r})
            loaded = paddle.jit.load({prefix!r})
            got = loaded(paddle.to_tensor(x))
            np.testing.assert_allclose(np.asarray(got._data), want, rtol=1e-5)
            print("FRESH-PROCESS-OK")
        """)
        sp = str(tmp_path / "load_script.py")
        with open(sp, "w") as f:
            f.write(script)
        env = dict(os.environ, PYTHONPATH=os.getcwd(), JAX_PLATFORMS="cpu")
        res = subprocess.run([sys.executable, sp], capture_output=True,
                             text=True, timeout=300, env=env)
        assert "FRESH-PROCESS-OK" in res.stdout, res.stderr[-2000:]

    def test_pickle_fallback_still_works(self, tmp_path):
        """No input_spec + picklable layer: legacy re-trace path."""
        net = nn.Sequential(nn.Linear(3, 2))
        prefix = str(tmp_path / "legacy")
        paddle.jit.save(net, prefix)
        assert not os.path.exists(prefix + ".pdmodel.jaxexport")
        loaded = paddle.jit.load(prefix)
        x = paddle.to_tensor(np.ones((1, 3), np.float32))
        np.testing.assert_allclose(np.asarray(loaded(x)._data),
                                   np.asarray(net(x)._data), rtol=1e-6)

    def test_unpicklable_without_spec_errors_helpfully(self, tmp_path):
        net = _make_unpicklable_net()
        prefix = str(tmp_path / "nospec")
        paddle.jit.save(net, prefix)
        with pytest.raises(RuntimeError, match="input_spec"):
            paddle.jit.load(prefix)

    def test_resave_without_spec_serves_new_model(self, tmp_path):
        """Review r2e: a stale jax.export artifact from a previous save must
        not shadow a re-save without input_spec."""
        paddle.seed(0)
        v1 = nn.Sequential(nn.Linear(4, 3))
        prefix = str(tmp_path / "resave")
        paddle.jit.save(v1, prefix,
                        input_spec=[paddle.jit.InputSpec([2, 4], "float32")])
        paddle.seed(7)
        v2 = nn.Sequential(nn.Linear(4, 3))
        paddle.jit.save(v2, prefix)  # no spec: pickle-only save
        assert not os.path.exists(prefix + ".pdmodel.jaxexport")
        loaded = paddle.jit.load(prefix)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        np.testing.assert_allclose(np.asarray(loaded(x)._data),
                                   np.asarray(v2(x)._data), rtol=1e-6)

    def test_two_dynamic_batch_inputs_share_symbol(self, tmp_path):
        """Review r2e: inputs related along batch need one shared symbol."""
        class TwoIn(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, a, b):
                return self.fc(a) + b  # requires batch(a) == batch(b)

        net = TwoIn()
        prefix = str(tmp_path / "twoin")
        paddle.jit.save(net, prefix, input_spec=[
            paddle.jit.InputSpec([None, 4], "float32"),
            paddle.jit.InputSpec([None, 4], "float32")])
        assert os.path.exists(prefix + ".pdmodel.jaxexport")
        loaded = paddle.jit.load(prefix)
        for bs in (2, 5):
            a = paddle.to_tensor(np.ones((bs, 4), np.float32))
            got = loaded(a, a)
            np.testing.assert_allclose(np.asarray(got._data),
                                       np.asarray(net(a, a)._data), rtol=1e-5)
