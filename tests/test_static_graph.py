"""Static-graph Program/Executor tests (VERDICT r1 #4): the reference's
canonical static scripts — fit-a-line (book/ch02) and a static MNIST MLP
(book/ch03 recognize_digits shape) — run unmodified against the recorded
Program + jax.jit replay executor (fluid/executor.py:916, framework.py:4174).
"""
import numpy as np
import pytest

import paddle_tpu as paddle


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    try:
        yield
    finally:
        paddle.disable_static()


def _build_fit_a_line():
    main = paddle.static.Program()
    startup = paddle.static.Program()
    with paddle.static.program_guard(main, startup):
        x = paddle.static.data(name="x", shape=[None, 13], dtype="float32")
        y = paddle.static.data(name="y", shape=[None, 1], dtype="float32")
        pred = paddle.static.nn.fc(x, size=1)
        loss = paddle.mean(
            paddle.nn.functional.square_error_cost(input=pred, label=y))
        test_program = main.clone(for_test=True)
        opt = paddle.optimizer.SGD(learning_rate=0.05)
        opt.minimize(loss)
    return main, startup, test_program, x, y, pred, loss


class TestFitALine:
    def test_canonical_script_trains(self):
        main, startup, test_prog, x, y, pred, loss = _build_fit_a_line()
        rng = np.random.RandomState(0)
        w_true = rng.randn(13, 1).astype(np.float32)
        xs = rng.randn(256, 13).astype(np.float32)
        ys = xs @ w_true + 0.01 * rng.randn(256, 1).astype(np.float32)

        exe = paddle.static.Executor(paddle.CPUPlace())
        exe.run(startup)
        losses = []
        for epoch in range(60):
            (l,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
            losses.append(float(l))
        assert losses[-1] < 0.1 * losses[0], losses[::20]

        # inference on the cloned test program: no optimizer step, label-free
        (p,) = exe.run(test_prog, feed={"x": xs[:8]}, fetch_list=[pred])
        assert p.shape == (8, 1)
        np.testing.assert_allclose(p, xs[:8] @ w_true, atol=0.5)

    def test_startup_rerun_resets_params(self):
        main, startup, test_prog, x, y, pred, loss = _build_fit_a_line()
        rng = np.random.RandomState(1)
        xs = rng.randn(64, 13).astype(np.float32)
        ys = rng.randn(64, 1).astype(np.float32)
        exe = paddle.static.Executor()
        exe.run(startup)
        (l0,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        for _ in range(5):
            exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        with paddle.static.program_guard(main, startup):
            exe.run(startup)  # re-initialize
            (l1,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-5)

    def test_executor_validates_feed_and_fetch(self):
        main, startup, test_prog, x, y, pred, loss = _build_fit_a_line()
        exe = paddle.static.Executor()
        exe.run(startup)
        xs = np.zeros((4, 13), np.float32)
        with pytest.raises(ValueError, match="missing from feed"):
            exe.run(main, feed={"x": xs}, fetch_list=[loss])
        with pytest.raises(ValueError, match="not a static.data placeholder"):
            exe.run(main, feed={"x": xs, "bogus": xs,
                                "y": np.zeros((4, 1), np.float32)},
                    fetch_list=[loss])

    def test_batch_size_polymorphism(self):
        """None batch dims: the same program runs at any fed batch size."""
        main, startup, test_prog, x, y, pred, loss = _build_fit_a_line()
        exe = paddle.static.Executor()
        exe.run(startup)
        for bs in (4, 16, 32):
            xs = np.random.rand(bs, 13).astype(np.float32)
            ys = np.random.rand(bs, 1).astype(np.float32)
            (l,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
            assert np.isfinite(float(l))

    def test_monitor_counts_compiles_and_steps(self):
        """ISSUE 2: Executor.run streams compile-cache and step-latency
        telemetry — per feed-signature, one miss then hits; a new batch
        size is a new signature (and a fresh compile)."""
        from paddle_tpu import monitor

        monitor.reset()
        main, startup, test_prog, x, y, pred, loss = _build_fit_a_line()
        exe = paddle.static.Executor()
        exe.run(startup)
        cache = monitor.counter("compile_cache_total",
                                labelnames=("site", "event", "sig",
                                            "source"))
        xs = np.random.rand(8, 13).astype(np.float32)
        ys = np.random.rand(8, 1).astype(np.float32)
        for _ in range(3):
            exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        sig = "x:float32[8,13]|y:float32[8,1]"
        assert cache.labels(site="executor", event="miss", sig=sig,
                            source="fresh").value == 1
        assert cache.labels(site="executor", event="hit", sig=sig,
                            source="memory").value == 2
        exe.run(main, feed={"x": xs[:4], "y": ys[:4]}, fetch_list=[loss])
        assert cache.labels(site="executor", event="miss",
                            sig="x:float32[4,13]|y:float32[4,1]",
                            source="fresh").value == 1
        assert monitor.counter("compile_total", labelnames=("site",)) \
            .labels(site="executor").value == 2
        assert monitor.histogram("step_latency_ms", labelnames=("site",)) \
            .labels(site="executor").count == 4

    def test_feed_dict_order_is_canonicalized(self):
        """Regression: the jit-cache key sorts the feed signature, but the
        compiled closure used to be built from dict INSERTION order — two
        insertion orders of the same feeds aliased one cache entry. Feeds
        are now sorted before compile, so both orders share one compile
        AND produce identical results."""
        from paddle_tpu import monitor

        monitor.reset()
        main, startup, test_prog, x, y, pred, loss = _build_fit_a_line()
        exe = paddle.static.Executor()
        exe.run(startup)
        xs = np.random.rand(8, 13).astype(np.float32)
        ys = np.random.rand(8, 1).astype(np.float32)
        fwd = {"x": xs, "y": ys}
        rev = {"y": ys, "x": xs}
        assert list(fwd) != list(rev)  # genuinely different insertion order
        (l1,) = exe.run(main, feed=fwd, fetch_list=[loss])
        # reversed-order feed must hit the same cache entry and stay
        # correct (it replays through the sorted closure)
        (l2,) = exe.run(main, feed=rev, fetch_list=[loss])
        assert np.isfinite(float(l1)) and np.isfinite(float(l2))
        cache = monitor.counter("compile_cache_total",
                                labelnames=("site", "event", "sig",
                                            "source"))
        sig = "x:float32[8,13]|y:float32[8,1]"
        assert cache.labels(site="executor", event="miss", sig=sig,
                            source="fresh").value == 1
        assert cache.labels(site="executor", event="hit", sig=sig,
                            source="memory").value == 1


class TestStaticMnistMLP:
    def test_recognize_digits_shape(self):
        """book/ch03 shape: two fc+relu layers, softmax cross-entropy, Adam,
        accuracy fetched alongside the loss."""
        main = paddle.static.Program()
        startup = paddle.static.Program()
        with paddle.static.program_guard(main, startup):
            img = paddle.static.data(name="img", shape=[None, 784],
                                     dtype="float32")
            label = paddle.static.data(name="label", shape=[None, 1],
                                       dtype="int64")
            h = paddle.static.nn.fc(img, size=64, activation="relu")
            logits = paddle.static.nn.fc(h, size=10)
            loss = paddle.mean(paddle.nn.functional.cross_entropy(
                logits, paddle.reshape(label, [-1])))
            acc = paddle.metric.accuracy(input=paddle.nn.functional.softmax(logits),
                                         label=label)
            opt = paddle.optimizer.Adam(learning_rate=1e-2)
            opt.minimize(loss)

        rng = np.random.RandomState(0)
        # separable synthetic digits: class mean + noise
        means = rng.randn(10, 784).astype(np.float32)
        ys = rng.randint(0, 10, 256)
        xs = means[ys] + 0.1 * rng.randn(256, 784).astype(np.float32)
        yb = ys.reshape(-1, 1).astype(np.int64)

        exe = paddle.static.Executor()
        exe.run(startup)
        accs = []
        for _ in range(30):
            l, a = exe.run(main, feed={"img": xs, "label": yb},
                           fetch_list=[loss, acc])
            accs.append(float(a))
        assert accs[-1] > 0.9, accs


class TestProgramIntrospection:
    def test_parameters_and_vars_listed(self):
        main, startup, test_prog, x, y, pred, loss = _build_fit_a_line()
        params = main.all_parameters()
        assert len(params) == 2  # fc weight + bias
        assert any(v is x for v in main.list_vars())

    def test_state_dict_tracks_training(self):
        main, startup, test_prog, *_rest = _build_fit_a_line()
        loss = _rest[-1]
        exe = paddle.static.Executor()
        exe.run(startup)
        before = {k: v.numpy().copy() for k, v in main.state_dict().items()}
        xs = np.random.rand(16, 13).astype(np.float32)
        ys = np.random.rand(16, 1).astype(np.float32)
        exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        after = main.state_dict()
        changed = any(not np.allclose(before[k], after[k].numpy())
                      for k in before)
        assert changed


class TestReviewFindings:
    """Regressions for code-review r2 findings on the static executor."""

    def test_inplace_op_in_graph(self):
        """SSA resolution: an in-place op on a recorded intermediate must
        keep the original producer reachable (rebind finding)."""
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data(name="x", shape=[None, 4], dtype="float32")
            h = x * 2.0
            h.add_(paddle.to_tensor(np.ones((1, 4), np.float32)))
            out = h.sum()
        exe = paddle.static.Executor()
        xs = np.full((2, 4), 3.0, np.float32)
        (o,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
        np.testing.assert_allclose(float(o), (3.0 * 2 + 1) * 8)

    def test_minimize_outside_program_raises(self):
        eager_loss = paddle.to_tensor(np.float32(1.0))
        eager_loss.stop_gradient = False
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        with pytest.raises(ValueError, match="not built in this program"):
            opt.minimize(eager_loss)

    def test_startup_reset_outside_guard(self):
        """exe.run(startup) outside the guard resets its PAIRED main."""
        main, startup, test_prog, x, y, pred, loss = _build_fit_a_line()
        rng = np.random.RandomState(2)
        xs = rng.randn(32, 13).astype(np.float32)
        ys = rng.randn(32, 1).astype(np.float32)
        exe = paddle.static.Executor()
        exe.run(startup)
        (l0,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        for _ in range(4):
            exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        exe.run(startup)  # outside any program_guard
        (l1,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-5)

    def test_second_model_params_untouched(self):
        """Only params the minimized loss reaches are updated: a second model
        in the same program must not decay/step (weight-decay finding)."""
        main = paddle.static.Program()
        startup = paddle.static.Program()
        with paddle.static.program_guard(main, startup):
            x = paddle.static.data(name="x", shape=[None, 4], dtype="float32")
            y = paddle.static.data(name="y", shape=[None, 1], dtype="float32")
            pred1 = paddle.static.nn.fc(x, size=1)
            pred2 = paddle.static.nn.fc(x, size=1)  # bystander model
            loss = paddle.mean(
                paddle.nn.functional.square_error_cost(pred1, y))
            opt = paddle.optimizer.AdamW(learning_rate=0.1, weight_decay=0.5)
            opt.minimize(loss)
        exe = paddle.static.Executor()
        exe.run(startup)
        before = {k: v.numpy().copy() for k, v in main.state_dict().items()}
        xs = np.random.rand(8, 4).astype(np.float32)
        ys = np.random.rand(8, 1).astype(np.float32)
        for _ in range(3):
            exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        after = {k: v.numpy() for k, v in main.state_dict().items()}
        changed = [k for k in before if not np.allclose(before[k], after[k])]
        # exactly the 2 params of model 1 (weight+bias) moved
        assert len(changed) == 2, changed

    def test_params_added_after_first_run(self):
        """_ensure_scope top-up: extending a program after running it."""
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data(name="x", shape=[None, 4], dtype="float32")
            h = paddle.static.nn.fc(x, size=3)
        exe = paddle.static.Executor()
        xs = np.random.rand(2, 4).astype(np.float32)
        (h0,) = exe.run(main, feed={"x": xs}, fetch_list=[h])
        with paddle.static.program_guard(main):
            out = paddle.static.nn.fc(h, size=2)
        (o,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
        assert o.shape == (2, 2)

    def test_fetch_rewrapped_and_inplace_tensors(self):
        """Executor fetch resolves via array identity (review r2b)."""
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data(name="x", shape=[None, 3], dtype="float32")
            h = x * 3.0
            rewrapped = paddle.Tensor(h)  # new object, same array
        exe = paddle.static.Executor()
        xs = np.ones((2, 3), np.float32)
        (o,) = exe.run(main, feed={"x": xs}, fetch_list=[rewrapped])
        np.testing.assert_allclose(o, xs * 3.0)

    def test_unfed_placeholder_fetch_raises_cleanly(self):
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data(name="x", shape=[None, 3], dtype="float32")
            y = paddle.static.data(name="y", shape=[None, 3], dtype="float32")
            out = x + 0.0
        exe = paddle.static.Executor()
        xs = np.ones((2, 3), np.float32)
        with pytest.raises(ValueError, match="placeholder 'y'"):
            exe.run(main, feed={"x": xs}, fetch_list=[y])


def test_py_func_backward():
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import static

    def forward(a):
        return a * a

    def backward(a, out, dout):
        return 2.0 * a * dout

    x = paddle.to_tensor(np.array([3.0, -2.0], np.float32))
    x.stop_gradient = False
    y = static.py_func(forward, x, None, backward_func=backward)
    y.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad._data), [6.0, -4.0],
                               rtol=1e-6)
    # without backward_func outputs are detached (reference: no grad op)
    z = static.py_func(forward, x, None)
    assert z.stop_gradient


def test_program_translator_enable_toggle():
    import numpy as np

    import paddle_tpu as paddle

    calls = []

    @paddle.jit.to_static
    def f(x):
        calls.append(1)
        return x + 1

    pt = paddle.jit.ProgramTranslator.get_instance()
    try:
        pt.enable(False)
        a = f(paddle.to_tensor(np.ones(2, np.float32)))
        b = f(paddle.to_tensor(np.ones(2, np.float32)))
        # eager fallback: python body runs every call (no trace cache)
        assert len(calls) >= 2
        np.testing.assert_allclose(np.asarray(a._data), 2.0)
    finally:
        pt.enable(True)


def test_static_save_load_program_state(tmp_path):
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import static

    paddle.enable_static()
    try:
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 4])
            lin = paddle.nn.Linear(4, 3)
            y = lin(x)
        exe = static.Executor()
        exe.run(startup)
        path = str(tmp_path / "model")
        static.save(main, path)
        state = static.load_program_state(path)
        assert any(v.size for v in state.values())
        # perturb then restore (write through the scope, not copies)
        static.set_program_state(main, {k: v * 0.0 for k, v in state.items()})
        for v in main.state_dict().values():
            np.testing.assert_allclose(np.asarray(v._data), 0.0)
        static.load(main, path)
        restored = {k: np.asarray(v._data) for k, v in main.state_dict().items()}
        for k, v in state.items():
            np.testing.assert_allclose(restored[k], v)
        # set_program_state roundtrip
        static.set_program_state(main, {k: v * 2 for k, v in state.items()})
        for k, v in state.items():
            np.testing.assert_allclose(
                np.asarray(main.state_dict()[k]._data), v * 2)
    finally:
        paddle.disable_static()


def test_compiled_program_and_parallel_executor():
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import static

    paddle.enable_static()
    try:
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 2])
            y = x * 2.0
        exe = static.Executor()
        exe.run(startup)
        cp = static.CompiledProgram(main).with_data_parallel(loss_name=None)
        out = exe.run(cp._program, feed={"x": np.ones((3, 2), np.float32)},
                      fetch_list=[y])
        np.testing.assert_allclose(out[0], 2.0)
        pe = static.ParallelExecutor(main_program=main)
        out2 = pe.run(feed={"x": np.ones((3, 2), np.float32)}, fetch_list=[y])
        np.testing.assert_allclose(out2[0], 2.0)
    finally:
        paddle.disable_static()
