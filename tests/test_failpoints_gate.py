"""Tier-1 gate for the fault-tolerance layer (ISSUE 4): with nothing armed
every failpoint site is a single boolean check — no fire machinery runs, no
robustness metric series appear, serving/trainer outputs are bit-identical
to the pre-PR engine — and the per-call overhead holds the same <5µs bar as
the monitor's disabled fast path. Plus: tools/chaos_check.py emits the
graph_lint report schema and exits 1 when a recovery path breaks."""
import importlib.util
import os
import sys
import time

import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.testing import failpoints as fp


@pytest.fixture(autouse=True)
def _disarmed():
    fp.reset()
    yield
    fp.reset()


def _forbid_fire(monkeypatch):
    """Any entry into the fire machinery while nothing is armed is a
    regression — the zero-overhead contract."""
    def boom(*a, **k):
        raise AssertionError("failpoint fire machinery ran with nothing "
                             "armed")
    monkeypatch.setattr(fp, "_fire", boom)


def _tiny_model():
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=2, max_seq_len=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


class TestInertByDefault:
    def test_disabled_overhead_under_5us(self):
        """Same bar and method as test_monitor_disabled_overhead /
        the CachedJit gate: a disarmed site costs one boolean check."""
        n = 100_000
        t0 = time.perf_counter()
        for _ in range(n):
            fp.failpoint("serving/step")
        per_call_us = (time.perf_counter() - t0) / n * 1e6
        assert per_call_us < 5.0, (
            f"disarmed failpoint costs {per_call_us:.2f}us/call — the "
            "one-boolean fast path regressed")

    def test_hot_paths_never_enter_fire_machinery(self, monkeypatch,
                                                  tmp_path):
        _forbid_fire(monkeypatch)
        # checkpoint write + read
        p = str(tmp_path / "s.pdparams")
        paddle.save({"w": paddle.to_tensor(np.ones(3))}, p)
        paddle.load(p)
        # executor compile + run
        import paddle_tpu.static as st

        paddle.seed(0)
        main, startup = st.Program(), st.Program()
        st.enable_static()
        try:
            with st.program_guard(main, startup):
                x = st.data("x", [None, 4])
                w = paddle.create_parameter([4, 4])
                y = paddle.matmul(x, w)
        finally:
            st.disable_static()
        exe = st.Executor()
        exe.run(startup)
        (r,) = exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                       fetch_list=[y])
        assert np.isfinite(r).all()
        # collective
        from paddle_tpu.distributed import collective

        collective.all_reduce(paddle.to_tensor(np.ones(2, np.float32)))
        # trainer step
        from paddle_tpu.distributed.mesh import build_mesh
        from paddle_tpu.distributed.spmd import SpmdTrainer

        model = paddle.nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        tr = SpmdTrainer(model, opt, loss_fn=paddle.nn.MSELoss(), mesh=mesh)
        tr.train_step(np.ones((2, 4), np.float32),
                      np.zeros((2, 1), np.float32))

    def test_serving_behavior_and_metrics_identical_to_before(self):
        """Nothing armed, no deadlines/priorities used: the engine's greedy
        output keeps exact solo-generate parity and NONE of the robustness
        metric families grow a series — the zero-drift contract."""
        from paddle_tpu.inference.serving import ServingEngine

        monitor.reset()
        m = _tiny_model()
        rng = np.random.RandomState(0)
        prompts = [rng.randint(0, 64, (n,)).astype(np.int32)
                   for n in (5, 9)]
        eng = ServingEngine(m, max_batch=2)
        rids = [eng.submit(p, max_new_tokens=8) for p in prompts]
        res = eng.run_until_complete()
        for rid, p in zip(rids, prompts):
            ref = m.generate(paddle.to_tensor(p[None]), max_new_tokens=8,
                             temperature=0.0)
            np.testing.assert_array_equal(
                res[rid].tokens, np.asarray(ref._data)[0, len(p):])
            assert res[rid].finish_reason == "length"
        assert eng.health()["state"] == "ok"

        reg = monitor.default_registry()
        for family in ("failpoint_trigger_total", "request_shed_total",
                       "train_step_skipped_total",
                       "checkpoint_recover_total"):
            metric = reg.get(family)
            assert metric is None or not list(metric.series()), family
        assert monitor.counter(
            "request_deadline_exceeded_total").value == 0
        finished = reg.get("serving_requests_finished_total")
        bad = {"error", "deadline", "shed", "cancelled", "engine_stalled"}
        assert not any(s.labels.get("reason") in bad
                       for s in finished.series())

    def test_checkpoint_formats_interoperate(self, tmp_path):
        """The durability footer must not break old readers' expectations:
        a file saved now loads through the plain pickle path (pickle stops
        at its STOP opcode) and a footerless legacy file still loads."""
        import pickle

        p = str(tmp_path / "s.pdparams")
        paddle.save({"v": 41}, p)
        with open(p, "rb") as f:
            assert pickle.load(f) == {"v": 41}   # footer invisible to pickle
        legacy = str(tmp_path / "legacy.pdparams")
        with open(legacy, "wb") as f:
            pickle.dump({"v": 42}, f, protocol=4)
        assert paddle.load(legacy) == {"v": 42}


class TestChaosCheckTool:
    def _load(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "chaos_check", os.path.join(repo, "tools", "chaos_check.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules.pop("chaos_check", None)
        spec.loader.exec_module(mod)
        return mod

    def test_all_recovery_paths_hold(self, capsys):
        import json

        cc = self._load()
        rc = cc.main(["--json"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert set(report) >= {"tool", "passes", "targets", "totals"}
        assert report["tool"] == "chaos_check"
        assert report["totals"]["error"] == 0
        names = {f["pass"]
                 for f in report["targets"]["chaos"]["findings"]}
        assert names == set(cc.PASSES)

    def test_broken_recovery_path_exits_1(self, capsys, monkeypatch):
        """The CI contract: a recovery path that stops recovering fails
        the run. Break the saver's fallback walk and watch it burn."""
        import json

        from paddle_tpu.incubate.checkpoint import auto_checkpoint as ac

        cc = self._load()

        def no_fallback(self, no=None):
            nums = self.get_checkpoint_numbers()
            return self._load_one(nums[-1])   # pre-PR behavior: crash

        monkeypatch.setattr(ac.CheckpointSaver, "load_checkpoint",
                            no_fallback)
        rc = cc.main(["--json", "--only", "ckpt_fallback"])
        assert rc == 1
        report = json.loads(capsys.readouterr().out)
        errs = [f for f in report["targets"]["chaos"]["findings"]
                if f["severity"] == "error"]
        assert any(f["pass"] == "ckpt_fallback" for f in errs)
