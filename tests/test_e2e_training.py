"""End-to-end model tests — the 'book' tests pattern
(fluid/tests/book/test_recognize_digits.py: build + train small models to a
convergence threshold) + hapi Model tests (python/paddle/tests/test_model.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.io import DataLoader, TensorDataset
from paddle_tpu.metric import Accuracy
from paddle_tpu.vision.models import LeNet


def _toy_classification(n=256, d=16, k=4, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(d, k).astype(np.float32)
    x = rng.randn(n, d).astype(np.float32)
    y = (x @ w).argmax(1).astype(np.int64)
    return x, y


class TestEagerTrainingLoop:
    def test_linear_regression_converges(self):
        rng = np.random.RandomState(0)
        x = rng.rand(128, 4).astype(np.float32)
        w_true = np.array([[1.0], [-2.0], [3.0], [0.5]], np.float32)
        y = x @ w_true + 0.1
        net = nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.2, parameters=net.parameters())
        xs, ys = paddle.to_tensor(x), paddle.to_tensor(y)
        for _ in range(300):
            loss = nn.functional.mse_loss(net(xs), ys)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(loss.numpy()) < 1e-3

    def test_mlp_classification_converges(self):
        x, y = _toy_classification()
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
        opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
        xs = paddle.to_tensor(x)
        ys = paddle.to_tensor(y)
        for _ in range(100):
            loss = nn.functional.cross_entropy(net(xs), ys)
            loss.backward()
            opt.step()
            opt.clear_grad()
        pred = net(xs).numpy().argmax(1)
        assert (pred == y).mean() > 0.9


class TestModelFit:
    def _mnist_like(self, n=128):
        rng = np.random.RandomState(0)
        imgs = rng.rand(n, 1, 28, 28).astype(np.float32)
        labels = rng.randint(0, 10, (n, 1)).astype(np.int64)
        # make it learnable: label leaks into a corner patch
        for i in range(n):
            imgs[i, 0, :3, :3] = labels[i, 0] / 10.0
        return TensorDataset([paddle.to_tensor(imgs), paddle.to_tensor(labels)])

    def test_fit_evaluate_predict(self):
        ds = self._mnist_like()
        model = paddle.Model(LeNet())
        opt = paddle.optimizer.Adam(learning_rate=0.001, parameters=model.parameters())
        model.prepare(opt, nn.CrossEntropyLoss(), Accuracy())
        model.fit(ds, epochs=2, batch_size=32, verbose=0)
        res = model.evaluate(ds, batch_size=32, verbose=0)
        assert "loss" in res and "acc" in res
        preds = model.predict(ds, batch_size=32, stack_outputs=True, verbose=0)
        assert preds[0].shape == (128, 10)

    def test_save_load_roundtrip(self, tmp_path):
        ds = self._mnist_like(32)
        model = paddle.Model(LeNet())
        opt = paddle.optimizer.Adam(parameters=model.parameters())
        model.prepare(opt, nn.CrossEntropyLoss())
        model.fit(ds, epochs=1, batch_size=16, verbose=0)
        path = str(tmp_path / "ckpt")
        model.save(path)
        model2 = paddle.Model(LeNet())
        opt2 = paddle.optimizer.Adam(parameters=model2.parameters())
        model2.prepare(opt2, nn.CrossEntropyLoss())
        model2.load(path)
        w1 = model.network.features[0].weight.numpy()
        w2 = model2.network.features[0].weight.numpy()
        np.testing.assert_allclose(w1, w2)

    def test_callbacks_early_stopping(self):
        ds = self._mnist_like(32)
        model = paddle.Model(LeNet())
        opt = paddle.optimizer.Adam(parameters=model.parameters())
        model.prepare(opt, nn.CrossEntropyLoss())
        es = paddle.callbacks.EarlyStopping(monitor="loss", patience=0, mode="min")
        model.fit(ds, eval_data=ds, epochs=3, batch_size=16, verbose=0, callbacks=[es])
        # ran without error; stop_training toggled at most after patience exceeded
        assert hasattr(model, "stop_training")


class TestToStatic:
    def test_function_jit(self):
        calls = []

        @paddle.jit.to_static
        def f(x):
            calls.append(1)
            return x * 2 + 1

        a = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        out1 = f(a)
        out2 = f(a)
        np.testing.assert_allclose(out1.numpy(), [3.0, 5.0])
        np.testing.assert_allclose(out2.numpy(), [3.0, 5.0])
        assert len(calls) == 1  # traced once, cached

    def test_layer_to_static_matches_eager(self):
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        x = paddle.to_tensor(np.random.rand(3, 4).astype(np.float32))
        eager = net(x).numpy()
        snet = paddle.jit.to_static(net)
        static = snet(x).numpy()
        np.testing.assert_allclose(eager, static, rtol=1e-5)

    def test_to_static_retrace_on_shape_change(self):
        @paddle.jit.to_static
        def f(x):
            return x.sum()

        f(paddle.to_tensor(np.zeros((2, 2), np.float32)))
        out = f(paddle.to_tensor(np.ones((3, 3), np.float32)))
        np.testing.assert_allclose(float(out.numpy()), 9.0)


class TestDataLoader:
    def test_single_process(self):
        x = np.arange(20, dtype=np.float32).reshape(10, 2)
        y = np.arange(10, dtype=np.int64).reshape(10, 1)
        ds = TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])
        loader = DataLoader(ds, batch_size=4, shuffle=False, drop_last=False)
        batches = list(loader)
        assert len(batches) == 3
        assert batches[0][0].shape == [4, 2]
        assert batches[2][0].shape == [2, 2]

    def test_shuffle_covers_all(self):
        ds = TensorDataset([paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(16, 1))])
        loader = DataLoader(ds, batch_size=4, shuffle=True)
        seen = np.concatenate([b[0].numpy().ravel() for b in loader])
        assert sorted(seen.tolist()) == list(range(16))

    def test_multiprocess_workers(self):
        from paddle_tpu.io.dataset import Dataset

        class Sq(Dataset):
            def __getitem__(self, i):
                return np.asarray([i * i], dtype=np.float32)

            def __len__(self):
                return 20

        loader = DataLoader(Sq(), batch_size=5, num_workers=2, shuffle=False)
        out = np.concatenate([b[0].numpy() if isinstance(b, list) else b.numpy() for b in loader])
        np.testing.assert_allclose(sorted(out.ravel().tolist()), [i * i for i in range(20)])

    def test_distributed_batch_sampler(self):
        from paddle_tpu.io import DistributedBatchSampler

        ds = TensorDataset([paddle.to_tensor(np.arange(10, dtype=np.float32).reshape(10, 1))])
        s0 = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=0)
        s1 = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=1)
        i0 = [i for b in s0 for i in b]
        i1 = [i for b in s1 for i in b]
        assert len(i0) == len(i1) == 5
        assert set(i0) | set(i1) == set(range(10))


class TestSaveLoad:
    def test_paddle_save_load(self, tmp_path):
        sd = {"w": paddle.to_tensor(np.random.rand(3, 3).astype(np.float32)), "meta": 7}
        p = str(tmp_path / "m.pdparams")
        paddle.save(sd, p)
        back = paddle.load(p)
        np.testing.assert_allclose(back["w"].numpy(), sd["w"].numpy())
        assert back["meta"] == 7


class TestJitAdapterMetricPath:
    def test_metrics_without_second_eager_forward(self):
        """VERDICT r1 weak #6: Model.fit (jit adapter) with metrics attached
        must take outputs from the jitted step, not re-run forward eagerly.
        Eager forwards run python; traced forwards run once per compile —
        counting python invocations outside a trace catches the regression."""
        import jax.core

        net = nn.Sequential(nn.Flatten(), nn.Linear(784, 10))
        calls = {"eager": 0}
        orig_forward = net.forward

        def counting_forward(*a, **kw):
            out = orig_forward(*a, **kw)
            leaf = out[0] if isinstance(out, (list, tuple)) else out
            if not isinstance(leaf._data, jax.core.Tracer):
                calls["eager"] += 1
            return out

        net.forward = counting_forward

        rng = np.random.RandomState(0)
        imgs = rng.rand(64, 1, 28, 28).astype(np.float32)
        labels = rng.randint(0, 10, (64, 1)).astype(np.int64)
        ds = TensorDataset([paddle.to_tensor(imgs), paddle.to_tensor(labels)])

        model = paddle.Model(net, use_jit=True)
        opt = paddle.optimizer.Adam(parameters=model.parameters())
        model.prepare(opt, nn.CrossEntropyLoss(), Accuracy())
        model.fit(ds, epochs=2, batch_size=16, verbose=0)
        # 2 epochs x 4 batches = 8 train steps; every eager call would count
        assert calls["eager"] == 0, f"{calls['eager']} eager forwards ran"

    def test_jit_adapter_metric_values_correct(self):
        """Accuracy from the jitted-step outputs matches an eager recompute."""
        paddle.seed(0)
        net = nn.Sequential(nn.Flatten(), nn.Linear(784, 10))
        rng = np.random.RandomState(0)
        imgs = rng.rand(32, 1, 28, 28).astype(np.float32)
        labels = rng.randint(0, 10, (32, 1)).astype(np.int64)
        ds = TensorDataset([paddle.to_tensor(imgs), paddle.to_tensor(labels)])
        model = paddle.Model(net, use_jit=True)
        opt = paddle.optimizer.SGD(learning_rate=0.0, parameters=model.parameters())
        model.prepare(opt, nn.CrossEntropyLoss(), Accuracy())
        model.fit(ds, epochs=1, batch_size=32, verbose=0)
        # lr=0: params unchanged; metric over the single batch == eager acc
        logits = net(paddle.to_tensor(imgs)).numpy()
        expected = (logits.argmax(1) == labels[:, 0]).mean()
        res = model.evaluate(ds, batch_size=32, verbose=0)
        np.testing.assert_allclose(res["acc"], expected, atol=1e-6)

    def test_reprepare_with_metrics_recompiles(self):
        """Review r2b: prepare() after fit must reset the jit trainer so a
        late-attached metric gets outputs from the step."""
        net = nn.Sequential(nn.Flatten(), nn.Linear(784, 10))
        rng = np.random.RandomState(0)
        imgs = rng.rand(32, 1, 28, 28).astype(np.float32)
        labels = rng.randint(0, 10, (32, 1)).astype(np.int64)
        ds = TensorDataset([paddle.to_tensor(imgs), paddle.to_tensor(labels)])
        model = paddle.Model(net, use_jit=True)
        opt = paddle.optimizer.Adam(parameters=model.parameters())
        model.prepare(opt, nn.CrossEntropyLoss())
        model.fit(ds, epochs=1, batch_size=16, verbose=0)
        model.prepare(opt, nn.CrossEntropyLoss(), Accuracy())
        model.fit(ds, epochs=1, batch_size=16, verbose=0)  # must not crash

    def test_eval_then_fit_keeps_train_mode(self):
        """Review r2h #1: an evaluate() before fit() must not bake eval mode
        (dropout off) into the compiled train step."""
        paddle.seed(0)
        net = nn.Sequential(nn.Flatten(), nn.Linear(784, 32), nn.Dropout(0.5),
                            nn.Linear(32, 10))
        rng = np.random.RandomState(0)
        imgs = rng.rand(32, 1, 28, 28).astype(np.float32)
        labels = rng.randint(0, 10, (32, 1)).astype(np.int64)
        ds = TensorDataset([paddle.to_tensor(imgs), paddle.to_tensor(labels)])
        model = paddle.Model(net, use_jit=True)
        opt = paddle.optimizer.SGD(learning_rate=0.0,
                                   parameters=model.parameters())
        model.prepare(opt, nn.CrossEntropyLoss())
        model.evaluate(ds, batch_size=32, verbose=0)  # net.eval() ran
        r1 = model.train_batch([paddle.to_tensor(imgs)],
                               [paddle.to_tensor(labels)])
        r2 = model.train_batch([paddle.to_tensor(imgs)],
                               [paddle.to_tensor(labels)])
        # lr=0: params frozen; with dropout ACTIVE the two losses differ
        # (different masks); with eval-mode baked in they would be identical
        assert abs(r1[0] - r2[0]) > 1e-8, (r1, r2)

    def test_jit_eval_loss_matches_eager(self):
        paddle.seed(0)
        net = nn.Sequential(nn.Flatten(), nn.Linear(784, 10))
        rng = np.random.RandomState(0)
        imgs = rng.rand(16, 1, 28, 28).astype(np.float32)
        labels = rng.randint(0, 10, (16, 1)).astype(np.int64)
        ds = TensorDataset([paddle.to_tensor(imgs), paddle.to_tensor(labels)])
        m_jit = paddle.Model(net, use_jit=True)
        m_jit.prepare(paddle.optimizer.SGD(parameters=m_jit.parameters()),
                      nn.CrossEntropyLoss())
        r_jit = m_jit.evaluate(ds, batch_size=16, verbose=0)
        m_dyn = paddle.Model(net)
        m_dyn.prepare(paddle.optimizer.SGD(parameters=m_dyn.parameters()),
                      nn.CrossEntropyLoss())
        r_dyn = m_dyn.evaluate(ds, batch_size=16, verbose=0)
        np.testing.assert_allclose(r_jit["loss"], r_dyn["loss"], rtol=1e-5)
