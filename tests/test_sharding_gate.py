"""Tier-1 sharding-flow / transfer-edge / kernel-budget gate (ISSUE 13).

Contract (the acceptance criteria, in executable form):

 - the sharding-flow battery reports ZERO error-severity findings on a
   representative subset of the bundled distributed programs in-process
   (gpt dp8 train, the dp8 quantized step, the pp pipeline step, the
   disagg prefill program) — the full seven-target battery is the
   `python tools/graph_lint.py --sharding` CLI surface;
 - every transfer edge (disagg KV, pipeline stage, federated adapter,
   checkpoint tree) extracts from source, audits clean, and matches the
   recorded tests/handoff_baseline.json fingerprints; a doctored
   baseline makes the CLI exit 1 (the planted-drift subprocess smoke);
 - the Pallas kernel audit reports zero errors over every registered
   manifest (tpp + flash attention + NMS);
 - `ServingEngine.admit_prefilled` consumes the SAME disagg_kv
   declaration the static pass extracts: a good row round-trips, a
   drifted row raises naming the offending leaf — one source of truth,
   regression-tested both ways;
 - the new rules ride --list-rules on both CLIs.

Budget: in-process work is trace-only (~10 s); ONE subprocess pays a
fresh interpreter for the exit-code smoke (AST-only handoff target — no
model tracing in the child). Not slow-marked. The planted-violation
matrix lives in tests/test_analysis_passes.py.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GATED_SHARDING_TARGETS = ("gpt_train", "dp8_quantized", "pipeline",
                          "disagg")


@pytest.fixture(scope="module")
def sharding_reps():
    from paddle_tpu.analysis import sharding_reports

    return sharding_reports(targets=GATED_SHARDING_TARGETS)


@pytest.mark.parametrize("target", GATED_SHARDING_TARGETS)
def test_sharding_zero_errors(sharding_reps, target):
    rep = sharding_reps[target]
    assert rep.errors == [], (
        f"{target}: NEW sharding-flow error findings:\n" + "\n".join(
            f"  [{f.pass_name}] {f.message} @ {f.where}"
            for f in rep.errors))


@pytest.mark.parametrize("target", GATED_SHARDING_TARGETS)
def test_sharding_zero_warnings(sharding_reps, target):
    """The distributed programs stay warning-clean too (implicit
    replication / resharding churn are fixed or threshold-justified,
    never accumulated)."""
    rep = sharding_reps[target]
    assert rep.warnings == [], [repr(f) for f in rep.warnings]


def test_quantized_target_sees_the_wire_ops(sharding_reps):
    """The dp8 quantized target actually exercised the int8 exchange —
    the collective-count pass must name the quantized reduce family."""
    msgs = [f.message for f in sharding_reps["dp8_quantized"].findings
            if f.pass_name == "collective-count"]
    assert any("quantized reduce family" in m for m in msgs), msgs


def test_pipeline_target_sees_the_ring(sharding_reps):
    """The pipeline target carries the ppermute ring (the thing the
    bijectivity pass exists to police)."""
    msgs = [f.message for f in sharding_reps["pipeline"].findings
            if f.pass_name == "collective-count"]
    assert any("collective-permute" in m for m in msgs), msgs


# ---------------------------------------------------------------------------
# transfer edges
# ---------------------------------------------------------------------------


def test_handoff_audit_clean_and_baselined():
    from paddle_tpu.analysis import handoff_schema as hs

    findings = hs.audit_package()
    assert findings == [], [repr(f) for f in findings]
    base = json.load(open(hs.BASELINE_PATH))
    decls, errs = hs.load_declarations()
    assert errs == []
    assert set(base["edges"]) == set(decls) == set(hs.EDGES)
    for edge, decl in decls.items():
        assert base["edges"][edge] == hs.fingerprint(decl)


def test_pallas_audit_zero_errors():
    from paddle_tpu.analysis import pallas_audit

    errs = [f for f in pallas_audit.audit_package()
            if f.severity == "error"]
    assert errs == [], [repr(f) for f in errs]
    # the manifest actually covers all three kernel families
    kerns = {e["kernel"].split(".")[0]
             for e in pallas_audit.collect_manifest()}
    assert kerns == {"tpp", "flash", "nms"}


def test_list_rules_carries_the_new_vocabulary():
    from paddle_tpu.analysis import contract_rules, rule_table

    rules = contract_rules()
    for rule in ("implicit-replication", "resharding-churn",
                 "collective-axis-mismatch", "ppermute-malformed",
                 "branch-collective-mismatch", "handoff-schema-drift",
                 "kernel-vmem-over-budget",
                 "kernel-low-precision-accumulator"):
        assert rule in rules, rule
        assert rule in rule_table()


# ---------------------------------------------------------------------------
# runtime <-> static: one declaration, consumed from both sides
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_engine_and_row():
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving.disagg import PrefillWorker

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=32, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    eng = ServingEngine(m, max_batch=1)
    worker = PrefillWorker(m, prompt_buckets=(16,))
    row, logits = worker.prefill(np.arange(5, dtype=np.int32))
    return m, eng, row, logits


def test_admit_prefilled_validates_against_the_declaration(
        tiny_engine_and_row):
    import jax.numpy as jnp

    from paddle_tpu.analysis.handoff_schema import HandoffMismatch
    from paddle_tpu.inference.serving import ServingEngine

    m, eng, row, logits = tiny_engine_and_row
    # the good row is admitted and serves (the bit-exactness half lives
    # in tests/test_serving_disagg.py)
    rid = eng.admit_prefilled(np.arange(5, dtype=np.int32), row, logits,
                              max_new_tokens=2)
    eng.run_until_complete()
    assert len(eng.get_request(rid).output_ids) == 2

    # drifted rows raise NAMING the leaf — before any slot is touched
    fresh = ServingEngine(m, max_batch=1)
    with pytest.raises(HandoffMismatch, match=r"\[disagg_kv\] kc: dtype"):
        fresh.admit_prefilled(np.arange(5, dtype=np.int32),
                              (row[0].astype(jnp.bfloat16), row[1]),
                              logits)
    with pytest.raises(HandoffMismatch, match="'T'"):
        fresh.admit_prefilled(np.arange(5, dtype=np.int32),
                              (row[0][:, :, :, :16], row[1]), logits)
    with pytest.raises(HandoffMismatch, match="logits"):
        fresh.admit_prefilled(np.arange(5, dtype=np.int32), row,
                              logits[:64])
    # nothing leaked into the engine's admission state
    assert fresh.stats()["requests"]["handoff"] == 0


def test_admit_prefilled_matches_static_extraction(tiny_engine_and_row):
    """The runtime validator and the static auditor read the SAME
    literal: the attribute the engine imports equals the AST-extracted
    declaration byte for byte."""
    from paddle_tpu.analysis import handoff_schema as hs
    from paddle_tpu.serving.disagg import HANDOFF_SCHEMA

    extracted = hs.extract_declaration(*hs.EDGES["disagg_kv"])
    assert extracted == HANDOFF_SCHEMA


def test_pipeline_declares_and_checks_its_edge():
    from paddle_tpu.analysis import handoff_schema as hs
    from paddle_tpu.distributed.pipeline import HANDOFF_SCHEMA

    assert hs.extract_declaration(
        *hs.EDGES["pipeline_stage"]) == HANDOFF_SCHEMA
    assert HANDOFF_SCHEMA["runtime_checked"]


# ---------------------------------------------------------------------------
# CLI exit codes (one subprocess; AST-only target, no tracing)
# ---------------------------------------------------------------------------


def test_cli_handoff_exit_codes(tmp_path):
    """contract_audit --handoff exits 0 against the recorded baseline
    and 1 against a doctored one (drift detection can actually fail)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    tool = os.path.join(REPO, "tools", "contract_audit.py")

    out = subprocess.run(
        [sys.executable, tool, "--handoff", "--json"],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    rep = json.loads(out.stdout)
    assert set(rep["targets"]) == {"handoff"}
    assert rep["totals"]["error"] == 0

    # doctor the baseline: flip the KV dtype the decode engine expects
    base = json.load(open(os.path.join(REPO, "tests",
                                       "handoff_baseline.json")))
    base["edges"]["disagg_kv"]["payload"]["kc"]["dtype"] = "float64"
    doctored = tmp_path / "handoff_drifted.json"
    doctored.write_text(json.dumps(base))
    out = subprocess.run(
        [sys.executable, tool, "--handoff", "--json",
         "--handoff-baseline", str(doctored)],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert out.returncode == 1, out.stdout + out.stderr
    rep = json.loads(out.stdout)
    msgs = [f["message"] for f in rep["targets"]["handoff"]["findings"]
            if f["pass"] == "handoff-schema-drift"]
    assert msgs and "disagg_kv" in msgs[0] and "kc" in msgs[0], msgs


if __name__ == "__main__":
    print(__doc__)
