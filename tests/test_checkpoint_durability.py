"""Durable checkpoints (docs/ROBUSTNESS.md): paddle.save commits atomically
with a sha256 integrity footer, paddle.load rejects corrupt/truncated files
with a clear error, and CheckpointSaver walks back to the newest VALID
checkpoint (evicting corrupt ones) and sweeps crash leftovers. The slow
subprocess test SIGKILLs a save mid-write — in the spirit of
tests/test_auto_checkpoint_kill.py — and proves the destination never tears
and the saver falls back."""
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.framework.io import CheckpointCorruptError
from paddle_tpu.incubate.checkpoint.auto_checkpoint import CheckpointSaver
from paddle_tpu.testing import failpoints as fp
from paddle_tpu.testing.failpoints import FailpointError


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.reset()
    yield
    fp.reset()


def _state():
    return {"w": paddle.to_tensor(np.arange(8, dtype=np.float32)),
            "step": 7}


class TestAtomicSave:
    def test_round_trip_with_footer(self, tmp_path):
        p = str(tmp_path / "m.pdparams")
        paddle.save(_state(), p)
        out = paddle.load(p)
        np.testing.assert_array_equal(np.asarray(out["w"]._data),
                                      np.arange(8, dtype=np.float32))
        assert out["step"] == 7
        # the footer is really there
        from paddle_tpu.framework.io import _FOOTER_MAGIC
        blob = open(p, "rb").read()
        assert blob[-40:-32] == _FOOTER_MAGIC

    def test_failed_save_leaves_destination_untouched(self, tmp_path):
        p = str(tmp_path / "m.pdparams")
        paddle.save({"v": 1}, p)
        before = open(p, "rb").read()
        with fp.scoped("ckpt/write=error:1"):
            with pytest.raises(FailpointError):
                paddle.save({"v": 2}, p)
        assert open(p, "rb").read() == before
        assert paddle.load(p) == {"v": 1}
        # the error path reclaimed its tmp file
        assert [f for f in os.listdir(str(tmp_path)) if ".tmp" in f] == []

    def test_failed_first_save_leaves_no_file(self, tmp_path):
        p = str(tmp_path / "fresh.pdparams")
        with fp.scoped("ckpt/write=error:1"):
            with pytest.raises(FailpointError):
                paddle.save({"v": 1}, p)
        assert not os.path.exists(p)


class TestCorruptionRejection:
    def test_flipped_byte_is_rejected(self, tmp_path):
        p = str(tmp_path / "m.pdparams")
        paddle.save(_state(), p)
        blob = bytearray(open(p, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        open(p, "wb").write(bytes(blob))
        with pytest.raises(CheckpointCorruptError, match="sha256"):
            paddle.load(p)

    def test_truncated_file_is_rejected(self, tmp_path):
        p = str(tmp_path / "m.pdparams")
        paddle.save(_state(), p)
        blob = open(p, "rb").read()
        open(p, "wb").write(blob[: len(blob) // 2])
        with pytest.raises(CheckpointCorruptError):
            paddle.load(p)

    def test_empty_file_is_rejected_clearly(self, tmp_path):
        p = str(tmp_path / "m.pdparams")
        open(p, "wb").close()
        with pytest.raises(CheckpointCorruptError, match="truncated"):
            paddle.load(p)

    def test_verified_file_unpickle_failure_is_not_corruption(self,
                                                              tmp_path):
        """A file whose sha256 footer verifies holds exactly the bytes save
        wrote — an unpickle failure there is environmental (class moved
        between versions, OOM), and must NOT be classified corrupt, or the
        saver's fallback walk would evict a good checkpoint over it."""
        import hashlib

        from paddle_tpu.framework.io import _FOOTER_MAGIC

        # a pickle referencing an attribute that does not exist at load
        # time: GLOBAL os.NoSuchClass123 -> AttributeError inside load
        payload = b"\x80\x04cos\nNoSuchClass123\n."
        p = str(tmp_path / "moved.pdparams")
        with open(p, "wb") as f:
            f.write(payload)
            f.write(_FOOTER_MAGIC + hashlib.sha256(payload).digest())
        with pytest.raises(AttributeError):
            paddle.load(p)
        # ambiguous errors (AttributeError/MemoryError) propagate for
        # footerless files too — only unambiguous pickle-level damage
        # (UnpicklingError/EOFError/ValueError) is classified corrupt
        legacy = str(tmp_path / "legacy_torn.pdparams")
        open(legacy, "wb").write(payload)
        with pytest.raises(AttributeError):
            paddle.load(legacy)
        torn = str(tmp_path / "garbage.pdparams")
        open(torn, "wb").write(b"not a pickle at all")
        with pytest.raises(CheckpointCorruptError):
            paddle.load(torn)
        # saver walk: the verified-but-unloadable checkpoint propagates
        # instead of being evicted
        saver = CheckpointSaver(str(tmp_path / "ckpts"))
        saver.save_checkpoint({"v": 1})
        sp = os.path.join(str(tmp_path / "ckpts"),
                          "__paddle_checkpoint__.0", "state.pdparams")
        with open(sp, "wb") as f:
            f.write(payload)
            f.write(_FOOTER_MAGIC + hashlib.sha256(payload).digest())
        with pytest.raises(AttributeError):
            saver.load_checkpoint()
        assert saver.get_checkpoint_numbers() == [0]   # not evicted

    def test_legacy_footerless_file_still_loads(self, tmp_path):
        import pickle

        p = str(tmp_path / "old.pdparams")
        with open(p, "wb") as f:
            pickle.dump({"legacy": True}, f, protocol=4)
        assert paddle.load(p) == {"legacy": True}

    def test_encrypted_round_trip_keeps_integrity_check(self, tmp_path):
        p = str(tmp_path / "enc.pdparams")
        paddle.save(_state(), p, encryption_key="k" * 32)
        out = paddle.load(p, encryption_key="k" * 32)
        assert out["step"] == 7
        blob = bytearray(open(p, "rb").read())
        blob[10] ^= 0xFF
        open(p, "wb").write(bytes(blob))
        with pytest.raises(CheckpointCorruptError, match="sha256"):
            paddle.load(p, encryption_key="k" * 32)


class TestSaverFallback:
    def test_corrupt_newest_falls_back_and_evicts(self, tmp_path):
        monitor.reset()
        saver = CheckpointSaver(str(tmp_path))
        saver.save_checkpoint({"v": paddle.to_tensor(np.zeros(2))},
                              meta={"epoch": 0})
        saver.save_checkpoint({"v": paddle.to_tensor(np.ones(2))},
                              meta={"epoch": 1})
        newest = os.path.join(str(tmp_path), "__paddle_checkpoint__.1",
                              "state.pdparams")
        blob = open(newest, "rb").read()
        open(newest, "wb").write(blob[:24])   # truncate the newest
        with pytest.warns(UserWarning, match="unreadable"):
            state, meta = saver.load_checkpoint()
        assert meta["epoch"] == 0
        np.testing.assert_array_equal(np.asarray(state["v"]._data),
                                      np.zeros(2))
        assert saver.get_checkpoint_numbers() == [0]   # corrupt one evicted
        c = monitor.counter("checkpoint_recover_total",
                            labelnames=("reason",))
        assert c.labels(reason="corrupt").value == 1

    def test_all_corrupt_returns_none(self, tmp_path):
        saver = CheckpointSaver(str(tmp_path))
        saver.save_checkpoint({"v": 1})
        f = os.path.join(str(tmp_path), "__paddle_checkpoint__.0",
                         "state.pdparams")
        open(f, "wb").write(b"garbage")
        with pytest.warns(UserWarning):
            state, meta = saver.load_checkpoint()
        assert state is None and meta is None

    def test_explicit_number_raises_instead_of_falling_back(self, tmp_path):
        saver = CheckpointSaver(str(tmp_path))
        saver.save_checkpoint({"v": 1})
        saver.save_checkpoint({"v": 2})
        f = os.path.join(str(tmp_path), "__paddle_checkpoint__.1",
                         "state.pdparams")
        open(f, "wb").write(b"garbage")
        with pytest.raises(Exception):
            saver.load_checkpoint(no=1)

    def test_non_corruption_error_does_not_evict(self, tmp_path):
        """A checkpoint that fails to load for a NON-corruption reason (here:
        encrypted state, no key) must propagate the error, not be rmtree'd —
        eviction is reserved for bad bytes."""
        saver = CheckpointSaver(str(tmp_path))
        saver.save_checkpoint({"v": 1})
        enc = os.path.join(str(tmp_path), "__paddle_checkpoint__.0",
                           "state.pdparams")
        paddle.save({"v": 1}, enc, encryption_key="k" * 32)
        with pytest.raises(ValueError, match="encrypted"):
            saver.load_checkpoint()
        assert saver.get_checkpoint_numbers() == [0]   # still on disk

    def test_startup_sweeps_orphaned_tmp_dirs(self, tmp_path):
        monitor.reset()
        orphan = os.path.join(str(tmp_path), "__paddle_checkpoint__.4.tmp")
        os.makedirs(orphan)
        open(os.path.join(orphan, "state.pdparams.tmp.123"), "wb").write(b"x")
        # age the marker-less dir past the mid-creation grace period
        old = time.time() - 3600
        os.utime(orphan, (old, old))
        saver = CheckpointSaver(str(tmp_path))
        assert not os.path.exists(orphan)
        assert saver.get_checkpoint_numbers() == []
        c = monitor.counter("checkpoint_recover_total",
                            labelnames=("reason",))
        assert c.labels(reason="tmp_swept").value == 1

    def test_sweep_spares_live_concurrent_savers_tmp(self, tmp_path):
        """A tmp dir whose owner.pid names a live OTHER process is a
        concurrent saver mid-commit in a shared directory — sweeping it
        would turn its atomic rename into ENOENT."""
        live = os.path.join(str(tmp_path), "__paddle_checkpoint__.7.tmp")
        os.makedirs(live)
        with open(os.path.join(live, "owner.pid"), "w") as f:
            f.write(str(os.getppid()))   # alive, and not us
        CheckpointSaver(str(tmp_path))
        assert os.path.isdir(live)
        # once the owner is gone (dead pid), the next start reclaims it
        with open(os.path.join(live, "owner.pid"), "w") as f:
            f.write("999999999")
        CheckpointSaver(str(tmp_path))
        assert not os.path.exists(live)

    def test_failed_save_checkpoint_leftovers_are_swept_next_start(
            self, tmp_path):
        saver = CheckpointSaver(str(tmp_path))
        with fp.scoped("ckpt/commit=error:1"):
            with pytest.raises(FailpointError):
                saver.save_checkpoint({"v": 1})
        # the aborted attempt left its tmp dir — a "crash" leftover
        assert any(n.endswith(".tmp") for n in os.listdir(str(tmp_path)))
        CheckpointSaver(str(tmp_path))   # restart sweeps
        assert not any(n.endswith(".tmp")
                       for n in os.listdir(str(tmp_path)))


_KILL_WORKER = r'''
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.incubate.checkpoint.auto_checkpoint import CheckpointSaver
from paddle_tpu.testing import failpoints

save_dir = sys.argv[1]
saver = CheckpointSaver(save_dir)
saver.save_checkpoint({"v": paddle.to_tensor(np.zeros(4))},
                      meta={"epoch": 0})
print("SAVED_0", flush=True)
# the second save dies by SIGKILL after the payload bytes are written but
# BEFORE the integrity footer and the atomic commit
failpoints.arm("ckpt/write", "kill")
saver.save_checkpoint({"v": paddle.to_tensor(np.ones(4))},
                      meta={"epoch": 1})
print("UNREACHABLE", flush=True)
'''


@pytest.mark.slow
def test_sigkill_mid_save_falls_back_to_previous_valid(tmp_path):
    """Crash-mid-save e2e: the killed process leaves only a .tmp dir (the
    destination is never torn — atomic commit), a restarted CheckpointSaver
    sweeps it and resumes from the previous valid checkpoint."""
    script = tmp_path / "worker.py"
    script.write_text(_KILL_WORKER)
    save_dir = tmp_path / "ckpts"
    repo_root = os.path.dirname(os.path.dirname(paddle.__file__))
    env = dict(os.environ, PYTHONPATH=repo_root + (
        os.pathsep + os.environ["PYTHONPATH"]
        if os.environ.get("PYTHONPATH") else ""))
    res = subprocess.run([sys.executable, str(script), str(save_dir)],
                         capture_output=True, text=True, timeout=300,
                         env=env)
    assert res.returncode == -signal.SIGKILL, (res.returncode, res.stderr)
    assert "SAVED_0" in res.stdout and "UNREACHABLE" not in res.stdout
    names = os.listdir(str(save_dir))
    assert "__paddle_checkpoint__.0" in names
    # checkpoint 1 never committed; its partial write sits in a .tmp dir
    assert "__paddle_checkpoint__.1" not in names
    assert any(n.endswith(".tmp") for n in names)

    saver = CheckpointSaver(str(save_dir))   # "restart": sweeps the orphan
    assert not any(n.endswith(".tmp") for n in os.listdir(str(save_dir)))
    state, meta = saver.load_checkpoint()
    assert meta["epoch"] == 0
    np.testing.assert_array_equal(np.asarray(state["v"]._data), np.zeros(4))
