"""Tier-1 federated gate: the federated tier costs a plain SPMD
deployment NOTHING when no federated API is touched.

Pins (ISSUE 8 satellite, same pattern as test_router_gate.py):
 - a plain SpmdTrainer train step never imports paddle_tpu.federated
   (subprocess check — the package is NOT on paddle_tpu/__init__'s
   import surface);
 - a plain trainer run leaves ZERO federated_* metric series and ZERO
   federated-subsystem spans;
 - the federated/round failpoint site and the nonreduced-client-output
   lint rule are REGISTERED (arming/suppressing a typo'd name must fail
   fast);
 - tools/metrics_dump.py --federated exits 1 when the federated metric
   families are missing (the CI contract in executable form).
"""
import importlib.util
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, nn, trace
from paddle_tpu.testing import failpoints

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _plain_train_steps(steps=3):
    import jax

    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 4))
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=net.parameters())
    mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
    trainer = SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randn(4, 4).astype(np.float32))
    for _ in range(steps):
        out = trainer.train_step(x, y)
    return float(np.asarray(out._data))


class TestZeroOverheadPlainTrainer:
    def test_plain_trainer_never_imports_federated(self):
        """The structural form of 'zero overhead': no federated API
        touched -> the package (and its metric registrations) is never
        even imported."""
        code = (
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "import numpy as np\n"
            "import paddle_tpu as paddle\n"
            "from paddle_tpu import nn\n"
            "from paddle_tpu.distributed.mesh import build_mesh\n"
            "from paddle_tpu.distributed.spmd import SpmdTrainer\n"
            "paddle.seed(0)\n"
            "net = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 4))\n"
            "opt = paddle.optimizer.AdamW(learning_rate=1e-3,\n"
            "    parameters=net.parameters())\n"
            "mesh = build_mesh((1,), ('dp',), devices=jax.devices()[:1])\n"
            "tr = SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)\n"
            "x = paddle.to_tensor(np.ones((4, 8), np.float32))\n"
            "y = paddle.to_tensor(np.ones((4, 4), np.float32))\n"
            "tr.train_step(x, y)\n"
            "import sys\n"
            "bad = [k for k in sys.modules\n"
            "       if k.startswith('paddle_tpu.federated')]\n"
            "assert not bad, f'federated tier imported eagerly: {bad}'\n"
            "print('LAZY_OK')\n")
        out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                             capture_output=True, text=True, timeout=560)
        assert out.returncode == 0, out.stderr[-2000:]
        assert "LAZY_OK" in out.stdout

    def test_plain_trainer_zero_federated_metrics_and_spans(self):
        monitor.reset()
        trace.clear()
        trace.enable()
        try:
            _plain_train_steps()
        finally:
            trace.disable()
        flat = monitor.flatten(monitor.snapshot())
        # zeroed () series can survive monitor.reset() when an earlier
        # in-process test ran the federated tier — zero overhead means
        # nothing was RECORDED by the plain trainer run
        leaked = {k: v for k, v in flat.items()
                  if k.startswith("federated_")
                  and (v["count"] if isinstance(v, dict) else v)}
        assert not leaked, leaked
        # no federated_sum collective rode along either
        assert not {k for k in flat
                    if "op=federated" in k and flat[k]}, flat
        assert not [s for s in trace.spans()
                    if s.subsystem == "federated"
                    or s.name.startswith("federated")]
        # the trainer's own span family is intact
        assert "train_step" in {s.name for s in trace.spans()}


class TestRegistrations:
    def test_failpoint_site_registered(self):
        assert "federated/round" in failpoints.SITES
        failpoints.arm("federated/round", "error:1")
        try:
            assert failpoints.armed() == {"federated/round": "error:1"}
        finally:
            failpoints.reset()

    def test_lint_rule_registered(self):
        from paddle_tpu.analysis.source_lint import RULES

        assert RULES.get("nonreduced-client-output") == "error"

    def test_clients_axis_documented_in_mesh(self):
        from paddle_tpu.distributed import mesh

        assert "clients" in (mesh.__doc__ or "")
        assert callable(mesh.client_mesh)


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules.pop(name, None)
    spec.loader.exec_module(mod)
    return mod


class TestFederatedToolGate:
    def test_metrics_dump_federated_missing_metrics_exits_1(
            self, capsys, monkeypatch):
        md = _load_tool("metrics_dump")
        monkeypatch.setattr(md, "run_federated_loop", lambda **kw: None)
        rc = md.main(["--federated", "--json"])
        assert rc == 1
        import json

        report = json.loads(capsys.readouterr().out)
        missing = {f["message"].split("'")[1]
                   for f in report["targets"]["federated"]["findings"]
                   if f["pass"] == "metrics-present"}
        # federated_round_total is labeled, so monitor.reset() drops its
        # series entirely; the histogram family may survive as a zeroed
        # () series when an earlier in-process test touched it
        assert "federated_round_total" in missing

    @pytest.mark.slow
    def test_metrics_dump_federated_green_subprocess(self):
        """Subprocess CI form: the --federated tool runs clean at HEAD
        (the green path; tier-1 covers the exit-1 contract above)."""
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "metrics_dump.py"),
             "--federated", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=560)
        assert out.returncode == 0, out.stderr[-2000:]
