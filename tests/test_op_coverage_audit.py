"""Mechanical audit of tools/op_coverage.py's mapping claims (VERDICT r2
weak #6: the ALIAS table and INFRA classifier were self-grading). This test
(a) resolves EVERY alias target against the tool's own module list, (b) calls
a ~20-op sample of claimed equivalents end-to-end, and (c) checks the
realizations the INFRA prose names (collective API, tensor arrays,
quantization, PS) actually exist."""
import importlib.util
import os

import numpy as np
import pytest

import paddle_tpu as paddle

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def oc():
    spec = importlib.util.spec_from_file_location(
        "op_coverage", os.path.join(REPO, "tools", "op_coverage.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_tool_runs_from_any_cwd(oc):
    # the module imported without PYTHONPATH tricks (sys.path self-fix)
    assert oc.names and oc.ALIAS


def test_every_alias_target_resolves(oc):
    unresolved = [n for n in oc.ALIAS if not oc.have(n)]
    assert unresolved == [], f"ALIAS names APIs that do not exist: {unresolved}"


def test_core_unmatched_stays_documented(oc):
    # r4: the core-unmatched tail is CLOSED — the remaining 6 were wired
    # (lookup_table_dequant -> SparseTable.quantize) or reclassified with
    # HLO-fusion / autodiff tests (tests/test_xla_fusion_na.py). Any
    # regression (an API rename dropping coverage) must fail loudly here.
    assert oc.core_missing == [], oc.core_missing


def test_disposition_table_is_exhaustive_and_regex_free(oc):
    """VERDICT r4 #2: every unmatched op has an EXPLICIT disposition —
    no prefix regex, no stale rows, every implemented-as target live."""
    assert oc.undispositioned == [], oc.undispositioned
    assert oc.stale == [], oc.stale
    assert oc.bad_targets == [], oc.bad_targets
    # the classifying regexes are gone for good
    assert not hasattr(oc, "INFRA")
    assert not hasattr(oc, "GRAD_REALIZED")
    # every entry is one of the three honest kinds
    for op, (kind, tgt, note) in oc.DISPOSITION.items():
        assert kind in ("implemented-as", "N/A", "descoped"), (op, kind)
        if kind == "implemented-as":
            assert tgt, op
        else:
            assert note, op  # N/A and descoped must state their reason


def test_r4_flagged_compute_ops_are_now_implemented(oc):
    """The five ops the r4 audit found swept by the old INFRA regex are
    real implementations now (tests/test_rec_ops.py), so they must MATCH
    (not appear in the unmatched list at all)."""
    for op in ("sequence_topk_avg_pooling", "batch_fc", "rank_attention",
               "filter_by_instag", "pyramid_hash"):
        assert oc.have(op), op
        assert op not in oc.missing, op


def test_fused_xla_claims_are_test_backed(oc):
    # the FUSED_XLA classification is only honest while the asserting test
    # file exists and names each op
    path = os.path.join(REPO, "tests", "test_xla_fusion_na.py")
    src = open(path).read()
    for op in oc.FUSED_XLA:
        assert op in src, f"{op} claim has no backing test"


def _rand(*s):
    return paddle.to_tensor(np.random.RandomState(0).rand(*s).astype("float32"))


# ~20 sampled ALIAS rows: reference op name -> zero-arg callable driving the
# claimed equivalent through the public API
SAMPLE_CALLS = {
    "elementwise_add": lambda: paddle.add(_rand(3, 4), _rand(3, 4)),
    "reduce_sum": lambda: paddle.sum(_rand(3, 4)),
    "matmul_v2": lambda: paddle.matmul(_rand(3, 4), _rand(4, 5)),
    "lookup_table_v2": lambda: paddle.nn.functional.embedding(
        paddle.to_tensor(np.array([1, 2], np.int64)), _rand(8, 4)),
    "top_k_v2": lambda: paddle.topk(_rand(3, 6), k=2),
    "one_hot_v2": lambda: paddle.nn.functional.one_hot(
        paddle.to_tensor(np.array([1, 2], np.int64)), 4),
    "fill_constant": lambda: paddle.full([2, 2], 3.0),
    "expand_v2": lambda: paddle.expand(_rand(1, 4), [3, 4]),
    "reshape2": lambda: paddle.reshape(_rand(2, 6), [3, 4]),
    "softmax_with_cross_entropy":
        lambda: paddle.nn.functional.softmax_with_cross_entropy(
            _rand(4, 5), paddle.to_tensor(np.array([[1], [2], [3], [0]],
                                                   np.int64))),
    "huber_loss": lambda: paddle.nn.functional.smooth_l1_loss(
        _rand(3, 2), _rand(3, 2)),
    "batch_norm": lambda: paddle.nn.functional.batch_norm(
        _rand(2, 3, 4, 4), _rand(3), _rand(3), _rand(3), _rand(3)),
    "pool2d": lambda: paddle.nn.functional.max_pool2d(_rand(1, 2, 6, 6), 2),
    "bilinear_interp_v2": lambda: paddle.nn.functional.interpolate(
        _rand(1, 2, 4, 4), size=[8, 8], mode="bilinear"),
    "grid_sampler": lambda: paddle.nn.functional.grid_sample(
        _rand(1, 2, 4, 4),
        paddle.to_tensor(
            np.zeros((1, 3, 3, 2), np.float32))),
    "tril_triu": lambda: paddle.tril(_rand(4, 4)),
    "multiclass_nms3": lambda: paddle.vision.ops.multiclass_nms(
        paddle.to_tensor(np.array([[[0, 0, 4, 4], [1, 1, 5, 5]]],
                                  np.float32)),
        paddle.to_tensor(np.array([[[0.9, 0.8], [0.2, 0.7]]], np.float32))),
    "roi_align": lambda: paddle.vision.ops.roi_align(
        _rand(1, 2, 8, 8),
        paddle.to_tensor(np.array([[0, 0, 4, 4]], np.float32)),
        boxes_num=paddle.to_tensor(np.array([1], np.int32)),
        output_size=2),
    "warpctc": lambda: paddle.nn.functional.ctc_loss(
        _rand(5, 2, 6), paddle.to_tensor(
            np.array([[1, 2], [2, 3]], np.int32)),
        paddle.to_tensor(np.array([5, 5], np.int64)),
        paddle.to_tensor(np.array([2, 2], np.int64))),
    "sgd": lambda: paddle.optimizer.SGD(
        learning_rate=0.1,
        parameters=paddle.nn.Linear(2, 2).parameters()),
    "clip_by_norm": lambda: paddle.clip(_rand(3), min=0.1, max=0.5),
    "gather_nd": lambda: paddle.gather_nd(
        _rand(3, 4), paddle.to_tensor(np.array([[0, 1]], np.int64))),
}


def test_sampled_alias_equivalents_execute(oc):
    for ref_op, call in SAMPLE_CALLS.items():
        assert ref_op in oc.ALIAS or oc.have(ref_op), ref_op
        out = call()
        leaves = out if isinstance(out, (tuple, list)) else [out]
        for leaf in leaves:
            if hasattr(leaf, "_data"):
                assert np.isfinite(
                    np.asarray(leaf._data).astype(np.float64)).all(), ref_op


def test_infra_realizations_exist():
    """The INFRA prose claims c_* -> collective API, lod_*/array ->
    tensor/array.py + lax, fake_quantize_* -> quantization/, push_/pull_ ->
    distributed/ps: check each named surface exists and minimally works."""
    import paddle_tpu.distributed as dist

    for fn in ("all_reduce", "all_gather", "broadcast", "reduce_scatter",
               "alltoall", "send", "recv", "barrier"):
        assert hasattr(dist, fn), fn

    from paddle_tpu.tensor.array import array_length, array_read, array_write

    arr = []
    array_write(_rand(2), 0, arr)
    assert array_length(arr) == 1
    got = array_read(arr, 0)
    assert tuple(got.shape) == (2,)

    import paddle_tpu.quantization as q

    for fq in ("fake_quantize_abs_max", "fake_quantize_moving_average_abs_max",
               "ImperativeQuantAware", "PostTrainingQuantization"):
        assert hasattr(q, fq), fq

    import paddle_tpu.distributed.ps as ps  # PS wire ops' realization

    assert ps is not None


def test_multiclass_nms_all_background_degenerate():
    """C==1 with background_label=0 must yield an empty (-1-padded) result,
    not crash (degenerate-shape sweep)."""
    out, num = paddle.vision.ops.multiclass_nms(
        paddle.to_tensor(np.array([[[0, 0, 4, 4]]], np.float32)),
        paddle.to_tensor(np.array([[[0.9]]], np.float32)))
    assert int(np.asarray(num._data)[0]) == 0
    assert (np.asarray(out._data) == -1).all()
