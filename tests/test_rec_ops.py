"""The five reference compute ops the r4 audit flagged as swept by the INFRA
regex without individual adjudication (VERDICT r4 Weak #3): each is now a real
implementation, checked here against a direct numpy mirror of the C++ kernel.

- sequence_topk_avg_pooling (sequence_topk_avg_pooling_op.h:131-170)
- batch_fc (batch_fc_op.h / .cu — per-slot FC)
- rank_attention (rank_attention.cu.h:32-95 expand+gemm)
- filter_by_instag (filter_by_instag_op.h — tag-intersection row filter)
- search_pyramid_hash (pyramid_hash_op.cc:226-247 hashed n-gram embeddings)
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F


class TestSequenceTopkAvgPooling:
    def _np_ref(self, x, rl, cl, topks, C):
        # direct mirror of the C++ loop: per (sample, channel, valid row),
        # top-k over the valid columns; running sum carries past padding;
        # divisor is always topks[k]
        B, _, R, Cm = x.shape
        K = len(topks)
        max_k = max(topks)
        out = np.zeros((B, R, C * K), np.float32)
        for b in range(B):
            for r in range(rl[b]):
                for j in range(C):
                    row = x[b, j, r, :cl[b]]
                    top = np.sort(row)[::-1][:max_k]
                    sums = np.zeros(max_k)
                    s = 0.0
                    for k in range(max_k):
                        if k < len(top):
                            s += top[k]
                        sums[k] = s
                    for ki, k in enumerate(topks):
                        out[b, r, j * K + ki] = sums[k - 1] / k
        return out

    def test_matches_kernel_mirror(self):
        rng = np.random.default_rng(0)
        B, C, R, Cm = 3, 2, 4, 6
        x = rng.standard_normal((B, C, R, Cm)).astype(np.float32)
        rl = np.array([4, 2, 3], np.int32)
        cl = np.array([6, 3, 1], np.int32)   # incl. cols < max(topks)
        topks = [1, 3, 5]
        out = F.sequence_topk_avg_pooling(
            paddle.to_tensor(x), paddle.to_tensor(rl), paddle.to_tensor(cl),
            topks=topks, channel_num=C)
        np.testing.assert_allclose(out.numpy(),
                                   self._np_ref(x, rl, cl, topks, C),
                                   rtol=1e-5, atol=1e-6)

    def test_grad_flows_to_topk_positions_only(self):
        x = paddle.to_tensor(
            np.array([[[[3.0, 1.0, 2.0, 5.0]]]], np.float32),
            stop_gradient=False)
        out = F.sequence_topk_avg_pooling(
            x, paddle.to_tensor([1]), paddle.to_tensor([4]),
            topks=[2], channel_num=1)
        out.sum().backward()
        # top-2 of [3,1,2,5] are positions 3 and 0; each gets d(mean)=1/2
        np.testing.assert_allclose(x.grad.numpy(),
                                   [[[[0.5, 0.0, 0.0, 0.5]]]], atol=1e-6)

    def test_rejects_bad_topks(self):
        with pytest.raises(ValueError):
            F.sequence_topk_avg_pooling(
                paddle.to_tensor(np.zeros((1, 1, 1, 1), np.float32)),
                paddle.to_tensor([1]), paddle.to_tensor([1]),
                topks=[0], channel_num=1)


class TestBatchFC:
    def test_matches_per_slot_gemm(self):
        rng = np.random.default_rng(1)
        S, B, I, O = 4, 5, 3, 2
        x = rng.standard_normal((S, B, I)).astype(np.float32)
        w = rng.standard_normal((S, I, O)).astype(np.float32)
        b = rng.standard_normal((S, O)).astype(np.float32)
        out = F.batch_fc(paddle.to_tensor(x), paddle.to_tensor(w),
                         paddle.to_tensor(b), act="relu")
        ref = np.maximum(np.einsum("sbi,sio->sbo", x, w) + b[:, None, :], 0)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-5)

    def test_grad_through_weights(self):
        x = paddle.to_tensor(np.ones((2, 3, 4), np.float32))
        w = paddle.to_tensor(np.ones((2, 4, 5), np.float32),
                             stop_gradient=False)
        F.batch_fc(x, w).sum().backward()
        np.testing.assert_allclose(w.grad.numpy(),
                                   np.full((2, 4, 5), 3.0))


class TestRankAttention:
    def _np_ref(self, x, ro, param, max_rank):
        # mirror of expand_input_by_rank_kernel + expand_rank_attention_param
        # + per-instance GEMM (rank_attention.cu.h)
        B, D = x.shape
        O = param.shape[-1]
        P = param.reshape(max_rank, max_rank, D, O)
        out = np.zeros((B, O), np.float32)
        for i in range(B):
            lower = ro[i, 0] - 1
            for k in range(max_rank):
                faster = ro[i, 2 * k + 1] - 1
                idx = ro[i, 2 * k + 2]
                if lower < 0 or faster < 0:
                    continue
                out[i] += x[idx] @ P[lower, faster]
        return out

    def test_matches_kernel_mirror(self):
        rng = np.random.default_rng(2)
        B, D, O, K = 5, 3, 4, 3
        x = rng.standard_normal((B, D)).astype(np.float32)
        param = rng.standard_normal((K * K * D, O)).astype(np.float32)
        ro = np.zeros((B, 2 * K + 1), np.int32)
        for i in range(B):
            ro[i, 0] = rng.integers(0, K + 1)       # own rank, 0 = invalid
            for k in range(K):
                ro[i, 2 * k + 1] = rng.integers(0, K + 1)
                ro[i, 2 * k + 2] = rng.integers(0, B)
        out = F.rank_attention(paddle.to_tensor(x), paddle.to_tensor(ro),
                               paddle.to_tensor(param), max_rank=K)
        np.testing.assert_allclose(out.numpy(),
                                   self._np_ref(x, ro, param, K),
                                   rtol=1e-5, atol=1e-5)

    def test_param_grad(self):
        B, D, O, K = 3, 2, 2, 2
        x = paddle.to_tensor(np.ones((B, D), np.float32))
        ro = np.array([[1, 1, 0, 2, 1],
                       [2, 1, 2, 0, 0],
                       [0, 1, 0, 1, 1]], np.int32)  # row 2: lower invalid
        p = paddle.to_tensor(np.ones((K * K * D, O), np.float32),
                             stop_gradient=False)
        F.rank_attention(x, paddle.to_tensor(ro), p, max_rank=K).sum() \
            .backward()
        g = p.grad.numpy().reshape(K, K, D, O)
        assert g[0, 0].sum() > 0           # used by row 0 slot 0
        assert np.all(g[1, 1] == 0)        # (lower=1, faster=1) never valid


class TestFilterByInstag:
    def test_filters_rows_by_tag_intersection(self):
        ins = np.arange(8, dtype=np.float32).reshape(4, 2) + 1
        tags = np.array([[0, 1], [1, 3], [0, 3], [2, 6]], np.int64)
        out, lw = F.filter_by_instag(paddle.to_tensor(ins),
                                     paddle.to_tensor(tags),
                                     paddle.to_tensor(np.array([1], np.int64)))
        # the docstring example: ins 0 and 1 pass, 2 and 3 are filtered
        np.testing.assert_allclose(lw.numpy().ravel(), [1, 1, 0, 0])
        np.testing.assert_allclose(out.numpy()[:2], ins[:2])
        np.testing.assert_allclose(out.numpy()[2:], 0)

    def test_padding_tag_never_matches(self):
        ins = np.ones((2, 3), np.float32)
        tags = np.array([[5, -1], [-1, -1]], np.int64)  # -1 = padding
        out, lw = F.filter_by_instag(
            paddle.to_tensor(ins), paddle.to_tensor(tags),
            paddle.to_tensor(np.array([-1, 5], np.int64)))
        np.testing.assert_allclose(lw.numpy().ravel(), [1, 0])


class TestSearchPyramidHash:
    def _run(self, **kw):
        B, T = 2, 5
        ids = np.array([[3, 1, 4, 1, 5], [9, 2, 6, 0, 0]], np.int32)
        ln = np.array([5, 3], np.int32)
        space_len, rand_len, num_emb = 64, 2, 6
        w = np.random.default_rng(3).standard_normal(
            space_len + rand_len).astype(np.float32)
        out, nlen = F.search_pyramid_hash(
            paddle.to_tensor(ids), paddle.to_tensor(ln), paddle.to_tensor(w),
            num_emb=num_emb, space_len=space_len, pyramid_layer=3,
            rand_len=rand_len, **kw)
        return out.numpy(), nlen.numpy()

    def test_shapes_counts_and_masking(self):
        out, nlen = self._run()
        # ngram sizes 2 and 3: (T-1) + (T-2) = 4 + 3 = 7 padded rows
        assert out.shape == (2, 7, 6)
        # sample 0 (len 5): 4 bigrams + 3 trigrams; sample 1 (len 3): 2 + 1
        np.testing.assert_array_equal(nlen, [7, 3])
        # sample 1's invalid ngram rows are zeroed: bigram rows 2,3 and
        # trigram rows 5,6 (row layout: size-2 block then size-3 block)
        assert np.all(out[1, [2, 3, 5, 6]] == 0)
        assert np.all(np.any(out[1, [0, 1, 4]] != 0, axis=-1))

    def test_deterministic_and_length_sensitive(self):
        a, _ = self._run()
        b, _ = self._run()
        np.testing.assert_array_equal(a, b)   # hash is deterministic

    def test_eval_scaling_and_train_dropout(self):
        full, _ = self._run(is_training=False)
        scaled, _ = self._run(is_training=False, drop_out_percent=0.5)
        np.testing.assert_allclose(scaled, full * 0.5, rtol=1e-6)
        dropped, nlen = self._run(is_training=True, drop_out_percent=0.9)
        # heavy dropout must zero some valid rows but counts track keeps
        kept_rows = np.any(dropped[0] != 0, axis=-1).sum()
        assert kept_rows == nlen[0] < 7

    def test_dropout_resamples_per_step(self):
        # the drop mask must vary with the training step — a frozen mask
        # would permanently exclude the same ngrams from training
        outs = [self._run(is_training=True, drop_out_percent=0.5,
                          step=s)[0] for s in range(6)]
        masks = [np.any(o[0] != 0, axis=-1) for o in outs]
        assert any(not np.array_equal(masks[0], m) for m in masks[1:])
        # every valid ngram is trainable across steps (none always dropped)
        assert np.logical_or.reduce(masks).all()

    def test_rejects_bad_rand_len(self):
        with pytest.raises(ValueError, match="multiple"):
            F.search_pyramid_hash(
                paddle.to_tensor(np.zeros((1, 3), np.int32)),
                paddle.to_tensor([3]),
                paddle.to_tensor(np.zeros(66, np.float32)),
                num_emb=5, space_len=64, pyramid_layer=3, rand_len=2)


class TestContribLayerWrappers:
    """fluid.contrib.layers-style signatures (parameters created from
    attrs inside the call) delegating to the functional forms."""

    def test_batch_fc_creates_params_and_runs(self):
        from paddle_tpu.incubate import contrib_layers as cl

        x = paddle.to_tensor(np.ones((3, 4, 5), np.float32))
        out = cl.batch_fc(x, param_size=[3, 5, 6], bias_size=[3, 6],
                          act="relu")
        assert tuple(out.shape) == (3, 4, 6)
        with pytest.raises(ValueError, match="bias_size"):
            cl.batch_fc(x, param_size=[3, 5, 6], bias_size=[3, 7])

    def test_rank_attention_shape_assert(self):
        from paddle_tpu.incubate import contrib_layers as cl

        x = paddle.to_tensor(np.ones((4, 2), np.float32))
        ro = paddle.to_tensor(np.zeros((4, 7), np.int32))
        out = cl.rank_attention(x, ro, rank_param_shape=[18, 3],
                                max_rank=3)
        assert tuple(out.shape) == (4, 3)
        with pytest.raises(ValueError, match="rank_param_shape"):
            cl.rank_attention(x, ro, rank_param_shape=[17, 3], max_rank=3)

    def test_pyramid_hash_creates_table(self):
        from paddle_tpu.incubate import contrib_layers as cl

        ids = paddle.to_tensor(np.array([[3, 1, 4]], np.int32))
        out, nlen = cl.search_pyramid_hash(
            ids, paddle.to_tensor([3]), num_emb=4, space_len=32,
            pyramid_layer=3, rand_len=2)
        assert tuple(out.shape)[2] == 4 and int(nlen.numpy()[0]) == 3
