"""Front-door Router (serving/router.py): fan-out over named engines,
session/prefix affinity, deadline-aware placement, drain-aware failover,
engine-death failover, and trace threading. Every completion holds the
serving tier's exact-parity bar vs solo generate()."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, trace
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.serving.router import NoLiveEngineError, Router
from paddle_tpu.testing import failpoints as fp


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def rng():
    return np.random.RandomState(0)


def _ref(m, prompt, n):
    out = m.generate(paddle.to_tensor(prompt[None]), max_new_tokens=n,
                     temperature=0.0)
    return np.asarray(out._data)[0, len(prompt):]


def _two_engine_router(model, **eng_kw):
    return Router({"a": ServingEngine(model, max_batch=2, **eng_kw),
                   "b": ServingEngine(model, max_batch=2, **eng_kw)})


class TestFanout:
    def test_two_engine_fanout_with_exact_parity(self, model, rng):
        router = _two_engine_router(model)
        prompts = [rng.randint(0, 128, (n,)).astype(np.int32)
                   for n in (4, 7, 9, 5, 12, 6)]
        rids = [router.submit(p, max_new_tokens=6, session_id=i)
                for i, p in enumerate(prompts)]
        res = router.run_until_complete()
        assert len(res) == 6
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(res[rid].tokens,
                                          _ref(model, p, 6))
            assert res[rid].finish_reason == "length"
        st = router.stats()["router"]
        # distinct sessions hash across BOTH engines (fan-out, not a
        # degenerate single-engine pile-up)
        assert set(st["requests"]) == {"a", "b"}
        assert sum(st["requests"].values()) == 6

    def test_router_requests_metric(self, model, rng):
        monitor.reset()
        router = _two_engine_router(model)
        for i in range(4):
            router.submit(rng.randint(0, 128, (5,)).astype(np.int32),
                          max_new_tokens=2, session_id=i)
        router.run_until_complete()
        flat = monitor.flatten(monitor.snapshot())
        total = sum(v for k, v in flat.items()
                    if k.startswith("router_requests_total"))
        assert total == 4

    def test_engine_level_shed_is_collected(self, model, rng):
        """A request finished OUTSIDE step() (priority-shed at submit
        time by the engine's bounded queue) must still surface in the
        router's results, not strand in the mapping."""
        router = Router({"only": ServingEngine(model, max_batch=1,
                                               max_queue=1)})
        p = rng.randint(0, 128, (5,)).astype(np.int32)
        r_low = router.submit(p, max_new_tokens=2, priority=0)
        r_high = router.submit(p, max_new_tokens=2, priority=5)
        done = router.step()
        assert r_low in done
        assert done[r_low].finish_reason == "shed"
        res = router.run_until_complete()
        assert res[r_high].finish_reason == "length"
        assert router.stats()["router"]["outstanding"] == 0

    def test_model_labels_route_per_model(self, model, rng):
        router = Router({"a": ServingEngine(model, max_batch=2),
                         "b": ServingEngine(model, max_batch=2)},
                        models={"a": "gpt-a", "b": "gpt-b"})
        p = rng.randint(0, 128, (5,)).astype(np.int32)
        rid = router.submit(p, max_new_tokens=2, model="gpt-b")
        assert router._reqs[rid].engine == "b"
        with pytest.raises(NoLiveEngineError):
            router.submit(p, max_new_tokens=2, model="gpt-z")


class TestAffinity:
    def test_session_affinity_pins_one_engine(self, model, rng):
        router = _two_engine_router(model)
        rids = [router.submit(rng.randint(0, 128, (5,)).astype(np.int32),
                              max_new_tokens=2, session_id="chat-1")
                for _ in range(4)]
        router.run_until_complete()
        engines = {router._reqs[r].engine for r in rids}
        assert len(engines) == 1
        aff = router.stats()["router"]["affinity"]
        assert aff == {"hit": 3, "miss": 1, "hit_rate": 0.75}

    def test_prefix_affinity_hit_rate_matches_single_engine(self, model,
                                                            rng):
        prefix = rng.randint(0, 128, (16,)).astype(np.int32)
        suffixes = [rng.randint(0, 128, (4,)).astype(np.int32)
                    for _ in range(4)]

        # single-engine baseline: register once, every submit hits.
        # prefill_chunk=8 keeps the suffix chunk schedule inside the
        # small test cache (prefix_len + chunk <= max_seq_len)
        solo = ServingEngine(model, max_batch=2, prefill_chunk=8)
        pid = solo.register_prefix(prefix)
        srids = [solo.submit(s, max_new_tokens=4, prefix_id=pid)
                 for s in suffixes]
        sres = solo.run_until_complete()
        base = solo.stats()["prefix_cache"]
        assert base["hit_rate"] == 1.0

        # routed: affinity sends every same-prefix request to ONE engine,
        # which registers the prefix lazily ONCE — aggregate hit rate must
        # be >= the single-engine baseline (here: equal)
        router = _two_engine_router(model, prefill_chunk=8)
        rpid = router.register_prefix(prefix)
        rrids = [router.submit(s, max_new_tokens=4, prefix_id=rpid)
                 for s in suffixes]
        rres = router.run_until_complete()
        assert len(router._prefix_sites[rpid]) == 1   # one warm engine
        hits = misses = 0
        for st in router.stats()["engines"].values():
            hits += st["prefix_cache"]["hit"]
            misses += st["prefix_cache"]["miss"]
        assert hits / (hits + misses) >= base["hit_rate"]
        # identical tokens either way (prefix reuse is exact)
        for sr, rr in zip(srids, rrids):
            np.testing.assert_array_equal(sres[sr].tokens,
                                          rres[rr].tokens)


class TestDeadlinePlacement:
    def test_deadline_routes_to_least_loaded(self, model, rng):
        eng_busy = ServingEngine(model, max_batch=1)
        eng_idle = ServingEngine(model, max_batch=1)
        router = Router({"busy": eng_busy, "idle": eng_idle})
        # pile queued work onto "busy" directly (bypassing placement)
        for _ in range(3):
            eng_busy.submit(rng.randint(0, 128, (5,)).astype(np.int32),
                            max_new_tokens=4)
        # a deadline request must ignore its affinity hash and take the
        # engine most likely to start it in time
        p = rng.randint(0, 128, (5,)).astype(np.int32)
        rid = router.submit(p, max_new_tokens=4, session_id="s",
                            deadline_ms=60_000)
        assert router._reqs[rid].engine == "idle"
        res = router.run_until_complete()
        np.testing.assert_array_equal(res[rid].tokens, _ref(model, p, 4))

    def test_queue_full_retries_on_other_candidates(self, model, rng):
        router = _two_engine_router(model, max_queue=1)
        # fill whichever engine session "s" hashes to
        r0 = router.submit(rng.randint(0, 128, (5,)).astype(np.int32),
                           max_new_tokens=2, session_id="s")
        first = router._reqs[r0].engine
        r1 = router.submit(rng.randint(0, 128, (5,)).astype(np.int32),
                           max_new_tokens=2, session_id="s")
        # same affinity target, full queue -> placed on the OTHER engine
        # instead of propagating QueueFullError
        assert router._reqs[r1].engine != first
        router.run_until_complete()


class TestFailover:
    def test_drain_reroutes_queued_keeps_inflight(self, model, rng):
        router = Router({"c": ServingEngine(model, max_batch=1),
                         "d": ServingEngine(model, max_batch=1)})
        prompts = [rng.randint(0, 128, (n,)).astype(np.int32)
                   for n in (5, 9, 13)]
        # one session -> all three requests pile on one engine
        rids = [router.submit(p, max_new_tokens=6, session_id="s")
                for p in prompts]
        router.step()                      # first request is now in-flight
        target = router._reqs[rids[0]].engine
        assert all(router._reqs[r].engine == target for r in rids)
        router.drain(target)
        # queued requests moved off; the in-flight one finishes in place
        assert router._reqs[rids[0]].engine == target
        assert all(router._reqs[r].engine != target for r in rids[1:])
        assert router.health()[target]["state"] == "draining"
        res = router.run_until_complete()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(res[rid].tokens,
                                          _ref(model, p, 6))
        assert router.stats()["router"]["failover"]["drain"] == 2
        # placement skips the draining engine for NEW work
        r_new = router.submit(prompts[0], max_new_tokens=2,
                              session_id="s")
        assert router._reqs[r_new].engine != target
        router.run_until_complete()

    def test_engine_death_mid_stream_finishes_on_survivor(self, model,
                                                          rng):
        router = _two_engine_router(model)
        prompts = [rng.randint(0, 128, (n,)).astype(np.int32)
                   for n in (5, 8, 11, 6)]
        rids = [router.submit(p, max_new_tokens=8, session_id=i)
                for i, p in enumerate(prompts)]
        for _ in range(2):
            router.step()                  # some tokens already decoded
        with fp.scoped("serving/step=error:1"):
            router.step()                  # first stepped engine dies
        st = router.stats()["router"]
        assert len(st["dead"]) == 1
        assert st["failover"]["engine_error"] >= 1
        res = router.run_until_complete()
        # every request — including the dead engine's in-flight ones —
        # finished on the survivor with exact greedy parity
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(res[rid].tokens,
                                          _ref(model, p, 8))
            assert res[rid].finish_reason == "length"
        survivor = st["alive"]
        assert all(router._reqs[r].engine in survivor for r in rids)

    def test_failover_parks_when_survivor_queue_full(self, model, rng):
        """An engine death while the survivor's bounded queue is full is
        TRANSIENT pressure: the stranded requests park and complete once
        the survivor drains — they are not terminally cancelled and the
        router does not falsely report 'no live engine'."""
        router = Router({"a": ServingEngine(model, max_batch=1,
                                            max_queue=1),
                         "b": ServingEngine(model, max_batch=1,
                                            max_queue=1)})
        prompts = [rng.randint(0, 128, (n,)).astype(np.int32)
                   for n in (4, 6, 8, 5)]
        rids = []
        rids.append(router.submit(prompts[0], max_new_tokens=4))
        rids.append(router.submit(prompts[1], max_new_tokens=4))
        router.step()   # both admitted into slots; queues empty again
        rids.append(router.submit(prompts[2], max_new_tokens=4))
        rids.append(router.submit(prompts[3], max_new_tokens=4))
        with fp.scoped("serving/step=error:1"):
            router.step()   # one engine dies; the survivor is at bound
        st = router.stats()["router"]
        assert len(st["dead"]) == 1
        assert st["parked"] >= 1   # transient pressure, not cancellation
        res = router.run_until_complete()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(res[rid].tokens,
                                          _ref(model, p, 4))
            assert res[rid].finish_reason == "length"
        assert router.stats()["router"]["parked"] == 0

    def test_cancel_of_parked_request_sticks(self, model, rng):
        """cancel() of a request parked by failover must be terminal —
        the next step() must NOT re-dispatch it to the survivor."""
        router = Router({"a": ServingEngine(model, max_batch=1,
                                            max_queue=1),
                         "b": ServingEngine(model, max_batch=1,
                                            max_queue=1)})
        prompts = [rng.randint(0, 128, (n,)).astype(np.int32)
                   for n in (4, 6, 8, 5)]
        rids = [router.submit(prompts[0], max_new_tokens=4),
                router.submit(prompts[1], max_new_tokens=4)]
        router.step()
        rids.append(router.submit(prompts[2], max_new_tokens=4))
        rids.append(router.submit(prompts[3], max_new_tokens=4))
        with fp.scoped("serving/step=error:1"):
            router.step()
        parked = [r for r in rids if router._reqs[r] in router._parked]
        assert parked
        victim = parked[0]
        assert router.cancel(victim) is True
        res = router.run_until_complete()
        assert res[victim].finish_reason == "cancelled"
        assert router._reqs[victim] not in router._parked

    def test_all_engines_dead_is_loud(self, model, rng):
        router = Router({"only": ServingEngine(model, max_batch=1)})
        router.submit(rng.randint(0, 128, (5,)).astype(np.int32),
                      max_new_tokens=4)
        with fp.scoped("serving/step=error:1"):
            with pytest.raises(NoLiveEngineError):
                router.step()


class TestObservability:
    def test_route_span_threads_router_engine_slot(self, model, rng):
        trace.clear()
        trace.enable()
        try:
            router = _two_engine_router(model)
            rid = router.submit(rng.randint(0, 128, (5,)).astype(np.int32),
                                max_new_tokens=3, session_id="t")
            router.run_until_complete()
        finally:
            trace.disable()
        tid = router._reqs[rid].trace_id
        fam = {s.name for s in trace.spans() if s.trace_id == tid}
        # one trace threads the route decision, the engine request root,
        # its queue wait, admission prefill, and slot-level decode steps
        assert {"route", "request", "queue_wait", "prefill",
                "decode"} <= fam
        route = [s for s in trace.spans()
                 if s.name == "route" and s.trace_id == tid][0]
        assert route.attrs["engine"] == router._reqs[rid].engine

    def test_get_request_and_cancel(self, model, rng):
        router = _two_engine_router(model)
        p = rng.randint(0, 128, (5,)).astype(np.int32)
        rid = router.submit(p, max_new_tokens=4, session_id="x")
        req = router.get_request(rid)
        assert not req.finished
        assert router.cancel(rid) is True
        assert router.get_request(rid).finish_reason == "cancelled"
        with pytest.raises(KeyError):
            router.get_request(999)
